"""Span tracer: nestable named wall-clock spans with bounded buffering.

The temporal half of the telemetry subsystem. Design constraints, in order:

1. **Zero overhead when disabled.** ``span()`` on a disabled tracer returns a
   shared no-op context after one attribute check — no allocation, no lock.
   Engine hot paths call it unconditionally.
2. **Honest on an async-dispatch runtime.** JAX dispatch is asynchronous, so a
   host-side span around a compiled-step call measures *dispatch*, not device
   time, unless the device queue is drained. ``sync_spans=True`` drains at
   both span boundaries (the ``utils/timer.py`` ``_sync`` contract) — true
   device-time spans at the cost of serializing the pipeline. The default
   (False) keeps spans free and labels what they are.
3. **Bounded memory.** At most ``max_events`` events are buffered; overflow
   increments ``dropped_events`` instead of growing without bound.

Spans on the same thread nest by timestamp containment, which is exactly how
the Chrome trace-event viewer (Perfetto) reconstructs flame graphs — no
explicit parent pointers needed. Every completed span also feeds the
``span/<name>`` histogram in the shared ``MetricsRegistry`` so phase
breakdowns come from the same source of truth as the trace.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from deepspeed_tpu.telemetry.registry import MetricsRegistry


def _drain_device() -> None:
    """Drain async dispatch so host wall-clock brackets device work
    (same contract as ``utils/timer.py:_sync``)."""
    try:
        import jax

        (jax.device_put(0.0) + 0).block_until_ready()
    except Exception:  # pragma: no cover - backendless environments
        pass


class _NoopSpan:
    """Shared do-nothing context manager returned by disabled tracers."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class _Span:
    """A live span; records itself into the tracer on ``__exit__``."""

    __slots__ = ("_tracer", "name", "cat", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        if self._tracer.sync_spans:
            _drain_device()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        # close is inlined (no helper-call indirection): the serving loop
        # closes three spans per decode chain, so every fixed cost here is
        # paid on the hot path
        tracer = self._tracer
        if tracer.sync_spans:
            _drain_device()
        t1 = time.perf_counter()
        dur_s = t1 - self._t0
        ev = {
            "kind": "span",
            "name": self.name,
            "cat": self.cat,
            "ts": self._t0 - tracer._origin,
            "dur": dur_s,
            "tid": threading.get_ident(),
        }
        if self.args:
            ev["args"] = self.args
        with tracer._lock:
            if len(tracer._events) >= tracer.max_events:
                tracer.dropped_events += 1
            else:
                tracer._events.append(ev)
        h = tracer._span_hists.get(self.name)
        if h is None:  # get-or-create once, then plain dict hits
            h = tracer._span_hists[self.name] = tracer.registry.histogram(
                "span/" + self.name)
        h.observe(dur_s)
        return False


class Tracer:
    """Nestable span recorder + shared metrics registry.

    One global instance (``get_tracer()``) serves the whole process so the
    engine, comm facade, dataloader, and checkpoint paths need no plumbing —
    the same pattern as ``comm.comms_logger``.
    """

    def __init__(self, enabled: bool = False, sync_spans: bool = False,
                 max_events: int = 100_000, memory_watermarks: bool = True):
        self.enabled = enabled
        self.sync_spans = sync_spans
        self.max_events = max_events
        self.memory_watermarks = memory_watermarks
        self.trace_path: Optional[str] = None
        self.jsonl_path: Optional[str] = None
        self.prometheus_path: Optional[str] = None
        self.dropped_events = 0
        self.registry = MetricsRegistry()
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._origin = time.perf_counter()
        # wall-clock stamp taken at the same instant as _origin: the anchor
        # that lets tools/trace_merge.py place this process's origin-relative
        # event stream on a fleet-wide timeline (telemetry/fleet.py)
        self._origin_unix = time.time()
        self._last_counts: Dict[str, float] = {}
        # virtual-track names (e.g. per-request serving tracks): tid -> label,
        # exported as Chrome thread_name metadata so Perfetto shows the label
        self._track_names: Dict[int, str] = {}
        # span-name -> Histogram handle cache: skips the f-string + registry
        # RLock on every span close (the serving hot path closes 3 per chain)
        self._span_hists: Dict[str, Any] = {}

    # ------------------------------------------------------------ config
    def configure(self, enabled: bool = True, sync_spans: Optional[bool] = None,
                  max_events: Optional[int] = None,
                  memory_watermarks: Optional[bool] = None,
                  trace_path: Optional[str] = None,
                  jsonl_path: Optional[str] = None,
                  prometheus_path: Optional[str] = None) -> "Tracer":
        self.enabled = enabled
        if sync_spans is not None:
            self.sync_spans = sync_spans
        if max_events is not None:
            self.max_events = max_events
        if memory_watermarks is not None:
            self.memory_watermarks = memory_watermarks
        if trace_path is not None:
            self.trace_path = trace_path
        if jsonl_path is not None:
            self.jsonl_path = jsonl_path
        if prometheus_path is not None:
            self.prometheus_path = prometheus_path
        return self

    def reset(self) -> None:
        """Drop buffered events and registry contents (config is kept)."""
        with self._lock:
            self._events = []
            self.dropped_events = 0
            self._origin = time.perf_counter()
            self._origin_unix = time.time()
            self._last_counts = {}
            self._track_names = {}
            self._span_hists = {}
        self.registry.reset()

    # ------------------------------------------------------------- spans
    def span(self, name: str, cat: str = "span", **args: Any):
        """Context manager recording one named span; no-op when disabled."""
        if not self.enabled:
            return NOOP_SPAN
        return _Span(self, name, cat, args or None)

    def instant(self, name: str, cat: str = "event", **args: Any) -> None:
        """Record a zero-duration marker event."""
        if not self.enabled:
            return
        ev = {
            "kind": "instant",
            "name": name,
            "cat": cat,
            "ts": time.perf_counter() - self._origin,
            "tid": threading.get_ident(),
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def count(self, name: str, value: float = 1.0) -> None:
        """Increment a registry counter (no trace event; cheap)."""
        if not self.enabled:
            return
        self.registry.counter(name).add(value)

    def sample_counter(self, name: str, value: float) -> None:
        """Set a gauge AND emit a Chrome 'C' counter event (a plotted track
        in Perfetto) — used for memory watermarks."""
        if not self.enabled:
            return
        self.registry.gauge(name).set(value)
        self._append({
            "kind": "counter",
            "name": name,
            "ts": time.perf_counter() - self._origin,
            "value": value,
        })

    # ------------------------------------------ virtual tracks + flow events
    # (serving per-request observability: each request gets its own Perfetto
    # track, and flow arrows link its admission to the prefill/chain dispatch
    # spans on the engine thread — see inference/lifecycle.py)
    def name_track(self, tid: int, name: str) -> None:
        """Label a virtual track (exported as Chrome thread_name metadata)."""
        if not self.enabled:
            return
        with self._lock:
            self._track_names[tid] = name

    def track_names(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._track_names)

    def emit_span(self, name: str, t0: float, t1: float, tid: Optional[int] = None,
                  cat: str = "span", **args: Any) -> None:
        """Record a span from explicit ``time.perf_counter()`` stamps —
        deferred emission for lifecycles whose phases are stamped on the hot
        path but materialized (one cheap append per phase) only at request
        finish. ``tid`` selects a virtual track; default: calling thread."""
        if not self.enabled:
            return
        ev: Dict[str, Any] = {
            "kind": "span",
            "name": name,
            "cat": cat,
            "ts": t0 - self._origin,
            "dur": max(t1 - t0, 0.0),
            "tid": threading.get_ident() if tid is None else tid,
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def flow(self, name: str, flow_id: int, phase: str,
             ts: Optional[float] = None, tid: Optional[int] = None,
             cat: str = "flow") -> None:
        """Record one flow event (``phase``: 'start' | 'step' | 'end').

        Chrome flow events with a shared (cat, name, id) draw arrows between
        the slices enclosing them — this is what links a request's admission
        on its own track to every dispatch span that served it."""
        if not self.enabled:
            return
        ev: Dict[str, Any] = {
            "kind": "flow",
            "name": name,
            "cat": cat,
            "ph": {"start": "s", "step": "t", "end": "f"}[phase],
            "id": flow_id,
            "ts": (time.perf_counter() if ts is None else ts) - self._origin,
            "tid": threading.get_ident() if tid is None else tid,
        }
        self._append(ev)

    def origin(self) -> float:
        """The ``perf_counter`` stamp event ``ts`` values are relative to —
        for callers building deferred event batches (``append_events``)."""
        return self._origin

    def origin_unix(self) -> float:
        """Wall-clock time of the origin — the per-process anchor the trace
        merger and the fleet collector's clock handshake align on. Every
        event's absolute wall time is ``origin_unix() + ev["ts"]``."""
        return self._origin_unix

    def append_events(self, evs: List[Dict[str, Any]]) -> None:
        """Append a pre-built event batch under ONE lock acquisition.

        Events must already carry origin-relative ``ts`` (see ``origin()``)
        and the raw tracer schema (``kind`` span/instant/flow/counter). This
        is the deferred-emission path: a request lifecycle materializes its
        whole track (spans + flow arrows) in one call at finish instead of
        paying a lock per event on the serving hot path."""
        if not self.enabled or not evs:
            return
        with self._lock:
            space = self.max_events - len(self._events)
            if space <= 0:
                self.dropped_events += len(evs)
                return
            if len(evs) > space:
                self.dropped_events += len(evs) - space
                evs = evs[:space]
            self._events.extend(evs)

    def _append(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped_events += 1
                return
            self._events.append(ev)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    # --------------------------------------------------------- summaries
    def phase_summary(self) -> Dict[str, Dict[str, float]]:
        """``{span_name: {count, total_ms, mean_ms, min_ms, max_ms}}`` from
        the registry — the single source of truth ``bench.py`` reports."""
        out: Dict[str, Dict[str, float]] = {}
        for name, val in self.registry.snapshot().items():
            if not name.startswith("span/") or not isinstance(val, dict):
                continue
            out[name[len("span/"):]] = {
                "count": val["count"],
                "total_ms": round(val["total"] * 1e3, 3),
                "mean_ms": round(val["mean"] * 1e3, 3),
                "min_ms": round(val["min"] * 1e3, 3),
                "max_ms": round(val["max"] * 1e3, 3),
            }
        return out

    def sample_memory(self) -> Dict[str, float]:
        """Device-memory watermark sample: PJRT ``memory_stats()`` where the
        backend reports it (TPU HBM), else the ``jax.live_arrays`` census
        (CPU test meshes). Feeds gauges + Perfetto counter tracks."""
        if not (self.enabled and self.memory_watermarks):
            return {}
        out: Dict[str, float] = {}
        try:
            import jax

            stats = {}
            try:
                stats = jax.local_devices()[0].memory_stats() or {}
            except Exception:
                stats = {}
            if "bytes_in_use" in stats:
                out["device_bytes_in_use"] = float(stats["bytes_in_use"])
                if "peak_bytes_in_use" in stats:
                    out["device_peak_bytes_in_use"] = float(stats["peak_bytes_in_use"])
            else:
                out["live_array_bytes"] = float(
                    sum(getattr(a, "nbytes", 0) for a in jax.live_arrays()))
        except Exception:  # pragma: no cover - backendless environments
            return {}
        for k, v in out.items():
            self.sample_counter(f"mem/{k}", v)
        return out

    def step_scalars(self, prefix: str = "Telemetry/") -> Dict[str, float]:
        """Per-step scalars for the ``MonitorMaster``: counter deltas since
        the previous call, gauge samples (flops/MFU, anomaly flags...),
        memory watermarks, and the last completed step-phase wall times. All
        host-side floats — never blocks the dispatch pipeline.

        Caveat on ``comm/*`` counters: the facade records collectives at
        TRACE time (one bump per compiled program, not per execution), so
        their deltas spike on compile steps and read 0 in steady state —
        they chart recompile/compile activity, not per-step wire volume."""
        if not self.enabled:
            return {}
        out: Dict[str, float] = {}
        for name, value in self.registry.counters().items():
            delta = value - self._last_counts.get(name, 0.0)
            self._last_counts[name] = value
            out[prefix + name] = float(delta)
        for name, value in self.registry.gauges().items():
            # gauges are last-write samples (flops/MFU, anomaly/ flags...);
            # mem/ gauges are refreshed + emitted by sample_memory below
            if not name.startswith("mem/"):
                out[prefix + name] = float(value)
        for k, v in self.sample_memory().items():
            out[f"{prefix}mem/{k}"] = v
        for phase in ("train_batch", "data", "step", "fwd_bwd", "fwd", "bwd"):
            h = self.registry.peek_histogram(f"span/{phase}")
            if h is not None and h.count:
                out[f"{prefix}span/{phase}_ms"] = round(h.last * 1e3, 3)
        return out

    # ----------------------------------------------------------- export
    def maybe_export(self) -> None:
        """Write configured exports (no-op when no path is configured)."""
        from deepspeed_tpu.telemetry import exporters

        if self.trace_path:
            exporters.export_chrome_trace(self.trace_path, tracer=self)
        if self.jsonl_path:
            exporters.export_jsonl(self.jsonl_path, tracer=self)
        if self.prometheus_path:
            from deepspeed_tpu.telemetry import exposition

            exposition.export_prometheus(self.prometheus_path, registry=self.registry)
        # the structured event stream (ISSUE 20) flushes next to the trace
        # stream when IT has a path configured — same flush cadence, one
        # artifact directory for the incident-report join
        from deepspeed_tpu.telemetry import events as events_mod

        events_mod.get_event_stream().maybe_export()


def env_enabled() -> bool:
    """True when DSTPU_TELEMETRY opts telemetry in from the environment —
    the ONE place the accepted truthy spellings live (bench.py consults
    this too; don't re-implement the parse)."""
    return os.environ.get("DSTPU_TELEMETRY", "").lower() in ("1", "true", "yes")


_tracer = Tracer(enabled=env_enabled())


def get_tracer() -> Tracer:
    return _tracer


def configure(**kwargs) -> Tracer:
    """Configure the process-global tracer (see ``Tracer.configure``)."""
    return _tracer.configure(**kwargs)


def span(name: str, cat: str = "span", **args: Any):
    return _tracer.span(name, cat=cat, **args)


def enabled() -> bool:
    return _tracer.enabled
