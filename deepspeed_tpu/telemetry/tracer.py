"""Span tracer: nestable named wall-clock spans with bounded buffering.

The temporal half of the telemetry subsystem. Design constraints, in order:

1. **Zero overhead when disabled.** ``span()`` on a disabled tracer returns a
   shared no-op context after one attribute check — no allocation, no lock.
   Engine hot paths call it unconditionally.
2. **Honest on an async-dispatch runtime.** JAX dispatch is asynchronous, so a
   host-side span around a compiled-step call measures *dispatch*, not device
   time, unless the device queue is drained. ``sync_spans=True`` drains at
   both span boundaries (the ``utils/timer.py`` ``_sync`` contract) — true
   device-time spans at the cost of serializing the pipeline. The default
   (False) keeps spans free and labels what they are.
3. **Bounded memory.** At most ``max_events`` events are buffered; overflow
   increments ``dropped_events`` instead of growing without bound.

Spans on the same thread nest by timestamp containment, which is exactly how
the Chrome trace-event viewer (Perfetto) reconstructs flame graphs — no
explicit parent pointers needed. Every completed span also feeds the
``span/<name>`` histogram in the shared ``MetricsRegistry`` so phase
breakdowns come from the same source of truth as the trace.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from deepspeed_tpu.telemetry.registry import MetricsRegistry


def _drain_device() -> None:
    """Drain async dispatch so host wall-clock brackets device work
    (same contract as ``utils/timer.py:_sync``)."""
    try:
        import jax

        (jax.device_put(0.0) + 0).block_until_ready()
    except Exception:  # pragma: no cover - backendless environments
        pass


class _NoopSpan:
    """Shared do-nothing context manager returned by disabled tracers."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class _Span:
    """A live span; records itself into the tracer on ``__exit__``."""

    __slots__ = ("_tracer", "name", "cat", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        if self._tracer.sync_spans:
            _drain_device()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._tracer.sync_spans:
            _drain_device()
        self._tracer._finish_span(self)
        return False


class Tracer:
    """Nestable span recorder + shared metrics registry.

    One global instance (``get_tracer()``) serves the whole process so the
    engine, comm facade, dataloader, and checkpoint paths need no plumbing —
    the same pattern as ``comm.comms_logger``.
    """

    def __init__(self, enabled: bool = False, sync_spans: bool = False,
                 max_events: int = 100_000, memory_watermarks: bool = True):
        self.enabled = enabled
        self.sync_spans = sync_spans
        self.max_events = max_events
        self.memory_watermarks = memory_watermarks
        self.trace_path: Optional[str] = None
        self.jsonl_path: Optional[str] = None
        self.dropped_events = 0
        self.registry = MetricsRegistry()
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._origin = time.perf_counter()
        self._last_counts: Dict[str, float] = {}

    # ------------------------------------------------------------ config
    def configure(self, enabled: bool = True, sync_spans: Optional[bool] = None,
                  max_events: Optional[int] = None,
                  memory_watermarks: Optional[bool] = None,
                  trace_path: Optional[str] = None,
                  jsonl_path: Optional[str] = None) -> "Tracer":
        self.enabled = enabled
        if sync_spans is not None:
            self.sync_spans = sync_spans
        if max_events is not None:
            self.max_events = max_events
        if memory_watermarks is not None:
            self.memory_watermarks = memory_watermarks
        if trace_path is not None:
            self.trace_path = trace_path
        if jsonl_path is not None:
            self.jsonl_path = jsonl_path
        return self

    def reset(self) -> None:
        """Drop buffered events and registry contents (config is kept)."""
        with self._lock:
            self._events = []
            self.dropped_events = 0
            self._origin = time.perf_counter()
            self._last_counts = {}
        self.registry.reset()

    # ------------------------------------------------------------- spans
    def span(self, name: str, cat: str = "span", **args: Any):
        """Context manager recording one named span; no-op when disabled."""
        if not self.enabled:
            return NOOP_SPAN
        return _Span(self, name, cat, args or None)

    def _finish_span(self, s: _Span) -> None:
        t1 = time.perf_counter()
        dur_s = t1 - s._t0
        ev = {
            "kind": "span",
            "name": s.name,
            "cat": s.cat,
            "ts": s._t0 - self._origin,
            "dur": dur_s,
            "tid": threading.get_ident(),
        }
        if s.args:
            ev["args"] = s.args
        self._append(ev)
        self.registry.histogram(f"span/{s.name}").observe(dur_s)

    def instant(self, name: str, cat: str = "event", **args: Any) -> None:
        """Record a zero-duration marker event."""
        if not self.enabled:
            return
        ev = {
            "kind": "instant",
            "name": name,
            "cat": cat,
            "ts": time.perf_counter() - self._origin,
            "tid": threading.get_ident(),
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def count(self, name: str, value: float = 1.0) -> None:
        """Increment a registry counter (no trace event; cheap)."""
        if not self.enabled:
            return
        self.registry.counter(name).add(value)

    def sample_counter(self, name: str, value: float) -> None:
        """Set a gauge AND emit a Chrome 'C' counter event (a plotted track
        in Perfetto) — used for memory watermarks."""
        if not self.enabled:
            return
        self.registry.gauge(name).set(value)
        self._append({
            "kind": "counter",
            "name": name,
            "ts": time.perf_counter() - self._origin,
            "value": value,
        })

    def _append(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped_events += 1
                return
            self._events.append(ev)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    # --------------------------------------------------------- summaries
    def phase_summary(self) -> Dict[str, Dict[str, float]]:
        """``{span_name: {count, total_ms, mean_ms, min_ms, max_ms}}`` from
        the registry — the single source of truth ``bench.py`` reports."""
        out: Dict[str, Dict[str, float]] = {}
        for name, val in self.registry.snapshot().items():
            if not name.startswith("span/") or not isinstance(val, dict):
                continue
            out[name[len("span/"):]] = {
                "count": val["count"],
                "total_ms": round(val["total"] * 1e3, 3),
                "mean_ms": round(val["mean"] * 1e3, 3),
                "min_ms": round(val["min"] * 1e3, 3),
                "max_ms": round(val["max"] * 1e3, 3),
            }
        return out

    def sample_memory(self) -> Dict[str, float]:
        """Device-memory watermark sample: PJRT ``memory_stats()`` where the
        backend reports it (TPU HBM), else the ``jax.live_arrays`` census
        (CPU test meshes). Feeds gauges + Perfetto counter tracks."""
        if not (self.enabled and self.memory_watermarks):
            return {}
        out: Dict[str, float] = {}
        try:
            import jax

            stats = {}
            try:
                stats = jax.local_devices()[0].memory_stats() or {}
            except Exception:
                stats = {}
            if "bytes_in_use" in stats:
                out["device_bytes_in_use"] = float(stats["bytes_in_use"])
                if "peak_bytes_in_use" in stats:
                    out["device_peak_bytes_in_use"] = float(stats["peak_bytes_in_use"])
            else:
                out["live_array_bytes"] = float(
                    sum(getattr(a, "nbytes", 0) for a in jax.live_arrays()))
        except Exception:  # pragma: no cover - backendless environments
            return {}
        for k, v in out.items():
            self.sample_counter(f"mem/{k}", v)
        return out

    def step_scalars(self, prefix: str = "Telemetry/") -> Dict[str, float]:
        """Per-step scalars for the ``MonitorMaster``: counter deltas since
        the previous call, gauge samples (flops/MFU, anomaly flags...),
        memory watermarks, and the last completed step-phase wall times. All
        host-side floats — never blocks the dispatch pipeline.

        Caveat on ``comm/*`` counters: the facade records collectives at
        TRACE time (one bump per compiled program, not per execution), so
        their deltas spike on compile steps and read 0 in steady state —
        they chart recompile/compile activity, not per-step wire volume."""
        if not self.enabled:
            return {}
        out: Dict[str, float] = {}
        for name, value in self.registry.counters().items():
            delta = value - self._last_counts.get(name, 0.0)
            self._last_counts[name] = value
            out[prefix + name] = float(delta)
        for name, value in self.registry.gauges().items():
            # gauges are last-write samples (flops/MFU, anomaly/ flags...);
            # mem/ gauges are refreshed + emitted by sample_memory below
            if not name.startswith("mem/"):
                out[prefix + name] = float(value)
        for k, v in self.sample_memory().items():
            out[f"{prefix}mem/{k}"] = v
        for phase in ("train_batch", "data", "step", "fwd_bwd", "fwd", "bwd"):
            h = self.registry.peek_histogram(f"span/{phase}")
            if h is not None and h.count:
                out[f"{prefix}span/{phase}_ms"] = round(h.last * 1e3, 3)
        return out

    # ----------------------------------------------------------- export
    def maybe_export(self) -> None:
        """Write configured exports (no-op when no path is configured)."""
        from deepspeed_tpu.telemetry import exporters

        if self.trace_path:
            exporters.export_chrome_trace(self.trace_path, tracer=self)
        if self.jsonl_path:
            exporters.export_jsonl(self.jsonl_path, tracer=self)


def env_enabled() -> bool:
    """True when DSTPU_TELEMETRY opts telemetry in from the environment —
    the ONE place the accepted truthy spellings live (bench.py consults
    this too; don't re-implement the parse)."""
    return os.environ.get("DSTPU_TELEMETRY", "").lower() in ("1", "true", "yes")


_tracer = Tracer(enabled=env_enabled())


def get_tracer() -> Tracer:
    return _tracer


def configure(**kwargs) -> Tracer:
    """Configure the process-global tracer (see ``Tracer.configure``)."""
    return _tracer.configure(**kwargs)


def span(name: str, cat: str = "span", **args: Any):
    return _tracer.span(name, cat=cat, **args)


def enabled() -> bool:
    return _tracer.enabled
