"""FleetCollector: cross-process metric federation + cluster health ledger.

One collector process (or thread) receives pushed snapshots from — or
scrapes — every process of a run and merges them into ONE federated view:

  - ``POST /register``   identity + clock handshake ({"time_unix", ...})
                         → {"ok", "clock_offset_s"} — the offset the trace
                         merger can apply to this process's stream
  - ``POST /push``       full snapshot: identity, clock, ``registry`` (the
                         :func:`fleet.registry_dump` wire form), optional
                         ``heartbeat`` and observatory ``coll_rows``
  - ``POST /heartbeat``  identity + heartbeat only (cheap liveness)
  - ``GET  /metrics``    FEDERATED Prometheus exposition (counters summed,
                         histograms merged bucket-wise, gauges
                         last-per-process under ``{proc=}``, plus the
                         ``fleet/*`` rollups)
  - ``GET  /metrics.json`` federated JSON snapshot
  - ``GET  /fleet``      the health ledger: per-process identity, last-seen
                         age, heartbeat (step rate, HBM watermark, queue
                         depth), clock offset, straggler verdict
  - ``GET  /coll_table`` the federated observatory decision table
                         (versioned envelope — a fresh selector warm-starts
                         measured mode from the whole mesh's measurements)
  - ``GET  /healthz``    the collector's own liveness

The incident plane (ISSUE 20) rides the same transport:

  - ``POST /events``     structured-event ingestion ({"identity",
                         "events": [...]} — the ``telemetry/events.py``
                         wire form; also accepted inline on ``/push``).
                         Events APPEND (a bounded fleet-wide ring with a
                         per-process seq guard against re-push duplicates)
                         — unlike registry dumps, which replace.
  - ``GET  /events``     the fleet event ring, filterable by
                         ``?proc=&severity=&subsystem=&since=&limit=``
  - ``GET  /incidents``  cross-process correlation: warn+ events grouped
                         into incidents by (run_id, trailing time window,
                         shared TraceContext flow/request id, or an
                         explicit ``incident_key`` label — the causal-chain
                         join a detector stamps on cause AND effect), with
                         ids stable across repeated reads
  - ``GET  /console``    one self-contained stdlib HTML ops page: health
                         ledger, firing alerts, recent incidents, SLO
                         rollups, perf-ledger sparklines

Merging happens at READ time from the latest dump per process: pushes carry
cumulative process-local snapshots, so the collector must replace a
process's previous contribution, never add to it — re-merging from the
stored dumps on each render is what makes a restarted worker's reset
counters harmless (its new dump simply replaces the old one).

The ledger (``ledger()`` / ``GET /fleet``) is the signal the elastic
supervisor (ROADMAP item 5) and router drain/join (item 1) consume: a
process whose heartbeat age exceeds ``stale_after_s`` is marked ``stale``;
cross-process stragglers are flagged by the PR-2 median+MAD discipline over
per-process step rates.

Scrape mode: :meth:`FleetCollector.scrape` GETs a worker's
``/metrics.fleet`` endpoint (``exposition.MetricsServer``) and ingests it —
same merge path as push, for fleets where workers can't reach out.
"""

from __future__ import annotations

import hashlib
import html
import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from deepspeed_tpu.telemetry import fleet
from deepspeed_tpu.telemetry.events import severity_rank
from deepspeed_tpu.telemetry.registry import MetricsRegistry, decode_key
from deepspeed_tpu.utils.logging import logger


# ------------------------------------------------------ incident correlation
def _event_run_id(ev: Dict[str, Any]) -> str:
    return str((ev.get("identity") or {}).get("run_id", "?"))


def _event_proc(ev: Dict[str, Any]) -> str:
    ident = ev.get("identity") or {}
    return f"{ident.get('run_id', '?')}/p{ident.get('process_index', '?')}"


def _incident_id(run_id: str, first_ev: Dict[str, Any]) -> str:
    """Stable across repeated correlations of the same state: derived from
    the FIRST event's immutable coordinates, never from list position."""
    basis = (f"{run_id}:{_event_proc(first_ev)}:{first_ev.get('seq', 0)}"
             f":{first_ev.get('subsystem')}:{first_ev.get('kind')}"
             f":{first_ev.get('ts')}")
    return "inc-" + hashlib.sha1(basis.encode()).hexdigest()[:10]


def correlate_events(events: List[Dict[str, Any]], window_s: float = 30.0,
                     min_severity: str = "warn") -> List[Dict[str, Any]]:
    """Group events (wire dicts) into incidents.

    Join rules, applied over the time-sorted warn+ stream:
      - same ``run_id`` AND within ``window_s`` of the incident's newest
        event (the drift -> profiler-capture -> regression causal chain is
        a cascade inside one window), OR
      - a shared ``flow_id`` / ``request_id`` (the TraceContext join — a
        request's failure on the router and its death on the replica are
        one incident however far apart), OR
      - a shared ``incident_key`` label (the explicit causal stamp a
        detector puts on cause and effect).
    An event bridging several open incidents MERGES them (the id of the
    earliest survives). Shared by the collector's ``/incidents`` and
    ``tools/incident_report.py`` — one correlation, two readers.
    """
    floor = severity_rank(min_severity)
    sev = [e for e in events if severity_rank(str(e.get("severity", "info")))
           >= floor]
    sev.sort(key=lambda e: (float(e.get("ts", 0.0)), _event_proc(e),
                            int(e.get("seq", 0))))
    incidents: List[Dict[str, Any]] = []

    def join_keys(ev: Dict[str, Any]) -> set:
        keys = set()
        if ev.get("flow_id") is not None:
            keys.add(("flow", ev["flow_id"]))
        if ev.get("request_id") is not None:
            keys.add(("req", _event_run_id(ev), ev["request_id"]))
        ik = (ev.get("labels") or {}).get("incident_key")
        if ik:
            keys.add(("key", ik))
        return keys

    for ev in sev:
        run_id = _event_run_id(ev)
        ts = float(ev.get("ts", 0.0))
        keys = join_keys(ev)
        matched = [
            inc for inc in incidents
            if (inc["run_id"] == run_id
                and ts - inc["end_ts"] <= window_s)
            or (keys & inc["_keys"])]
        if not matched:
            incidents.append({
                "id": _incident_id(run_id, ev), "run_id": run_id,
                "start_ts": ts, "end_ts": ts, "events": [ev],
                "_keys": keys})
            continue
        primary = matched[0]
        for other in matched[1:]:  # bridge: fold later incidents in
            primary["events"].extend(other["events"])
            primary["_keys"] |= other["_keys"]
            primary["start_ts"] = min(primary["start_ts"], other["start_ts"])
            primary["end_ts"] = max(primary["end_ts"], other["end_ts"])
            incidents.remove(other)
        primary["events"].append(ev)
        primary["_keys"] |= keys
        primary["start_ts"] = min(primary["start_ts"], ts)
        primary["end_ts"] = max(primary["end_ts"], ts)
    out = []
    for inc in incidents:
        evs = sorted(inc["events"], key=lambda e: float(e.get("ts", 0.0)))
        worst = max(evs, key=lambda e: severity_rank(
            str(e.get("severity", "info"))))
        out.append({
            "id": inc["id"], "run_id": inc["run_id"],
            "start_ts": inc["start_ts"], "end_ts": inc["end_ts"],
            "duration_s": round(inc["end_ts"] - inc["start_ts"], 3),
            "severity": worst.get("severity", "warn"),
            "event_count": sum(int(e.get("count", 1)) for e in evs),
            "procs": sorted({_event_proc(e) for e in evs}),
            "subsystems": sorted({str(e.get("subsystem", "")) for e in evs}),
            "kinds": sorted({f"{e.get('subsystem')}/{e.get('kind')}"
                             for e in evs}),
            "events": evs,
        })
    out.sort(key=lambda i: i["start_ts"])
    return out


class FleetCollector:
    """Merge-at-read federation over the latest snapshot per process."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 stale_after_s: float = 60.0,
                 straggler_mads: float = 6.0,
                 table_path: Optional[str] = None,
                 events_capacity: int = 4096,
                 incident_window_s: float = 30.0,
                 ledger_root: Optional[str] = None):
        self._host = host
        self._requested_port = port
        self.stale_after_s = float(stale_after_s)
        self.straggler_mads = float(straggler_mads)
        self.table_path = table_path
        self.incident_window_s = float(incident_window_s)
        # perf-ledger root for the console sparklines (None = repo default)
        self.ledger_root = ledger_root
        self._server = None  # exposition.RouteServer, built at start()
        self._lock = threading.Lock()
        # proc key -> {"identity", "dump", "heartbeat", "coll_rows",
        #              "last_seen", "clock_offset_s", "origin_unix",
        #              "events_seq"}
        self._procs: Dict[str, Dict[str, Any]] = {}
        # fleet-wide event ring: APPEND semantics (each push carries only
        # events past the sender's cursor; the per-proc seq guard below
        # makes a re-push of the same tail idempotent)
        self._events: deque = deque(maxlen=int(events_capacity))
        self.events_ingested = 0

    # ------------------------------------------------------------- ingest
    def ingest(self, doc: Dict[str, Any],
               recv_time: Optional[float] = None) -> Dict[str, Any]:
        """Fold one pushed document (register/push/heartbeat all share this
        shape) into the collector state; returns the ack the HTTP layer
        sends back. In-process callers (tests, same-process supervisors)
        use it directly — HTTP is transport, not semantics."""
        now = recv_time if recv_time is not None else time.time()
        ident = fleet.ProcessIdentity.from_dict(
            doc.get("identity") or {"run_id": "?"})
        clock = doc.get("clock") or {}
        offset = None
        if clock.get("time_unix") is not None:
            # one-way handshake: includes transport latency, which is the
            # honest bound for the localhost/LAN fleets this targets
            offset = round(now - float(clock["time_unix"]), 6)
        with self._lock:
            entry = self._procs.setdefault(ident.key(), {})
            entry["identity"] = ident
            entry["last_seen"] = now
            if offset is not None:
                entry["clock_offset_s"] = offset
            if clock.get("origin_unix") is not None:
                entry["origin_unix"] = float(clock["origin_unix"])
            if "registry" in doc:
                entry["dump"] = doc["registry"]
            if "heartbeat" in doc:
                entry["heartbeat"] = dict(doc["heartbeat"])
            if "coll_rows" in doc:
                # REPLACE, like the registry dump: a push carries the
                # process's full cumulative table, so re-folding it
                # additively would inflate sample counts and re-apply the
                # EMA to identical data on every cadence push — the
                # cross-process fold happens once per READ (table_rows)
                entry["coll_rows"] = list(doc["coll_rows"])
            if doc.get("events"):
                # APPEND, unlike everything above: events are occurrences,
                # not cumulative state. The per-proc high-seq guard makes a
                # retried push (ack lost, client re-sends the same tail)
                # idempotent.
                high = int(entry.get("events_seq", 0))
                for ev in doc["events"]:
                    if not isinstance(ev, dict):
                        raise ValueError("events entries must be objects")
                    seq = int(ev.get("seq", 0))
                    if seq and seq <= high:
                        continue
                    high = max(high, seq)
                    ev = dict(ev)
                    ev.setdefault("identity", ident.to_dict())
                    ev["proc"] = ident.key()
                    ev["recv_ts"] = now
                    self._events.append(ev)
                    self.events_ingested += 1
                entry["events_seq"] = high
        if doc.get("coll_rows") and self.table_path:
            self.persist_table()
        return {"ok": True, "proc": ident.key(),
                **({"clock_offset_s": offset} if offset is not None else {})}

    def scrape(self, url: str, timeout_s: float = 5.0) -> Dict[str, Any]:
        """Pull one worker's ``/metrics.fleet`` dump and ingest it (the
        collector-initiated alternative to push). ``url`` is the worker
        MetricsServer base, e.g. ``http://127.0.0.1:9400``."""
        import urllib.request

        with urllib.request.urlopen(url.rstrip("/") + "/metrics.fleet",
                                    timeout=timeout_s) as resp:
            dump = json.loads(resp.read().decode())
        return self.ingest({"identity": dump.get("identity"),
                            "registry": dump,
                            "clock": {"time_unix": dump.get("time_unix")}})

    def persist_table(self) -> None:
        from deepspeed_tpu.collectives import table as table_mod

        try:
            table_mod.write_table(self.table_path, self.table_rows(),
                                  source="fleet")
        except OSError as e:  # pragma: no cover - disk trouble
            logger.warning(f"fleet collector: cannot persist federated "
                           f"table to {self.table_path!r}: {e}")

    # ------------------------------------------------------------- views
    def processes(self) -> List[str]:
        with self._lock:
            return sorted(self._procs)

    def dumps(self) -> Dict[str, Dict[str, Any]]:
        """proc key -> the latest registry dump that process pushed — the
        raw inputs of the federated merge, for verifiers (the nightly
        smoke's bit-exactness gate sums these independently)."""
        with self._lock:
            return {k: e["dump"] for k, e in self._procs.items()
                    if e.get("dump") is not None}

    @staticmethod
    def _proc_labels(entries) -> Dict[str, str]:
        """entry key -> ``{proc=}`` label: the short ``p<index>`` when it is
        unique across the fleet, the run_id-qualified key otherwise — two
        standalone workers that both defaulted to process_index 0 (distinct
        minted run_ids) must not clobber each other's gauges, heartbeats,
        or straggler math."""
        shorts = [e["identity"].proc for _k, e in entries]
        dupes = {p for p in shorts if shorts.count(p) > 1}
        return {k: (e["identity"].key() if e["identity"].proc in dupes
                    else e["identity"].proc)
                for k, e in entries}

    def table_rows(self) -> List[dict]:
        """The federated observatory table: each process's LATEST rows,
        folded at read time through the ONE table fold
        (``collectives/table.py:merge_rows``, EMA mode — the online
        semantics) in sorted-proc order, so repeated reads of the same
        state are identical and a signature measured on several processes
        lands in one row without per-push inflation."""
        from deepspeed_tpu.collectives import table as table_mod

        with self._lock:
            per_proc = [(k, list(e["coll_rows"]))
                        for k, e in sorted(self._procs.items())
                        if e.get("coll_rows")]
        rows: List[dict] = []
        for _key, proc_rows in per_proc:
            rows = table_mod.merge_rows(rows, proc_rows, ema=0.25)
        return rows

    def federated_registry(self) -> MetricsRegistry:
        """Build the merged view from the latest dump per process —
        deterministic merge order (sorted proc keys) so repeated renders of
        the same state are bit-identical."""
        with self._lock:
            entries = [(k, dict(v)) for k, v in sorted(self._procs.items())]
        labels = self._proc_labels(entries)
        reg = MetricsRegistry()
        heartbeats: Dict[str, Dict[str, Any]] = {}
        now = time.time()
        for key, entry in entries:
            proc = labels[key]
            dump = entry.get("dump")
            if dump is not None:
                fleet.merge_dump_into(reg, dump, proc_label=proc)
            hb = entry.get("heartbeat")
            if hb is not None:
                heartbeats[proc] = hb
                for field in ("queue_depth", "hbm_bytes_in_use"):
                    if hb.get(field) is not None:
                        reg.gauge(f"fleet/{field}", proc=proc).set(
                            float(hb[field]))
            reg.gauge("fleet/last_seen_age_s", proc=proc).set(
                round(now - entry["last_seen"], 3))
            if entry.get("clock_offset_s") is not None:
                reg.gauge("fleet/clock_offset_s", proc=proc).set(
                    entry["clock_offset_s"])
        # the ONE definition of fleet/processes: every registered member,
        # heartbeat or not — must always agree with the ledger's row count
        reg.gauge("fleet/processes").set(float(len(entries)))
        # disagg topology rollups (ISSUE 14): membership per declared role
        # (prefill/decode/...) plus role-summed serving rates inside
        # fleet_rollups — the phase pools read as two series
        roles = {labels[k]: e["identity"].role for k, e in entries}
        role_counts: Dict[str, int] = {}
        for r in roles.values():
            role_counts[r] = role_counts.get(r, 0) + 1
        for r, n in role_counts.items():
            reg.gauge("fleet/role_processes", role=r).set(float(n))
        fleet.fleet_rollups(reg, heartbeats,
                            straggler_mads=self.straggler_mads, roles=roles)
        return reg

    def render_prometheus(self) -> str:
        from deepspeed_tpu.telemetry import exposition

        # identity=False: the federated view spans processes — stamping the
        # collector's own process_info on it would misattribute the fleet
        return exposition.render_prometheus(self.federated_registry(),
                                            identity=False)

    def render_json(self) -> str:
        from deepspeed_tpu.telemetry import exposition

        return exposition.render_json_snapshot(self.federated_registry(),
                                               identity=False)

    def ledger(self) -> Dict[str, Any]:
        """The cluster health ledger: one row per process — what the
        elastic supervisor polls to decide drain/join/restart."""
        with self._lock:
            entries = [(k, dict(v)) for k, v in sorted(self._procs.items())]
        labels = self._proc_labels(entries)
        now = time.time()
        rates = {labels[k]: float(e["heartbeat"]["step_rate"])
                 for k, e in entries
                 if e.get("heartbeat", {}).get("step_rate") is not None}
        stragglers = fleet.straggler_flags(rates, mads=self.straggler_mads)
        rows = []
        for key, entry in entries:
            ident: fleet.ProcessIdentity = entry["identity"]
            age = now - entry["last_seen"]
            rows.append({
                "proc": key,
                "identity": ident.to_dict(),
                "last_seen_age_s": round(age, 3),
                "stale": age > self.stale_after_s,
                "clock_offset_s": entry.get("clock_offset_s"),
                "origin_unix": entry.get("origin_unix"),
                "heartbeat": entry.get("heartbeat"),
                "straggler": bool(stragglers.get(labels[key], False)),
            })
        return {"time_unix": now, "processes": rows,
                "coll_table_rows": len(self.table_rows())}

    # ------------------------------------------------------------- events
    def events(self, proc: Optional[str] = None,
               min_severity: Optional[str] = None,
               subsystem: Optional[str] = None,
               since: Optional[float] = None,
               limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """The fleet event ring, filtered. ``proc`` matches either the full
        ``run_id/pN`` key or the short ``pN``; ``since`` is a unix ts over
        the event's own ``ts``."""
        with self._lock:
            out = list(self._events)
        if proc:
            out = [e for e in out
                   if e.get("proc") == proc
                   or str(e.get("proc", "")).endswith("/" + proc)]
        if min_severity:
            floor = severity_rank(min_severity)
            out = [e for e in out
                   if severity_rank(str(e.get("severity", "info"))) >= floor]
        if subsystem:
            out = [e for e in out if e.get("subsystem") == subsystem]
        if since is not None:
            out = [e for e in out if float(e.get("ts", 0.0)) >= float(since)]
        if limit is not None and limit >= 0:
            out = out[-int(limit):]
        return out

    def incidents(self, window_s: Optional[float] = None,
                  min_severity: str = "warn") -> List[Dict[str, Any]]:
        """Cross-process incident correlation over the event ring (see
        :func:`correlate_events`) — recomputed per read from the same
        state, so ids are stable across repeated GETs."""
        with self._lock:
            evs = list(self._events)
        return correlate_events(
            evs, window_s=self.incident_window_s if window_s is None
            else float(window_s), min_severity=min_severity)

    def _events_doc(self, query: Dict[str, str]) -> bytes:
        since = query.get("since")
        limit = query.get("limit")
        evs = self.events(
            proc=query.get("proc") or None,
            min_severity=query.get("severity") or None,
            subsystem=query.get("subsystem") or None,
            since=float(since) if since else None,
            limit=int(limit) if limit else None)
        return json.dumps({"time_unix": time.time(), "count": len(evs),
                           "events": evs}).encode()

    def _incidents_doc(self, query: Dict[str, str]) -> bytes:
        window = query.get("window_s")
        incs = self.incidents(
            window_s=float(window) if window else None,
            min_severity=query.get("severity") or "warn")
        return json.dumps({"time_unix": time.time(), "count": len(incs),
                           "incidents": incs}).encode()

    # ------------------------------------------------------------- console
    def _ledger_sparklines(self, width: int = 160, height: int = 28,
                           max_series: int = 8) -> List[Dict[str, str]]:
        """Inline-SVG sparklines of the perf ledger's headline series —
        best-effort: no ledger on disk renders as no section, never an
        error page."""
        try:
            from deepspeed_tpu.telemetry.perfgate import is_headline, GateConfig
            from deepspeed_tpu.telemetry.perfledger import PerfLedger, row_key

            ledger = PerfLedger(self.ledger_root)
            cfg = GateConfig()
            series: Dict[tuple, List[tuple]] = {}
            for row in ledger.rows():
                if not is_headline(row, cfg):
                    continue
                series.setdefault(row_key(row), []).append(
                    (int(row["round"]), float(row["value"])))
        except Exception:  # noqa: BLE001 - console stays up without a ledger
            return []
        out = []
        for key in sorted(series)[:max_series]:
            pts = sorted(series[key])
            vals = [v for _r, v in pts]
            if len(vals) < 2:
                continue
            lo, hi = min(vals), max(vals)
            span = (hi - lo) or 1.0
            step = width / max(len(vals) - 1, 1)
            poly = " ".join(
                f"{i * step:.1f},{height - 3 - (v - lo) / span * (height - 6):.1f}"
                for i, v in enumerate(vals))
            svg = (f'<svg width="{width}" height="{height}">'
                   f'<polyline fill="none" stroke="#2b7" stroke-width="1.5" '
                   f'points="{poly}"/></svg>')
            out.append({"label": "/".join(key), "svg": svg,
                        "last": f"{vals[-1]:.6g}", "n": str(len(vals))})
        return out

    def _console_html(self) -> bytes:
        """GET /console: ONE self-contained page (inline CSS, inline SVG,
        zero external assets — it must render from a curl dump during the
        exact outage it exists for)."""
        esc = html.escape
        now = time.time()
        led = self.ledger()
        incidents = self.incidents()
        recent = self.events(limit=30)
        reg = self.federated_registry()
        gauges = reg.gauges()
        firing = []
        for key, val in sorted(gauges.items()):
            base, labels = decode_key(key)
            if base == "alerts/firing" and val > 0:
                firing.append((labels.get("rule", "?"), int(val)))
        slo = {k: v for k, v in sorted(gauges.items())
               if decode_key(k)[0] in (
                   "fleet/goodput", "fleet/tokens_per_s",
                   "fleet/step_rate_min", "fleet/processes",
                   "fleet/role_processes")}
        sev_color = {"info": "#8aa", "warn": "#c80", "critical": "#c22"}

        def ts_fmt(ts):
            try:
                return time.strftime("%H:%M:%S", time.localtime(float(ts)))
            except Exception:  # noqa: BLE001
                return "?"

        parts = [
            "<!doctype html><html><head><meta charset='utf-8'>",
            "<title>deepspeed_tpu fleet console</title><style>",
            "body{font:13px/1.4 monospace;margin:1.2em;background:#fafafa;"
            "color:#123}",
            "h1{font-size:17px}h2{font-size:14px;margin:1.2em 0 .3em;"
            "border-bottom:1px solid #ccc}",
            "table{border-collapse:collapse}td,th{padding:2px 9px;"
            "border:1px solid #ddd;text-align:left}",
            ".ok{color:#2b7}.bad{color:#c22;font-weight:bold}"
            ".warn{color:#c80}</style></head><body>",
            f"<h1>fleet console</h1><p>{len(led['processes'])} processes · "
            f"{len(firing)} firing alert(s) · {len(incidents)} incident(s) · "
            f"rendered {ts_fmt(now)}</p>",
        ]
        # firing alerts
        parts.append("<h2>firing alerts</h2>")
        if firing:
            parts.append("<table><tr><th>rule</th><th>instances</th></tr>")
            for rule, n in firing:
                parts.append(f"<tr><td class='bad'>{esc(rule)}</td>"
                             f"<td>{n}</td></tr>")
            parts.append("</table>")
        else:
            parts.append("<p class='ok'>none firing</p>")
        # incidents
        parts.append("<h2>recent incidents</h2>")
        if incidents:
            parts.append("<table><tr><th>id</th><th>severity</th><th>start"
                         "</th><th>dur</th><th>procs</th><th>kinds</th>"
                         "<th>events</th></tr>")
            for inc in incidents[-10:][::-1]:
                cls = "bad" if inc["severity"] == "critical" else "warn"
                parts.append(
                    f"<tr><td>{esc(inc['id'])}</td>"
                    f"<td class='{cls}'>{esc(inc['severity'])}</td>"
                    f"<td>{ts_fmt(inc['start_ts'])}</td>"
                    f"<td>{inc['duration_s']:.1f}s</td>"
                    f"<td>{esc(', '.join(inc['procs']))}</td>"
                    f"<td>{esc(', '.join(inc['kinds']))}</td>"
                    f"<td>{inc['event_count']}</td></tr>")
            parts.append("</table>")
        else:
            parts.append("<p class='ok'>no incidents</p>")
        # health ledger
        parts.append("<h2>health ledger</h2><table><tr><th>proc</th>"
                     "<th>role</th><th>age</th><th>step</th><th>rate</th>"
                     "<th>straggler</th><th>stale</th></tr>")
        for row in led["processes"]:
            hb = row.get("heartbeat") or {}
            stale = ("<td class='bad'>STALE</td>" if row["stale"]
                     else "<td class='ok'>ok</td>")
            strag = ("<td class='warn'>straggler</td>" if row["straggler"]
                     else "<td class='ok'>ok</td>")
            parts.append(
                f"<tr><td>{esc(str(row['proc']))}</td>"
                f"<td>{esc(str(row['identity'].get('role', '?')))}</td>"
                f"<td>{row['last_seen_age_s']:.1f}s</td>"
                f"<td>{esc(str(hb.get('step', '—')))}</td>"
                f"<td>{esc(str(hb.get('step_rate', '—')))}</td>"
                f"{strag}{stale}</tr>")
        parts.append("</table>")
        # SLO rollups
        parts.append("<h2>SLO rollups</h2>")
        if slo:
            parts.append("<table><tr><th>metric</th><th>value</th></tr>")
            for k, v in slo.items():
                parts.append(f"<tr><td>{esc(k)}</td><td>{v:.6g}</td></tr>")
            parts.append("</table>")
        else:
            parts.append("<p>no rollups yet</p>")
        # perf sparklines
        sparks = self._ledger_sparklines()
        if sparks:
            parts.append("<h2>perf ledger (headline trajectories)</h2>"
                         "<table><tr><th>series</th><th>trend</th>"
                         "<th>last</th><th>rounds</th></tr>")
            for s in sparks:
                parts.append(f"<tr><td>{esc(s['label'])}</td><td>{s['svg']}"
                             f"</td><td>{esc(s['last'])}</td>"
                             f"<td>{esc(s['n'])}</td></tr>")
            parts.append("</table>")
        # recent events
        parts.append("<h2>recent events</h2>")
        if recent:
            parts.append("<table><tr><th>ts</th><th>proc</th><th>sev</th>"
                         "<th>event</th><th>message</th><th>n</th></tr>")
            for ev in recent[::-1]:
                color = sev_color.get(str(ev.get("severity")), "#123")
                parts.append(
                    f"<tr><td>{ts_fmt(ev.get('ts'))}</td>"
                    f"<td>{esc(str(ev.get('proc', '?')))}</td>"
                    f"<td style='color:{color}'>"
                    f"{esc(str(ev.get('severity')))}</td>"
                    f"<td>{esc(str(ev.get('subsystem')))}/"
                    f"{esc(str(ev.get('kind')))}</td>"
                    f"<td>{esc(str(ev.get('message', ''))[:140])}</td>"
                    f"<td>{int(ev.get('count', 1))}</td></tr>")
            parts.append("</table>")
        else:
            parts.append("<p class='ok'>no events</p>")
        parts.append("</body></html>")
        return "".join(parts).encode()

    # -------------------------------------------------------------- serve
    def _coll_table_doc(self) -> bytes:
        from deepspeed_tpu.collectives.table import SCHEMA_VERSION

        return json.dumps({"schema": SCHEMA_VERSION, "source": "fleet",
                           "rows": self.table_rows()}).encode()

    def _healthz_doc(self) -> bytes:
        return json.dumps({
            "ok": True, "role": "collector",
            "identity": fleet.get_identity().to_dict(),
            "processes": len(self.processes()),
            "time_unix": time.time()}).encode()

    def start(self) -> "FleetCollector":
        if self._server is None:
            from deepspeed_tpu.telemetry.exposition import RouteServer

            js = "application/json"
            self._server = RouteServer(
                get_routes={
                    "/metrics": lambda: (
                        self.render_prometheus().encode(),
                        "text/plain; version=0.0.4; charset=utf-8"),
                    "/metrics.json": lambda: (
                        self.render_json().encode(), js),
                    "/fleet": lambda: (
                        json.dumps(self.ledger()).encode(), js),
                    "/coll_table": lambda: (self._coll_table_doc(), js),
                    "/healthz": lambda: (self._healthz_doc(), js),
                    # incident plane (ISSUE 20): query-taking handlers get
                    # the parsed query dict from RouteServer
                    "/events": lambda query: (self._events_doc(query), js),
                    "/incidents": lambda query: (
                        self._incidents_doc(query), js),
                    "/console": lambda: (
                        self._console_html(),
                        "text/html; charset=utf-8"),
                },
                # register/push/heartbeat/events all share the ingest shape
                # — the paths differ only in what the sender chose to
                # include
                post_routes={p: self.ingest
                             for p in ("/register", "/push", "/heartbeat",
                                       "/events")},
                port=self._requested_port, host=self._host,
                name="dstpu-fleet-collector")
        self._server.start()
        return self

    @property
    def port(self) -> Optional[int]:
        return self._server.port if self._server is not None else None

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop()

    @property
    def url(self) -> Optional[str]:
        if self.port is None:
            return None
        return f"http://{self._host}:{self.port}"


class FleetClient:
    """One process's push side: registers (clock handshake), then pushes
    registry dumps + heartbeats + observatory rows — on demand
    (:meth:`push`) or on a background cadence (:meth:`start`).

    Push failures NEVER raise into the caller (a dead collector must not
    take the training step down with it): they count in ``push_failures``
    and warn once."""

    def __init__(self, url: str, identity: Optional[fleet.ProcessIdentity] = None,
                 registry=None, observatory=None, timeout_s: float = 2.0):
        self.url = url.rstrip("/")
        self._identity = identity
        self._registry = registry
        self._observatory = observatory
        self.timeout_s = float(timeout_s)
        self.pushes = 0
        self.push_failures = 0
        self.clock_offset_s: Optional[float] = None
        self._warned = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # async-push hand-off: hot-path callers snapshot (sub-ms) and the
        # worker thread pays the HTTP round-trip. ONE pending slot, latest
        # wins — snapshots are cumulative, so a newer one strictly
        # supersedes an unsent older one (no queue to bound)
        self._pending: Optional[Dict[str, Any]] = None
        self._pending_lock = threading.Lock()
        self._pending_event = threading.Event()
        self._worker: Optional[threading.Thread] = None
        # event-stream push cursor: advanced only on an ACKED push, so a
        # failed push's events ride the next one (the collector's per-proc
        # seq guard dedups the overlap if the ack was merely lost)
        self._events_sent_seq = 0

    def _identity_dict(self) -> Dict[str, Any]:
        ident = self._identity or fleet.get_identity()
        return ident.to_dict()

    def _post(self, path: str, doc: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        import urllib.request

        body = json.dumps(doc).encode()
        req = urllib.request.Request(
            self.url + path, data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return json.loads(resp.read().decode())
        except Exception as e:  # noqa: BLE001 - collector may be down
            self.push_failures += 1
            if not self._warned:
                self._warned = True
                logger.warning(
                    f"fleet: push to {self.url}{path} failed ({e}); further "
                    "failures count silently in push_failures")
            return None

    def register(self) -> Optional[Dict[str, Any]]:
        ack = self._post("/register", {
            "identity": self._identity_dict(),
            "clock": fleet.clock_sync_doc()})
        if ack is not None and ack.get("clock_offset_s") is not None:
            self.clock_offset_s = float(ack["clock_offset_s"])
        return ack

    def heartbeat_doc(self) -> Dict[str, Any]:
        """The per-process health sample: last step + age, step wall time
        (rate), HBM watermark, serving queue depth, anomaly flags — read
        from the process registry so it costs a few dict lookups, never a
        device fetch."""
        from deepspeed_tpu.telemetry.tracer import get_tracer

        registry = self._registry or get_tracer().registry
        info = fleet.last_step_info()
        hb: Dict[str, Any] = {"step": info["step"],
                              "last_step_age_s": info["age_s"]}
        h = registry.peek_histogram("span/train_batch")
        if h is not None and h.count:
            hb["step_time_ms"] = round(h.last * 1e3, 3)
            if h.last > 0:
                hb["step_rate"] = round(1.0 / h.last, 4)
        gauges = registry.gauges()
        for name, field in (
                ("mem/device_bytes_in_use", "hbm_bytes_in_use"),
                ("mem/device_peak_bytes_in_use", "hbm_peak_bytes"),
                ("mem/live_array_bytes", "hbm_bytes_in_use"),
                ("serving/queue_depth", "queue_depth"),
                ("anomaly/step_straggler", "straggler"),
                ("anomaly/step_regression", "regression"),
                # cross-process divergence comparator (telemetry/numerics.py):
                # the whole-tree xor digest is bit-stable across mesh shapes,
                # so unequal values across processes mean diverged replicas
                ("numerics/digest_checksum", "numerics_checksum")):
            if name in gauges and field not in hb:
                hb[field] = gauges[name]
        return hb

    def _build_doc(self, heartbeat_extra: Optional[Dict[str, Any]],
                   include_registry: bool, include_table: bool,
                   coll_rows: Optional[List[dict]] = None) -> Dict[str, Any]:
        hb = self.heartbeat_doc()
        if heartbeat_extra:
            hb.update(heartbeat_extra)
        doc: Dict[str, Any] = {
            "identity": self._identity_dict(),
            "clock": fleet.clock_sync_doc(),
            "heartbeat": hb,
        }
        if include_registry:
            doc["registry"] = fleet.registry_dump(
                registry=self._registry,
                identity=self._identity or fleet.get_identity())
        # structured events (ISSUE 20): ship the tail past the acked cursor
        from deepspeed_tpu.telemetry.events import get_event_stream

        stream = get_event_stream()
        tail = stream.drain_since(self._events_sent_seq)
        if tail:
            doc["events"] = tail
            doc["events_high_seq"] = tail[-1]["seq"]
        if coll_rows is not None:
            doc["coll_rows"] = list(coll_rows)
        elif include_table:
            obs = self._observatory
            if obs is None:
                from deepspeed_tpu.collectives import observatory as obs_mod

                obs = obs_mod.get_observatory()
                # CollectiveObservatory.enabled is a PROPERTY — calling it
                # raised TypeError on the push worker thread whenever a
                # live observatory existed, silently killing fleet pushes
                if not obs.enabled:
                    obs = None
            if obs is not None:
                rows = obs.table_rows()
                if rows:
                    doc["coll_rows"] = rows
        return doc

    def push(self, heartbeat_extra: Optional[Dict[str, Any]] = None,
             include_registry: bool = True,
             include_table: bool = True,
             coll_rows: Optional[List[dict]] = None
             ) -> Optional[Dict[str, Any]]:
        """One synchronous snapshot push (background-thread and shutdown
        callers). ``heartbeat_extra`` merges caller facts into the
        heartbeat (the resilience supervisor stamps rewind counts);
        ``coll_rows`` ships an explicit observatory-row list instead of
        pulling from the process observatory (tools/tests)."""
        return self._send(self._build_doc(heartbeat_extra, include_registry,
                                          include_table, coll_rows))

    def push_async(self, heartbeat_extra: Optional[Dict[str, Any]] = None,
                   include_registry: bool = True,
                   include_table: bool = True) -> None:
        """Hot-path push: snapshot NOW (sub-millisecond — dump + heartbeat
        are dict walks), pay the HTTP round-trip on the client's worker
        thread. One pending slot, latest-wins: snapshots are cumulative, so
        an unsent older one is strictly superseded — a slow collector
        back-pressures into dropped intermediate snapshots, never into the
        caller's step."""
        doc = self._build_doc(heartbeat_extra, include_registry,
                              include_table)
        self._ensure_worker()
        with self._pending_lock:
            self._pending = doc
        self._pending_event.set()

    def _send(self, doc: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        ack = self._post("/push", doc)
        if ack is not None:
            self.pushes += 1
            if ack.get("clock_offset_s") is not None:
                self.clock_offset_s = float(ack["clock_offset_s"])
            high = doc.get("events_high_seq")
            if high is not None and high > self._events_sent_seq:
                self._events_sent_seq = int(high)
        return ack

    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return

        def drain():
            while True:
                self._pending_event.wait()
                with self._pending_lock:
                    doc, self._pending = self._pending, None
                    self._pending_event.clear()
                    self._inflight = doc is not None
                if doc is not None:
                    try:
                        self._send(doc)
                    finally:
                        self._inflight = False

        self._inflight = False
        self._worker = threading.Thread(
            target=drain, name="dstpu-fleet-push-async", daemon=True)
        self._worker.start()

    def flush(self, timeout_s: float = 5.0) -> None:
        """Wait until the async-pending slot drains (tests, shutdown)."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._pending_lock:
                idle = (self._pending is None
                        and not self._pending_event.is_set()
                        and not getattr(self, "_inflight", False))
            if idle:
                return
            time.sleep(0.005)

    # ------------------------------------------------------ background push
    def start(self, interval_s: float = 5.0) -> "FleetClient":
        """Register, then push on a daemon-thread cadence — the zero-touch
        wiring the ``telemetry.fleet_url`` config key turns on."""
        if self._thread is not None:
            return self
        self.register()
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                self.push()

        self._thread = threading.Thread(
            target=loop, name="dstpu-fleet-push", daemon=True)
        self._thread.start()
        return self

    def stop(self, final_push: bool = True) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=self.timeout_s + 1.0)
            self._thread = None
        if final_push:
            self.push()
