"""FleetCollector: cross-process metric federation + cluster health ledger.

One collector process (or thread) receives pushed snapshots from — or
scrapes — every process of a run and merges them into ONE federated view:

  - ``POST /register``   identity + clock handshake ({"time_unix", ...})
                         → {"ok", "clock_offset_s"} — the offset the trace
                         merger can apply to this process's stream
  - ``POST /push``       full snapshot: identity, clock, ``registry`` (the
                         :func:`fleet.registry_dump` wire form), optional
                         ``heartbeat`` and observatory ``coll_rows``
  - ``POST /heartbeat``  identity + heartbeat only (cheap liveness)
  - ``GET  /metrics``    FEDERATED Prometheus exposition (counters summed,
                         histograms merged bucket-wise, gauges
                         last-per-process under ``{proc=}``, plus the
                         ``fleet/*`` rollups)
  - ``GET  /metrics.json`` federated JSON snapshot
  - ``GET  /fleet``      the health ledger: per-process identity, last-seen
                         age, heartbeat (step rate, HBM watermark, queue
                         depth), clock offset, straggler verdict
  - ``GET  /coll_table`` the federated observatory decision table
                         (versioned envelope — a fresh selector warm-starts
                         measured mode from the whole mesh's measurements)
  - ``GET  /healthz``    the collector's own liveness

Merging happens at READ time from the latest dump per process: pushes carry
cumulative process-local snapshots, so the collector must replace a
process's previous contribution, never add to it — re-merging from the
stored dumps on each render is what makes a restarted worker's reset
counters harmless (its new dump simply replaces the old one).

The ledger (``ledger()`` / ``GET /fleet``) is the signal the elastic
supervisor (ROADMAP item 5) and router drain/join (item 1) consume: a
process whose heartbeat age exceeds ``stale_after_s`` is marked ``stale``;
cross-process stragglers are flagged by the PR-2 median+MAD discipline over
per-process step rates.

Scrape mode: :meth:`FleetCollector.scrape` GETs a worker's
``/metrics.fleet`` endpoint (``exposition.MetricsServer``) and ingests it —
same merge path as push, for fleets where workers can't reach out.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

from deepspeed_tpu.telemetry import fleet
from deepspeed_tpu.telemetry.registry import MetricsRegistry
from deepspeed_tpu.utils.logging import logger


class FleetCollector:
    """Merge-at-read federation over the latest snapshot per process."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 stale_after_s: float = 60.0,
                 straggler_mads: float = 6.0,
                 table_path: Optional[str] = None):
        self._host = host
        self._requested_port = port
        self.stale_after_s = float(stale_after_s)
        self.straggler_mads = float(straggler_mads)
        self.table_path = table_path
        self._server = None  # exposition.RouteServer, built at start()
        self._lock = threading.Lock()
        # proc key -> {"identity", "dump", "heartbeat", "coll_rows",
        #              "last_seen", "clock_offset_s", "origin_unix"}
        self._procs: Dict[str, Dict[str, Any]] = {}

    # ------------------------------------------------------------- ingest
    def ingest(self, doc: Dict[str, Any],
               recv_time: Optional[float] = None) -> Dict[str, Any]:
        """Fold one pushed document (register/push/heartbeat all share this
        shape) into the collector state; returns the ack the HTTP layer
        sends back. In-process callers (tests, same-process supervisors)
        use it directly — HTTP is transport, not semantics."""
        now = recv_time if recv_time is not None else time.time()
        ident = fleet.ProcessIdentity.from_dict(
            doc.get("identity") or {"run_id": "?"})
        clock = doc.get("clock") or {}
        offset = None
        if clock.get("time_unix") is not None:
            # one-way handshake: includes transport latency, which is the
            # honest bound for the localhost/LAN fleets this targets
            offset = round(now - float(clock["time_unix"]), 6)
        with self._lock:
            entry = self._procs.setdefault(ident.key(), {})
            entry["identity"] = ident
            entry["last_seen"] = now
            if offset is not None:
                entry["clock_offset_s"] = offset
            if clock.get("origin_unix") is not None:
                entry["origin_unix"] = float(clock["origin_unix"])
            if "registry" in doc:
                entry["dump"] = doc["registry"]
            if "heartbeat" in doc:
                entry["heartbeat"] = dict(doc["heartbeat"])
            if "coll_rows" in doc:
                # REPLACE, like the registry dump: a push carries the
                # process's full cumulative table, so re-folding it
                # additively would inflate sample counts and re-apply the
                # EMA to identical data on every cadence push — the
                # cross-process fold happens once per READ (table_rows)
                entry["coll_rows"] = list(doc["coll_rows"])
        if doc.get("coll_rows") and self.table_path:
            self.persist_table()
        return {"ok": True, "proc": ident.key(),
                **({"clock_offset_s": offset} if offset is not None else {})}

    def scrape(self, url: str, timeout_s: float = 5.0) -> Dict[str, Any]:
        """Pull one worker's ``/metrics.fleet`` dump and ingest it (the
        collector-initiated alternative to push). ``url`` is the worker
        MetricsServer base, e.g. ``http://127.0.0.1:9400``."""
        import urllib.request

        with urllib.request.urlopen(url.rstrip("/") + "/metrics.fleet",
                                    timeout=timeout_s) as resp:
            dump = json.loads(resp.read().decode())
        return self.ingest({"identity": dump.get("identity"),
                            "registry": dump,
                            "clock": {"time_unix": dump.get("time_unix")}})

    def persist_table(self) -> None:
        from deepspeed_tpu.collectives import table as table_mod

        try:
            table_mod.write_table(self.table_path, self.table_rows(),
                                  source="fleet")
        except OSError as e:  # pragma: no cover - disk trouble
            logger.warning(f"fleet collector: cannot persist federated "
                           f"table to {self.table_path!r}: {e}")

    # ------------------------------------------------------------- views
    def processes(self) -> List[str]:
        with self._lock:
            return sorted(self._procs)

    def dumps(self) -> Dict[str, Dict[str, Any]]:
        """proc key -> the latest registry dump that process pushed — the
        raw inputs of the federated merge, for verifiers (the nightly
        smoke's bit-exactness gate sums these independently)."""
        with self._lock:
            return {k: e["dump"] for k, e in self._procs.items()
                    if e.get("dump") is not None}

    @staticmethod
    def _proc_labels(entries) -> Dict[str, str]:
        """entry key -> ``{proc=}`` label: the short ``p<index>`` when it is
        unique across the fleet, the run_id-qualified key otherwise — two
        standalone workers that both defaulted to process_index 0 (distinct
        minted run_ids) must not clobber each other's gauges, heartbeats,
        or straggler math."""
        shorts = [e["identity"].proc for _k, e in entries]
        dupes = {p for p in shorts if shorts.count(p) > 1}
        return {k: (e["identity"].key() if e["identity"].proc in dupes
                    else e["identity"].proc)
                for k, e in entries}

    def table_rows(self) -> List[dict]:
        """The federated observatory table: each process's LATEST rows,
        folded at read time through the ONE table fold
        (``collectives/table.py:merge_rows``, EMA mode — the online
        semantics) in sorted-proc order, so repeated reads of the same
        state are identical and a signature measured on several processes
        lands in one row without per-push inflation."""
        from deepspeed_tpu.collectives import table as table_mod

        with self._lock:
            per_proc = [(k, list(e["coll_rows"]))
                        for k, e in sorted(self._procs.items())
                        if e.get("coll_rows")]
        rows: List[dict] = []
        for _key, proc_rows in per_proc:
            rows = table_mod.merge_rows(rows, proc_rows, ema=0.25)
        return rows

    def federated_registry(self) -> MetricsRegistry:
        """Build the merged view from the latest dump per process —
        deterministic merge order (sorted proc keys) so repeated renders of
        the same state are bit-identical."""
        with self._lock:
            entries = [(k, dict(v)) for k, v in sorted(self._procs.items())]
        labels = self._proc_labels(entries)
        reg = MetricsRegistry()
        heartbeats: Dict[str, Dict[str, Any]] = {}
        now = time.time()
        for key, entry in entries:
            proc = labels[key]
            dump = entry.get("dump")
            if dump is not None:
                fleet.merge_dump_into(reg, dump, proc_label=proc)
            hb = entry.get("heartbeat")
            if hb is not None:
                heartbeats[proc] = hb
                for field in ("queue_depth", "hbm_bytes_in_use"):
                    if hb.get(field) is not None:
                        reg.gauge(f"fleet/{field}", proc=proc).set(
                            float(hb[field]))
            reg.gauge("fleet/last_seen_age_s", proc=proc).set(
                round(now - entry["last_seen"], 3))
            if entry.get("clock_offset_s") is not None:
                reg.gauge("fleet/clock_offset_s", proc=proc).set(
                    entry["clock_offset_s"])
        # the ONE definition of fleet/processes: every registered member,
        # heartbeat or not — must always agree with the ledger's row count
        reg.gauge("fleet/processes").set(float(len(entries)))
        # disagg topology rollups (ISSUE 14): membership per declared role
        # (prefill/decode/...) plus role-summed serving rates inside
        # fleet_rollups — the phase pools read as two series
        roles = {labels[k]: e["identity"].role for k, e in entries}
        role_counts: Dict[str, int] = {}
        for r in roles.values():
            role_counts[r] = role_counts.get(r, 0) + 1
        for r, n in role_counts.items():
            reg.gauge("fleet/role_processes", role=r).set(float(n))
        fleet.fleet_rollups(reg, heartbeats,
                            straggler_mads=self.straggler_mads, roles=roles)
        return reg

    def render_prometheus(self) -> str:
        from deepspeed_tpu.telemetry import exposition

        # identity=False: the federated view spans processes — stamping the
        # collector's own process_info on it would misattribute the fleet
        return exposition.render_prometheus(self.federated_registry(),
                                            identity=False)

    def render_json(self) -> str:
        from deepspeed_tpu.telemetry import exposition

        return exposition.render_json_snapshot(self.federated_registry(),
                                               identity=False)

    def ledger(self) -> Dict[str, Any]:
        """The cluster health ledger: one row per process — what the
        elastic supervisor polls to decide drain/join/restart."""
        with self._lock:
            entries = [(k, dict(v)) for k, v in sorted(self._procs.items())]
        labels = self._proc_labels(entries)
        now = time.time()
        rates = {labels[k]: float(e["heartbeat"]["step_rate"])
                 for k, e in entries
                 if e.get("heartbeat", {}).get("step_rate") is not None}
        stragglers = fleet.straggler_flags(rates, mads=self.straggler_mads)
        rows = []
        for key, entry in entries:
            ident: fleet.ProcessIdentity = entry["identity"]
            age = now - entry["last_seen"]
            rows.append({
                "proc": key,
                "identity": ident.to_dict(),
                "last_seen_age_s": round(age, 3),
                "stale": age > self.stale_after_s,
                "clock_offset_s": entry.get("clock_offset_s"),
                "origin_unix": entry.get("origin_unix"),
                "heartbeat": entry.get("heartbeat"),
                "straggler": bool(stragglers.get(labels[key], False)),
            })
        return {"time_unix": now, "processes": rows,
                "coll_table_rows": len(self.table_rows())}

    # -------------------------------------------------------------- serve
    def _coll_table_doc(self) -> bytes:
        from deepspeed_tpu.collectives.table import SCHEMA_VERSION

        return json.dumps({"schema": SCHEMA_VERSION, "source": "fleet",
                           "rows": self.table_rows()}).encode()

    def _healthz_doc(self) -> bytes:
        return json.dumps({
            "ok": True, "role": "collector",
            "identity": fleet.get_identity().to_dict(),
            "processes": len(self.processes()),
            "time_unix": time.time()}).encode()

    def start(self) -> "FleetCollector":
        if self._server is None:
            from deepspeed_tpu.telemetry.exposition import RouteServer

            js = "application/json"
            self._server = RouteServer(
                get_routes={
                    "/metrics": lambda: (
                        self.render_prometheus().encode(),
                        "text/plain; version=0.0.4; charset=utf-8"),
                    "/metrics.json": lambda: (
                        self.render_json().encode(), js),
                    "/fleet": lambda: (
                        json.dumps(self.ledger()).encode(), js),
                    "/coll_table": lambda: (self._coll_table_doc(), js),
                    "/healthz": lambda: (self._healthz_doc(), js),
                },
                # register/push/heartbeat all share the ingest shape — the
                # paths differ only in what the sender chose to include
                post_routes={p: self.ingest
                             for p in ("/register", "/push", "/heartbeat")},
                port=self._requested_port, host=self._host,
                name="dstpu-fleet-collector")
        self._server.start()
        return self

    @property
    def port(self) -> Optional[int]:
        return self._server.port if self._server is not None else None

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop()

    @property
    def url(self) -> Optional[str]:
        if self.port is None:
            return None
        return f"http://{self._host}:{self.port}"


class FleetClient:
    """One process's push side: registers (clock handshake), then pushes
    registry dumps + heartbeats + observatory rows — on demand
    (:meth:`push`) or on a background cadence (:meth:`start`).

    Push failures NEVER raise into the caller (a dead collector must not
    take the training step down with it): they count in ``push_failures``
    and warn once."""

    def __init__(self, url: str, identity: Optional[fleet.ProcessIdentity] = None,
                 registry=None, observatory=None, timeout_s: float = 2.0):
        self.url = url.rstrip("/")
        self._identity = identity
        self._registry = registry
        self._observatory = observatory
        self.timeout_s = float(timeout_s)
        self.pushes = 0
        self.push_failures = 0
        self.clock_offset_s: Optional[float] = None
        self._warned = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # async-push hand-off: hot-path callers snapshot (sub-ms) and the
        # worker thread pays the HTTP round-trip. ONE pending slot, latest
        # wins — snapshots are cumulative, so a newer one strictly
        # supersedes an unsent older one (no queue to bound)
        self._pending: Optional[Dict[str, Any]] = None
        self._pending_lock = threading.Lock()
        self._pending_event = threading.Event()
        self._worker: Optional[threading.Thread] = None

    def _identity_dict(self) -> Dict[str, Any]:
        ident = self._identity or fleet.get_identity()
        return ident.to_dict()

    def _post(self, path: str, doc: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        import urllib.request

        body = json.dumps(doc).encode()
        req = urllib.request.Request(
            self.url + path, data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return json.loads(resp.read().decode())
        except Exception as e:  # noqa: BLE001 - collector may be down
            self.push_failures += 1
            if not self._warned:
                self._warned = True
                logger.warning(
                    f"fleet: push to {self.url}{path} failed ({e}); further "
                    "failures count silently in push_failures")
            return None

    def register(self) -> Optional[Dict[str, Any]]:
        ack = self._post("/register", {
            "identity": self._identity_dict(),
            "clock": fleet.clock_sync_doc()})
        if ack is not None and ack.get("clock_offset_s") is not None:
            self.clock_offset_s = float(ack["clock_offset_s"])
        return ack

    def heartbeat_doc(self) -> Dict[str, Any]:
        """The per-process health sample: last step + age, step wall time
        (rate), HBM watermark, serving queue depth, anomaly flags — read
        from the process registry so it costs a few dict lookups, never a
        device fetch."""
        from deepspeed_tpu.telemetry.tracer import get_tracer

        registry = self._registry or get_tracer().registry
        info = fleet.last_step_info()
        hb: Dict[str, Any] = {"step": info["step"],
                              "last_step_age_s": info["age_s"]}
        h = registry.peek_histogram("span/train_batch")
        if h is not None and h.count:
            hb["step_time_ms"] = round(h.last * 1e3, 3)
            if h.last > 0:
                hb["step_rate"] = round(1.0 / h.last, 4)
        gauges = registry.gauges()
        for name, field in (
                ("mem/device_bytes_in_use", "hbm_bytes_in_use"),
                ("mem/device_peak_bytes_in_use", "hbm_peak_bytes"),
                ("mem/live_array_bytes", "hbm_bytes_in_use"),
                ("serving/queue_depth", "queue_depth"),
                ("anomaly/step_straggler", "straggler"),
                ("anomaly/step_regression", "regression"),
                # cross-process divergence comparator (telemetry/numerics.py):
                # the whole-tree xor digest is bit-stable across mesh shapes,
                # so unequal values across processes mean diverged replicas
                ("numerics/digest_checksum", "numerics_checksum")):
            if name in gauges and field not in hb:
                hb[field] = gauges[name]
        return hb

    def _build_doc(self, heartbeat_extra: Optional[Dict[str, Any]],
                   include_registry: bool, include_table: bool,
                   coll_rows: Optional[List[dict]] = None) -> Dict[str, Any]:
        hb = self.heartbeat_doc()
        if heartbeat_extra:
            hb.update(heartbeat_extra)
        doc: Dict[str, Any] = {
            "identity": self._identity_dict(),
            "clock": fleet.clock_sync_doc(),
            "heartbeat": hb,
        }
        if include_registry:
            doc["registry"] = fleet.registry_dump(
                registry=self._registry,
                identity=self._identity or fleet.get_identity())
        if coll_rows is not None:
            doc["coll_rows"] = list(coll_rows)
        elif include_table:
            obs = self._observatory
            if obs is None:
                from deepspeed_tpu.collectives import observatory as obs_mod

                obs = obs_mod.get_observatory()
                # CollectiveObservatory.enabled is a PROPERTY — calling it
                # raised TypeError on the push worker thread whenever a
                # live observatory existed, silently killing fleet pushes
                if not obs.enabled:
                    obs = None
            if obs is not None:
                rows = obs.table_rows()
                if rows:
                    doc["coll_rows"] = rows
        return doc

    def push(self, heartbeat_extra: Optional[Dict[str, Any]] = None,
             include_registry: bool = True,
             include_table: bool = True,
             coll_rows: Optional[List[dict]] = None
             ) -> Optional[Dict[str, Any]]:
        """One synchronous snapshot push (background-thread and shutdown
        callers). ``heartbeat_extra`` merges caller facts into the
        heartbeat (the resilience supervisor stamps rewind counts);
        ``coll_rows`` ships an explicit observatory-row list instead of
        pulling from the process observatory (tools/tests)."""
        return self._send(self._build_doc(heartbeat_extra, include_registry,
                                          include_table, coll_rows))

    def push_async(self, heartbeat_extra: Optional[Dict[str, Any]] = None,
                   include_registry: bool = True,
                   include_table: bool = True) -> None:
        """Hot-path push: snapshot NOW (sub-millisecond — dump + heartbeat
        are dict walks), pay the HTTP round-trip on the client's worker
        thread. One pending slot, latest-wins: snapshots are cumulative, so
        an unsent older one is strictly superseded — a slow collector
        back-pressures into dropped intermediate snapshots, never into the
        caller's step."""
        doc = self._build_doc(heartbeat_extra, include_registry,
                              include_table)
        self._ensure_worker()
        with self._pending_lock:
            self._pending = doc
        self._pending_event.set()

    def _send(self, doc: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        ack = self._post("/push", doc)
        if ack is not None:
            self.pushes += 1
            if ack.get("clock_offset_s") is not None:
                self.clock_offset_s = float(ack["clock_offset_s"])
        return ack

    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return

        def drain():
            while True:
                self._pending_event.wait()
                with self._pending_lock:
                    doc, self._pending = self._pending, None
                    self._pending_event.clear()
                    self._inflight = doc is not None
                if doc is not None:
                    try:
                        self._send(doc)
                    finally:
                        self._inflight = False

        self._inflight = False
        self._worker = threading.Thread(
            target=drain, name="dstpu-fleet-push-async", daemon=True)
        self._worker.start()

    def flush(self, timeout_s: float = 5.0) -> None:
        """Wait until the async-pending slot drains (tests, shutdown)."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._pending_lock:
                idle = (self._pending is None
                        and not self._pending_event.is_set()
                        and not getattr(self, "_inflight", False))
            if idle:
                return
            time.sleep(0.005)

    # ------------------------------------------------------ background push
    def start(self, interval_s: float = 5.0) -> "FleetClient":
        """Register, then push on a daemon-thread cadence — the zero-touch
        wiring the ``telemetry.fleet_url`` config key turns on."""
        if self._thread is not None:
            return self
        self.register()
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                self.push()

        self._thread = threading.Thread(
            target=loop, name="dstpu-fleet-push", daemon=True)
        self._thread.start()
        return self

    def stop(self, final_push: bool = True) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=self.timeout_s + 1.0)
            self._thread = None
        if final_push:
            self.push()
