"""Standard-format metric exposition: Prometheus text format + JSON snapshot.

The registry half of the telemetry subsystem gains a scrapeable surface:

  - ``render_prometheus()``   -> Prometheus text exposition format 0.0.4
  - ``export_prometheus(p)``  -> write it to a file (node-exporter textfile
    collector style, or for tests/artifacts)
  - ``render_json_snapshot()`` / ``export_json_snapshot(p)`` -> the registry's
    flat snapshot (labelled keys, histogram summaries incl. p50/p95/p99)
  - ``MetricsServer``         -> opt-in stdlib ``http.server`` thread serving
    ``GET /metrics`` (text) and ``GET /metrics.json`` (snapshot) — no new
    dependencies, daemon thread, ``port=0`` picks a free port

Name mapping: registry names use ``subsystem/name`` (enforced by
``tests/unit/test_metric_names.py``); Prometheus identifiers cannot contain
``/``, so ``serving/ttft_ms`` exports as ``dstpu_serving_ttft_ms`` (every
non-identifier character becomes ``_``, one ``dstpu_`` namespace prefix).
Counters get the conventional ``_total`` suffix. Labelled registry children
(``name{k="8"}``) export as one family with proper label sets.

Histograms export the standard cumulative ``_bucket{le=...}`` series straight
from the registry's sparse log buckets (upper bound of populated buckets
only, plus ``+Inf``), ``_sum`` and ``_count`` — PromQL's
``histogram_quantile`` reproduces the same bounded-error percentiles the
in-process ``Histogram.quantile`` answers. For operators reading the raw
exposition, precomputed ``<name>_p50/_p95/_p99`` gauges ride along.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional

PROM_PREFIX = "dstpu_"
_IDENT_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _resolve_registry(registry) -> Any:
    if registry is None:
        from deepspeed_tpu.telemetry.tracer import get_tracer

        registry = get_tracer().registry
    return registry


def prom_name(name: str) -> str:
    """Registry ``subsystem/name`` -> Prometheus identifier."""
    return PROM_PREFIX + _IDENT_RE.sub("_", name)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels_str(labels: Dict[str, str], extra: Optional[Dict[str, str]] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in sorted(merged.items()))
    return "{" + inner + "}"


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return repr(float(v))


def render_prometheus(registry=None, identity=None) -> str:
    """Render the whole registry in Prometheus text exposition format.

    Every exposition carries a ``dstpu_process_info`` info-gauge stamped
    with the process identity (run_id/proc/host/role — the Prometheus
    "info metric" idiom), so a scrape is joinable across a fleet without
    out-of-band bookkeeping. ``identity=False`` suppresses it (the fleet
    collector's FEDERATED view is multi-process by construction — one
    process_info row would be a lie)."""
    from deepspeed_tpu.telemetry.registry import bucket_upper_bound

    registry = _resolve_registry(registry)
    families: Dict[str, Dict[str, Any]] = {}  # pname -> {kind, help, rows}
    for kind, base, metric in registry.iter_metrics():
        pname = prom_name(base) + ("_total" if kind == "counter" else "")
        fam = families.setdefault(
            pname, {"kind": kind, "help": base, "rows": [], "extra": []})
        if kind in ("counter", "gauge"):
            fam["rows"].append((metric.labels, metric.value))
            continue
        # histogram: cumulative buckets from the sparse log buckets
        s = metric.summary()
        cum = 0
        bucket_rows: List[str] = []
        for idx, c in metric.buckets():
            cum += c
            le = bucket_upper_bound(idx)
            bucket_rows.append(
                f"{pname}_bucket{_labels_str(metric.labels, {'le': _fmt(le)})} {cum}")
        bucket_rows.append(
            f"{pname}_bucket{_labels_str(metric.labels, {'le': '+Inf'})} {s['count']}")
        bucket_rows.append(f"{pname}_sum{_labels_str(metric.labels)} {_fmt(s['total'])}")
        bucket_rows.append(f"{pname}_count{_labels_str(metric.labels)} {s['count']}")
        fam["rows"].append((metric.labels, bucket_rows))
        # precomputed quantile gauges for humans reading the raw exposition
        if s["count"]:
            for q in ("p50", "p95", "p99"):
                fam["extra"].append(
                    f"{pname}_{q}{_labels_str(metric.labels)} {_fmt(s[q])}")

    lines: List[str] = []
    if identity is not False:
        if identity is None:
            from deepspeed_tpu.telemetry.fleet import get_identity

            identity = get_identity()
        pname = PROM_PREFIX + "process_info"
        lines.append(f"# HELP {pname} process identity (fleet join key)")
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname}{_labels_str(identity.labels())} 1")
    for pname in sorted(families):
        fam = families[pname]
        lines.append(f"# HELP {pname} registry metric {fam['help']}")
        lines.append(f"# TYPE {pname} {fam['kind']}")
        if fam["kind"] in ("counter", "gauge"):
            for labels, value in fam["rows"]:
                lines.append(f"{pname}{_labels_str(labels)} {_fmt(value)}")
        else:
            for _labels, bucket_rows in fam["rows"]:
                lines.extend(bucket_rows)
        for row in fam["extra"]:
            lines.append(row)
    return "\n".join(lines) + "\n"


def render_json_snapshot(registry=None, indent: Optional[int] = 2,
                         identity=None) -> str:
    """The registry's flat snapshot as JSON (labelled keys preserved,
    histogram summaries carry p50/p95/p99), stamped with the process
    identity (``identity=False`` suppresses — the collector's federated
    snapshot)."""
    registry = _resolve_registry(registry)
    doc = {"time_unix": time.time(), "metrics": registry.snapshot()}
    if identity is not False:
        if identity is None:
            from deepspeed_tpu.telemetry.fleet import get_identity

            identity = get_identity()
        doc["identity"] = identity.to_dict()
    return json.dumps(doc, indent=indent, sort_keys=True)


def _write(path: str, text: str) -> str:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        f.write(text)
    return path


def export_prometheus(path: Optional[str] = None, registry=None) -> str:
    """Write the Prometheus text exposition; returns the path written."""
    from deepspeed_tpu.telemetry.exporters import default_output_dir

    path = path or os.path.join(default_output_dir(), "metrics.prom")
    return _write(path, render_prometheus(registry))


def export_json_snapshot(path: Optional[str] = None, registry=None) -> str:
    """Write the JSON metrics snapshot; returns the path written."""
    from deepspeed_tpu.telemetry.exporters import default_output_dir

    path = path or os.path.join(default_output_dir(), "metrics.json")
    return _write(path, render_json_snapshot(registry) + "\n")


def _takes_query(fn) -> bool:
    """True when a GET handler declares a positional parameter (the parsed
    query dict). Inspected once per handler and cached on the function —
    signature inspection per request would be silly."""
    cached = getattr(fn, "_dstpu_takes_query", None)
    if cached is None:
        import inspect

        try:
            params = [
                p for p in inspect.signature(fn).parameters.values()
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
            cached = len(params) >= 1
        except (TypeError, ValueError):
            cached = False
        try:
            fn._dstpu_takes_query = cached
        except AttributeError:  # bound methods/partials: recomputed per call
            pass
    return cached


class RouteServer:
    """Tiny stdlib HTTP server over a route table — THE one
    daemon-thread/bind/handler implementation behind :class:`MetricsServer`
    and the fleet :class:`~deepspeed_tpu.telemetry.collector.FleetCollector`.

    ``get_routes`` maps a path to ``fn() -> (body_bytes, content_type)``,
    or — when the handler declares a positional parameter — to
    ``fn(query) -> (body_bytes, content_type)`` with the parsed query
    string as a flat ``{key: last_value}`` dict (the ``/events`` filters);
    ``post_routes`` maps a path to ``fn(doc) -> ack_dict`` (body parsed as
    JSON, ack serialized back; ``ValueError``/``KeyError`` from the handler
    answer 400 — GET handlers get the same guard, so a malformed filter
    answers 400 too). ``port=0`` binds a free port (``.port`` holds the
    real one). Handlers run per request, so every response reflects live
    state.
    """

    def __init__(self, get_routes, post_routes=None, port: int = 0,
                 host: str = "127.0.0.1", name: str = "dstpu-http"):
        self._get_routes = dict(get_routes)
        self._post_routes = dict(post_routes or {})
        self._host = host
        self._requested_port = port
        self._name = name
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    def start(self) -> "RouteServer":
        if self._httpd is not None:
            return self
        import http.server

        get_routes, post_routes = self._get_routes, self._post_routes

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - stdlib handler contract
                path, _, qs = self.path.partition("?")
                fn = get_routes.get(path)
                if fn is None:
                    self.send_error(404)
                    return
                try:
                    if _takes_query(fn):
                        import urllib.parse

                        query = {k: v[-1] for k, v in
                                 urllib.parse.parse_qs(qs).items()}
                        body, ctype = fn(query)
                    else:
                        body, ctype = fn()
                except (ValueError, KeyError, TypeError, AttributeError) as e:
                    self._send(400, json.dumps(
                        {"ok": False, "error": str(e)}).encode(),
                        "application/json")
                    return
                self._send(200, body, ctype)

            def do_POST(self):  # noqa: N802 - stdlib handler contract
                fn = post_routes.get(self.path.split("?")[0])
                if fn is None:
                    self.send_error(404)
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    doc = json.loads(self.rfile.read(n).decode())
                    if not isinstance(doc, dict):
                        raise ValueError(
                            f"body must be a JSON object, got "
                            f"{type(doc).__name__}")
                    ack = fn(doc)
                # TypeError/AttributeError: a well-formed JSON object whose
                # FIELDS have the wrong shape (e.g. a scalar heartbeat) must
                # answer 400, not drop the connection with a stderr traceback
                except (ValueError, KeyError, TypeError, AttributeError) as e:
                    self._send(400, json.dumps(
                        {"ok": False, "error": str(e)}).encode(),
                        "application/json")
                    return
                self._send(200, json.dumps(ack).encode(), "application/json")

            def log_message(self, *a):  # silence per-request stderr noise
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            (self._host, self._requested_port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name=self._name, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None
            self.port = None


class MetricsServer:
    """Opt-in ``/metrics`` HTTP endpoint (stdlib only, daemon thread).

    ``GET /metrics`` serves the Prometheus text exposition (content type
    ``text/plain; version=0.0.4``), ``GET /metrics.json`` the JSON snapshot.
    ``port=0`` binds a free port (``server.port`` holds the real one) —
    tests and multi-engine processes never collide. The handler renders at
    request time, so a scraper always sees the live registry.

    Fleet endpoints (``telemetry/fleet.py``):
      - ``GET /healthz`` — liveness without parsing the full exposition:
        process identity, last-step + age (``fleet.note_step``), registry
        size. What the collector and the future elastic supervisor poll.
      - ``GET /metrics.fleet`` — the MERGEABLE registry dump
        (``fleet.registry_dump``: raw histogram buckets, not summaries) a
        ``FleetCollector.scrape`` federates bit-exactly.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1", registry=None):
        registry = _resolve_registry(registry)
        self._registry = registry

        def healthz():
            from deepspeed_tpu.telemetry import fleet

            doc = {
                "ok": True,
                "identity": fleet.get_identity().to_dict(),
                **fleet.last_step_info(),
                "registry_size": registry.size(),
                "time_unix": time.time(),
            }
            return json.dumps(doc).encode(), "application/json"

        def metrics_fleet():
            from deepspeed_tpu.telemetry import fleet

            return (json.dumps(fleet.registry_dump(registry)).encode(),
                    "application/json")

        self._server = RouteServer({
            "/metrics": lambda: (
                render_prometheus(registry).encode(),
                "text/plain; version=0.0.4; charset=utf-8"),
            "/metrics.json": lambda: (
                render_json_snapshot(registry).encode(), "application/json"),
            "/healthz": healthz,
            "/metrics.fleet": metrics_fleet,
        }, port=port, host=host, name="dstpu-metrics")

    @property
    def port(self) -> Optional[int]:
        return self._server.port

    def start(self) -> "MetricsServer":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop()


def serve_metrics(port: int = 0, host: str = "127.0.0.1", registry=None) -> MetricsServer:
    """Start a ``MetricsServer`` and return it (``.port`` has the bound port)."""
    return MetricsServer(port=port, host=host, registry=registry).start()
