"""Compiled-program registry: what did XLA actually build for this process?

Host-side observability (spans, SLO metrics, flight records) watches the
*dispatch* of programs; this module watches the *programs themselves*. Every
jitted callable the engines build — train/eval/grad/apply/offload steps, the
v2 prefill/decode-chain programs, collectives probes — is captured once per
compile at the same wrap point the recompile detector already owns, and per
program the registry records:

  - compile wall time (the call that paid the compile) and capture overhead
  - ``cost_analysis()`` flops / bytes accessed — exact for the program run
  - ``memory_analysis()`` argument/output/temp/alias bytes and the derived
    peak HBM (argument + output − alias + temp: XLA's own live-set bound)
  - a donation/aliasing summary (aliased bytes + input→output alias pairs)
  - the collective ops in the compiled HLO text: op kind, tensor bytes,
    replica groups — the measured per-program comm volume the cost models in
    ``collectives/selector.py`` otherwise have to assume
  - an HLO fingerprint (content hash + instruction count) so a recompile
    report can say *what grew*, not just which argument shape changed

Everything lands in the shared ``MetricsRegistry`` as ``program/*`` gauges
and ``compile/*`` counters labelled ``{program="<label>"}``, rides the
Prometheus exposition and Perfetto counter tracks for free, and feeds the
HBM calibration loop: engines register their pre-flight ``utils/hbm.py``
estimate and every captured program's XLA peak is reconciled against it
(``hbm/estimate_ratio`` — see :func:`deepspeed_tpu.utils.hbm.record_calibration`).

Capture cost, honestly: JAX does not expose the executable its dispatch
cache just built, so capture goes through the AOT path
(``fn.lower(args).compile()``). Tracing/lowering are cache hits from the
dispatch compile; the backend compile is partially cached by XLA's in-memory
caches (measured ~0.4x of a cold compile on CPU). This is paid ONCE per
compile event — exactly when the dispatch path is already paying a full
compile — never per step, and the ``compile/capture_ms`` gauge reports it.
Disabled (the default when telemetry is off), nothing is allocated, wrapped
callables fall straight through, and the dispatched program is byte-identical.
"""

from __future__ import annotations

import hashlib
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

# HLO opcodes that move tensors across participants. ``-start`` variants are
# counted (async collectives are captured at issue); ``-done`` halves are not
# (same transfer, second instruction).
_COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
    "collective-broadcast",
)

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1, "f8e5m2fnuz": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "s4": 1, "u4": 1, "pred": 1, "c64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
_RG_RE = re.compile(r"replica_groups=(\{\{[0-9,{} ]*\}\}|\[[0-9,]+\]<=\[[0-9,]+\](?:T\([0-9,]+\))?)")


def _shape_bytes(segment: str) -> int:
    """Total bytes of every shape literal (``f32[8,128]``) in ``segment``."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(segment):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def extract_collectives(hlo_text: str) -> List[Dict[str, Any]]:
    """Collective ops in compiled HLO text: kind, result tensor bytes,
    replica groups. Pure text analysis — works on any backend's ``as_text()``
    (post-optimization HLO, so fused/rewritten collectives are what is
    actually on the wire)."""
    out: List[Dict[str, Any]] = []
    for raw in hlo_text.splitlines():
        line = raw.strip()
        if not (line.startswith("%") or line.startswith("ROOT ")):
            continue
        eq = line.find(" = ")
        if eq < 0:
            continue
        rest = line[eq + 3:]
        for kind in _COLLECTIVE_KINDS:
            m = re.search(r"\b" + re.escape(kind) + r"(-start)?\(", rest)
            if m is None:
                continue
            if re.search(r"\b" + re.escape(kind) + r"-done\(", rest):
                break  # the -start half already carried the bytes
            rg = _RG_RE.search(line)
            out.append({
                "kind": kind,
                # result shapes sit between '=' and the opcode; for tuple-
                # shaped fused collectives every element contributes
                "bytes": _shape_bytes(rest[: m.start()]),
                "replica_groups": rg.group(1) if rg else "",
            })
            break
    return out


# custom-call targets that are HAND-WRITTEN kernels (vs partitioning /
# placement annotations GSPMD sprinkles through every sharded program)
_KERNEL_TARGETS = ("tpu_custom_call", "mosaic", "triton")


def extract_custom_kernels(hlo_text: str) -> List[Dict[str, Any]]:
    """Custom-call targets in compiled HLO text: ``[{target, count,
    kernel}]`` where ``kernel`` marks hand-written kernels (Pallas/Mosaic/
    Triton) as opposed to GSPMD/placement annotations. This is how a FUSED
    collective hop reads in a program inventory — e.g. one
    ``tpu_custom_call`` per hop where the ppermute path showed separate
    quantize custom calls (or fused HLO) plus a ``collective-permute``;
    see docs/telemetry.md."""
    counts: Dict[str, int] = {}
    for m in re.finditer(r'custom_call_target="([^"]+)"', hlo_text):
        target = m.group(1)
        counts[target] = counts.get(target, 0) + 1
    return [{"target": t, "count": c,
             "kernel": any(k in t.lower() for k in _KERNEL_TARGETS)}
            for t, c in sorted(counts.items())]


def hlo_fingerprint(hlo_text: str) -> Tuple[str, int]:
    """(content hash, instruction count) of an HLO module's text — the
    identity a recompile report diffs to say what grew."""
    digest = hashlib.sha256(hlo_text.encode("utf-8", "replace")).hexdigest()[:12]
    n_instr = sum(1 for ln in hlo_text.splitlines() if " = " in ln)
    return digest, n_instr


@dataclass
class ProgramRecord:
    """One captured compile of one labelled program."""

    label: str
    index: int                       # capture sequence number (process-wide)
    fingerprint: str = ""
    instruction_count: int = 0
    compile_wall_s: Optional[float] = None   # the call that paid the compile
    capture_s: float = 0.0                   # cost of this capture itself
    flops: float = 0.0
    bytes_accessed: float = 0.0
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    alias_bytes: int = 0                     # donated/aliased input bytes
    alias_pairs: int = 0                     # input→output alias entries
    generated_code_bytes: int = 0
    peak_hbm_bytes: int = 0                  # argument + output − alias + temp
    collectives: List[Dict[str, Any]] = field(default_factory=list)
    custom_kernels: List[Dict[str, Any]] = field(default_factory=list)
    hbm_estimate_bytes: Optional[int] = None
    hbm_estimate_ratio: Optional[float] = None
    # wire bytes the collectives observatory traced for the ROUTED facade
    # collectives of this program, and the ratio of the HLO-extracted
    # collective bytes to them (collectives/observatory.py reconciliation)
    routed_wire_bytes: int = 0
    wire_ratio: Optional[float] = None

    @property
    def collective_bytes(self) -> int:
        return sum(c["bytes"] for c in self.collectives)

    @property
    def custom_kernel_count(self) -> int:
        # hand-written kernels only — GSPMD/annotation custom calls are in
        # the list (kernel=False) but must not inflate the kernel census
        return sum(k["count"] for k in self.custom_kernels if k.get("kernel"))

    def as_dict(self) -> Dict[str, Any]:
        return {
            "label": self.label,
            "index": self.index,
            "fingerprint": self.fingerprint,
            "instruction_count": self.instruction_count,
            "compile_wall_ms": (round(self.compile_wall_s * 1e3, 3)
                                if self.compile_wall_s is not None else None),
            "capture_ms": round(self.capture_s * 1e3, 3),
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "alias_bytes": self.alias_bytes,
            "alias_pairs": self.alias_pairs,
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "collective_count": len(self.collectives),
            "collective_bytes": self.collective_bytes,
            "collectives": list(self.collectives),
            "custom_kernel_count": self.custom_kernel_count,
            "custom_kernels": list(self.custom_kernels),
            "hbm_estimate_bytes": self.hbm_estimate_bytes,
            "hbm_estimate_ratio": self.hbm_estimate_ratio,
            "routed_wire_bytes": self.routed_wire_bytes,
            "wire_ratio": self.wire_ratio,
        }


class _Watch:
    """Minimal cache-growth watcher for jitted callables outside the
    recompile detector's reach (telemetry-without-diagnostics engines, the
    v2 step programs). Same probe discipline as the detector's wrapper: two
    ``_cache_size()`` reads per call, capture only when a compile actually
    happened, attribute access forwards to the wrapped function."""

    __slots__ = ("_fn", "_label", "_registry", "_hbm_scope", "_program_record")

    def __init__(self, fn: Callable, label: str, registry: "ProgramRegistry",
                 hbm_scope: Optional[str]):
        self._fn = fn
        self._label = label
        self._registry = registry
        self._hbm_scope = hbm_scope
        # freshest ProgramRecord captured for THIS watcher's program (the
        # flops profiler reads it instead of AOT-compiling a second copy)
        self._program_record = None

    def __call__(self, *args, **kwargs):
        reg = self._registry
        if not reg.enabled:
            return self._fn(*args, **kwargs)
        before = self._cache_size()
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        if before is not None:
            after = self._cache_size()
            if after is not None and after > before:
                record = reg.on_compile(self._label, self._fn, args, kwargs,
                                        wall_s=time.perf_counter() - t0,
                                        hbm_scope=self._hbm_scope)
                if record is not None:
                    self._program_record = record
        return out

    def _cache_size(self) -> Optional[int]:
        try:
            return self._fn._cache_size()
        except Exception:  # noqa: BLE001 - non-pjit callables
            return None

    def __getattr__(self, name):
        return getattr(self._fn, name)


def unwrap_program_watch(fn: Callable) -> Callable:
    """The underlying jitted callable of a registry watcher (identity for
    anything else)."""
    return fn._fn if isinstance(fn, _Watch) else fn


class ProgramRegistry:
    """Process-wide inventory of captured compiled programs.

    ``enabled`` follows the process-global tracer by default (telemetry on ⇒
    programs on) and can be pinned either way with :meth:`configure` — the
    ``telemetry.programs`` config knob. All mutation is lock-guarded; capture
    never raises into the training/serving loop (a failed capture logs at
    debug and returns None).
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._explicit_enabled: Optional[bool] = None
        self._records: Dict[str, List[ProgramRecord]] = {}
        self._hbm_estimates: Dict[str, int] = {}
        self._seq = 0
        self.capture_failures = 0

    # ------------------------------------------------------------- config
    @property
    def enabled(self) -> bool:
        if self._explicit_enabled is not None:
            return self._explicit_enabled
        from deepspeed_tpu.telemetry.tracer import get_tracer

        return get_tracer().enabled

    def configure(self, enabled: Optional[bool] = None) -> "ProgramRegistry":
        """Pin enablement (True/False) or restore follow-the-tracer (None)."""
        self._explicit_enabled = enabled
        return self

    def reset(self) -> None:
        with self._lock:
            self._records = {}
            self._hbm_estimates = {}
            self._seq = 0
            self.capture_failures = 0

    # ------------------------------------------------------------ queries
    def labels(self) -> List[str]:
        with self._lock:
            return list(self._records)

    def latest(self, label: str) -> Optional[ProgramRecord]:
        with self._lock:
            hist = self._records.get(label)
            return hist[-1] if hist else None

    def history(self, label: str) -> List[ProgramRecord]:
        with self._lock:
            return list(self._records.get(label, ()))

    def records(self) -> List[ProgramRecord]:
        """Every capture, in capture order."""
        with self._lock:
            out = [r for hist in self._records.values() for r in hist]
        return sorted(out, key=lambda r: r.index)

    # ------------------------------------------------------- hbm estimates
    def set_hbm_estimate(self, estimate_bytes: int, scope: str = "train") -> None:
        """Register a pre-flight ``utils/hbm.py`` estimate for calibration.

        ``scope`` names which programs the estimate covers ("train" for the
        runtime engine's step programs, "serving" for the v2 engine's) — the
        wrap point tags each program with its scope. Last writer wins per
        scope (one live engine per scope is the norm; multi-engine tests
        overwrite, which is the honest reading of "the current engine").
        """
        if estimate_bytes and estimate_bytes > 0:
            with self._lock:
                self._hbm_estimates[scope] = int(estimate_bytes)

    def hbm_estimate(self, scope: str) -> Optional[int]:
        with self._lock:
            return self._hbm_estimates.get(scope)

    # ------------------------------------------------------------- wrapping
    def wrap(self, fn: Callable, label: str,
             hbm_scope: Optional[str] = None) -> Callable:
        """Cache-growth watcher for a jitted callable (engines with the
        recompile detector installed get capture through the detector's
        wrapper instead — one probe, not two)."""
        if fn is None:
            return fn
        return _Watch(fn, label, self, hbm_scope)

    # -------------------------------------------------------------- capture
    def on_compile(self, label: str, fn: Callable, args: Tuple, kwargs: Dict,
                   wall_s: Optional[float] = None,
                   hbm_scope: Optional[str] = None) -> Optional[ProgramRecord]:
        """Capture the program ``fn`` just compiled for ``(args, kwargs)``.

        Called from the wrap points right after a dispatch compile was
        detected; must never raise. Lowering only needs avals, so donated
        (already-deleted) argument buffers are fine.
        """
        if not self.enabled:
            return None
        t0 = time.perf_counter()
        try:
            compiled = fn.lower(*args, **kwargs).compile()
            record = self._record_compiled(label, compiled, wall_s, hbm_scope, t0)
        except Exception as e:  # noqa: BLE001 — observability must not break the step
            self.capture_failures += 1
            logger.debug(f"program capture failed for {label!r}: {e}")
            return None
        return record

    def capture(self, fn: Callable, *args, label: Optional[str] = None,
                hbm_scope: Optional[str] = None, **kwargs) -> Optional[ProgramRecord]:
        """Explicit capture of a jittable/jitted ``fn`` (the
        ``flops_profiler.compiled_cost`` entry point). Reuses an existing
        record when one was already captured for this label's current
        program fingerprint-equivalent signature; otherwise lowers+compiles
        once (XLA's in-memory caches absorb repeats) and records it.
        Works even when the registry is disabled — explicit calls are their
        own opt-in — but publishes metrics only when telemetry is enabled.
        """
        import jax

        jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
        label = label or f"capture:{getattr(fn, '__name__', 'fn')}"
        t0 = time.perf_counter()
        try:
            compiled = jitted.lower(*args, **kwargs).compile()
            return self._record_compiled(label, compiled, None, hbm_scope, t0,
                                         dedupe=True)
        except Exception as e:  # noqa: BLE001
            self.capture_failures += 1
            logger.debug(f"program capture failed for {label!r}: {e}")
            return None

    # ------------------------------------------------------------ internals
    def _record_compiled(self, label: str, compiled, wall_s: Optional[float],
                         hbm_scope: Optional[str], t0: float,
                         dedupe: bool = False) -> ProgramRecord:
        """``dedupe``: return the label's existing record when the program
        content is unchanged (explicit ``capture()`` calls may repeat per
        step — without this they would grow the inventory unboundedly; the
        wrap-point path never dedupes: each dispatch compile IS an event)."""
        costs = compiled.cost_analysis()
        if isinstance(costs, list):  # older jax returns [dict]
            costs = costs[0] if costs else {}
        costs = dict(costs or {})
        flops = float(costs.get("flops", 0.0))
        bytes_accessed = float(
            costs.get("bytes accessed", costs.get("bytes_accessed", 0.0)))

        arg_b = out_b = temp_b = alias_b = code_b = 0
        try:
            mem = compiled.memory_analysis()
        except Exception:  # noqa: BLE001 - not all backends implement it
            mem = None
        if mem is not None:
            arg_b = int(getattr(mem, "argument_size_in_bytes", 0))
            out_b = int(getattr(mem, "output_size_in_bytes", 0))
            temp_b = int(getattr(mem, "temp_size_in_bytes", 0))
            alias_b = int(getattr(mem, "alias_size_in_bytes", 0))
            code_b = int(getattr(mem, "generated_code_size_in_bytes", 0))
        peak = max(arg_b + out_b - alias_b + temp_b, 0)

        fingerprint, n_instr, colls, kernels, alias_pairs = "", 0, [], [], 0
        try:
            text = compiled.as_text()
            fingerprint, n_instr = hlo_fingerprint(text)
            colls = extract_collectives(text)
            kernels = extract_custom_kernels(text)
            header = text.split("\n", 1)[0]
            if "input_output_alias=" in header:
                alias_pairs = header.count(": (")
        except Exception as e:  # noqa: BLE001 - text dump is best-effort
            logger.debug(f"HLO text analysis unavailable for {label!r}: {e}")

        if dedupe and fingerprint:
            prev = self.latest(label)
            if prev is not None and prev.fingerprint == fingerprint:
                return prev

        with self._lock:
            index = self._seq
            self._seq += 1
        record = ProgramRecord(
            label=label, index=index,
            fingerprint=fingerprint, instruction_count=n_instr,
            compile_wall_s=wall_s, capture_s=time.perf_counter() - t0,
            flops=flops, bytes_accessed=bytes_accessed,
            argument_bytes=arg_b, output_bytes=out_b, temp_bytes=temp_b,
            alias_bytes=alias_b, alias_pairs=alias_pairs,
            generated_code_bytes=code_b, peak_hbm_bytes=peak,
            collectives=colls, custom_kernels=kernels,
        )

        # Reconcile the wire bytes the selector's routing traced (the
        # observatory's per-trace census, drained since the last capture)
        # against what the compiled HLO actually moves. HLO collective
        # bytes include EVERY collective (loss psums, GSPMD resharding), so
        # the ratio runs >= 1 on healthy programs; well below 1 means routed
        # wires the extraction cannot see — the selector is costing bytes
        # that never hit the interconnect.
        try:
            from deepspeed_tpu.collectives import observatory as _coll_obs

            routed = _coll_obs.drain_program_wire()
        except Exception:  # noqa: BLE001 — reconciliation is best-effort
            routed = 0
        if routed > 0:
            record.routed_wire_bytes = routed
            record.wire_ratio = record.collective_bytes / routed
            if record.wire_ratio < 0.5:
                logger.warning(
                    f"collectives: program {label!r} lowered "
                    f"{record.collective_bytes} collective bytes but the "
                    f"selector's routing traced {routed} wire bytes "
                    f"(ratio {record.wire_ratio:.2f}) — routed wires are "
                    "not reaching the interconnect as costed")

        estimate = self.hbm_estimate(hbm_scope) if hbm_scope else None
        if estimate:
            from deepspeed_tpu.utils.hbm import record_calibration

            record.hbm_estimate_bytes = estimate
            record.hbm_estimate_ratio = record_calibration(
                estimate, peak, what=label)

        with self._lock:
            self._records.setdefault(label, []).append(record)
        self._publish(record)
        return record

    def _publish(self, r: ProgramRecord) -> None:
        from deepspeed_tpu.telemetry.tracer import get_tracer

        tracer = get_tracer()
        if not tracer.enabled:
            return
        reg = tracer.registry
        for name, value in (
            ("program/flops", r.flops),
            ("program/bytes_accessed", r.bytes_accessed),
            ("program/peak_hbm_bytes", r.peak_hbm_bytes),
            ("program/argument_bytes", r.argument_bytes),
            ("program/output_bytes", r.output_bytes),
            ("program/temp_bytes", r.temp_bytes),
            ("program/alias_bytes", r.alias_bytes),
            ("program/instruction_count", r.instruction_count),
            ("program/collective_count", len(r.collectives)),
            ("program/collective_bytes", r.collective_bytes),
            ("program/custom_kernel_count", r.custom_kernel_count),
        ):
            reg.gauge(name, program=r.label).set(float(value))
        if r.wire_ratio is not None:
            reg.gauge("coll/wire_bytes_ratio", program=r.label).set(r.wire_ratio)
        reg.counter("compile/count", program=r.label).add(1.0)
        if r.compile_wall_s is not None:
            reg.gauge("compile/last_wall_ms", program=r.label).set(
                r.compile_wall_s * 1e3)
            reg.counter("compile/wall_ms_total", program=r.label).add(
                r.compile_wall_s * 1e3)
            # Perfetto counter track: compile activity over the run
            tracer.sample_counter("compile/wall_ms", r.compile_wall_s * 1e3)
        tracer.sample_counter("compile/capture_ms", r.capture_s * 1e3)
        tracer.sample_counter("program/peak_hbm_bytes", float(r.peak_hbm_bytes))
        tracer.instant(
            f"program:{r.label}", cat="programs",
            fingerprint=r.fingerprint, instructions=r.instruction_count,
            flops=r.flops, peak_hbm_bytes=r.peak_hbm_bytes,
            collectives=len(r.collectives),
        )


_registry = ProgramRegistry()


def get_program_registry() -> ProgramRegistry:
    return _registry


def configure(enabled: Optional[bool] = None) -> ProgramRegistry:
    """Configure the process-global program registry (the
    ``telemetry.programs`` config knob routes here)."""
    return _registry.configure(enabled=enabled)
