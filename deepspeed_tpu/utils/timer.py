"""Wall-clock and throughput timers.

TPU-native analog of the reference's ``deepspeed/utils/timer.py``
(``SynchronizedWallClockTimer`` :44, ``ThroughputTimer`` :199). Synchronization
uses ``jax.block_until_ready`` on a token instead of accelerator events: JAX
dispatch is async, so a timer stop must drain the device queue to be meaningful.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"
TRAIN_BATCH_TIMER = "train_batch"


def _sync() -> None:
    """Drain async dispatch so host wall-clock brackets device work."""
    try:
        import jax

        (jax.device_put(0.0) + 0).block_until_ready()
    except Exception:  # pragma: no cover
        pass


class Timer:
    """A single named wall-clock timer with accumulation."""

    def __init__(self, name: str, synchronize: bool = True):
        self.name = name
        self.synchronize = synchronize
        self.started = False
        self._start_time = 0.0
        self._elapsed = 0.0
        self._record: List[float] = []

    def start(self) -> None:
        if self.started:
            return
        if self.synchronize:
            _sync()
        self._start_time = time.time()
        self.started = True

    def stop(self, record: bool = True) -> None:
        if not self.started:
            return
        if self.synchronize:
            _sync()
        span = time.time() - self._start_time
        self._elapsed += span
        if record:
            self._record.append(span)
        self.started = False

    def elapsed(self, reset: bool = True) -> float:
        """Total accumulated seconds; optionally reset."""
        now = time.time()
        value = self._elapsed
        if self.started:
            value += now - self._start_time
        if reset:
            self._elapsed = 0.0
            if self.started:
                self._start_time = now  # don't re-count the span just reported
        return value

    def mean(self) -> float:
        return sum(self._record) / len(self._record) if self._record else 0.0

    def reset(self) -> None:
        self.started = False
        self._elapsed = 0.0
        self._record = []


class SynchronizedWallClockTimer:
    """Group of named timers (reference ``utils/timer.py:44``)."""

    def __init__(self, synchronize: bool = True):
        self.timers: Dict[str, Timer] = {}
        self.synchronize = synchronize

    def __call__(self, name: str) -> Timer:
        if name not in self.timers:
            self.timers[name] = Timer(name, synchronize=self.synchronize)
        return self.timers[name]

    def has_timer(self, name: str) -> bool:
        return name in self.timers

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True) -> str:
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {ms:.2f}")
        msg = "time (ms) | " + " | ".join(parts)
        log_dist(msg, ranks=[0])
        return msg


class ThroughputTimer:
    """Samples/sec + TFLOPs reporting (reference ``utils/timer.py:199``).

    Deliberately does NOT synchronize the device per step: a per-step sync
    would serialize JAX async dispatch and dominate the step itself (the
    round-2 verdict's engine.py:810 finding). Instead it measures continuous
    wall-clock across a reporting window — steps dispatch asynchronously
    inside the window, and the engine's periodic metrics fetch provides the
    real sync point, so window averages reflect true device throughput while
    individual in-window spans only capture dispatch.
    """

    def __init__(
        self,
        batch_size: int,
        steps_per_output: int = 100,
        monitor_memory: bool = False,
        logging_fn=None,
    ):
        self.batch_size = max(1, batch_size)
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or (lambda msg: log_dist(msg, ranks=[0]))
        self.started = False
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self._window_start = None  # wall-clock origin of the current window
        self._window_steps = 0
        self._steps_accounted = 0  # steps inside completed windows
        self._initialized = False

    def update_epoch_count(self) -> None:
        self._initialized = False

    def start(self) -> None:
        self.started = True
        if not self._initialized:
            self._initialized = True
        if self._window_start is None:
            self._window_start = time.time()

    def stop(self, global_step: bool = True, report_speed: bool = True) -> None:
        if not self.started:
            return
        self.started = False
        if global_step:
            self.global_step_count += 1
            self._window_steps += 1
            if self.global_step_count % self.steps_per_output == 0:
                duration = time.time() - self._window_start
                self.total_elapsed_time += duration
                self.step_elapsed_time = duration
                if report_speed:
                    self.logging(
                        f"epoch step rate: "
                        f"{self._window_steps * self.batch_size / max(duration, 1e-9):.2f} samples/sec, "
                        f"step time {duration / max(self._window_steps, 1) * 1000:.1f} ms"
                    )
                self._steps_accounted += self._window_steps
                self._window_start = None
                self._window_steps = 0

    def avg_samples_per_sec(self) -> float:
        steps, elapsed = self._steps_accounted, self.total_elapsed_time
        if steps == 0 and self._window_steps > 0 and self._window_start is not None:
            # no completed window yet: use the live one
            steps, elapsed = self._window_steps, time.time() - self._window_start
        if steps > 0 and elapsed > 0:
            return steps * self.batch_size / elapsed
        return 0.0


def trainable_parameters_numel(params) -> int:
    """Total element count of a parameter pytree."""
    import jax

    return sum(x.size for x in jax.tree_util.tree_leaves(params))
