"""Force the CPU platform in a sandbox whose sitecustomize registers the
experimental axon TPU PJRT plugin in every interpreter.

With the plugin factory registered, the FIRST jax computation can initialize
it and block indefinitely on a wedged relay — even when the platform is
pinned to cpu via env or config (observed round 5: a 4x4 matmul hung with 0%
CPU under JAX_PLATFORMS=cpu). Dropping the factory before first device access
is the only reliable workaround; this is the single shared implementation for
tests/conftest.py, bench.py, __graft_entry__.py, and tools/.
"""

from __future__ import annotations


def force_cpu_backend() -> None:
    """Pin jax to the CPU platform and drop the axon backend factory.

    Safe to call multiple times; must run before the first device access
    (jax may already be imported — sitecustomize does that — so env vars
    alone are not enough)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge

        xla_bridge._backend_factories.pop("axon", None)
    except Exception:
        # private jax API — if it moves, the config pin above still covers
        # the non-wedged case rather than breaking startup
        pass
