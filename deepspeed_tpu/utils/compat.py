"""Version compatibility shims for the jax API surface.

``shard_map`` is the one symbol this package needs whose location AND
signature moved across jax releases:

  - new jax exports ``jax.shard_map(f, mesh=..., in_specs=..., out_specs=...,
    axis_names=..., check_vma=...)`` as a function
  - some intermediate versions expose ``jax.shard_map`` as a MODULE holding
    the function
  - jax 0.4.x (this environment) only has
    ``jax.experimental.shard_map.shard_map(f, mesh, in_specs, out_specs,
    check_rep=..., auto=...)`` — no ``axis_names``/``check_vma`` kwargs

Every call site in the package imports ``shard_map`` from HERE and writes the
new-API spelling; this wrapper translates to whatever the installed jax
understands (``check_vma`` -> ``check_rep``; ``axis_names={manual}`` ->
``auto = mesh_axes - manual``). A tier-1 lint (tests/unit/
test_no_bare_shard_map.py) greps the tree so bare ``jax.shard_map`` /
``from jax import shard_map`` imports cannot regress.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax


def _resolve_native() -> Optional[Callable]:
    sm = getattr(jax, "shard_map", None)
    if sm is not None and not callable(sm):  # module-valued on some versions
        sm = getattr(sm, "shard_map", None)
    return sm


_NATIVE = _resolve_native()
if _NATIVE is None:
    from jax.experimental.shard_map import shard_map as _EXPERIMENTAL
else:
    _EXPERIMENTAL = None


def axis_size(axis, default: Optional[int] = None) -> int:
    """``jax.lax.axis_size`` with the pre-0.5 fallback: a unit psum over a
    bound axis is statically the axis size at trace time. Accepts an axis
    name or a tuple of them. This is THE axis-size helper — the comm facade,
    zeropp, and the collectives algorithms all route here.

    Outside a bound-axis context the size is unknowable; pass ``default`` to
    get it back instead of the NameError (the comm facade's record path uses
    ``default=1`` so telemetry works outside shard_map too)."""
    if isinstance(axis, (tuple, list)):
        out = 1
        for a in axis:
            out *= axis_size(a, default=default)
        return out
    try:
        try:
            return int(jax.lax.axis_size(axis))
        except (AttributeError, TypeError):
            return int(jax.lax.psum(1, axis))
    except Exception:
        if default is not None:
            return int(default)
        raise


def shape_dtype_struct(shape, dtype, *like):
    """``jax.ShapeDtypeStruct`` for a Pallas ``out_shape``, stamped with the
    union of the varying-manual-axes of ``like`` where this jax tracks them
    (``jax.typeof(x).vma`` + the ``vma=`` kwarg, new-jax ``check_vma``);
    0.4.x has neither, and shard_map composition is governed by
    ``check_rep``/``check_vma=False`` at the shard_map call instead."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    vma = frozenset()
    for a in like:
        vma = vma | getattr(typeof(a), "vma", frozenset())
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # typeof exists but ShapeDtypeStruct predates vma=
        return jax.ShapeDtypeStruct(shape, dtype)


def axis_env_sizes() -> "dict[str, int]":
    """(name -> size) of every mesh axis bound in the trace-time axis env,
    in binding order (full-manual shard_map binds them all). The axis env
    lives behind private jax internals that have moved across releases —
    try each known spelling (same pattern as ``tpu_compiler_params`` /
    ``shape_dtype_struct``) so a rename cannot break every caller at trace
    time. Returns ``{}`` outside any bound-axis context."""
    from jax._src import core as _core

    get_env = getattr(_core, "get_axis_env", None)
    if get_env is not None:  # jax >= 0.4.3x: AxisEnv with .axis_sizes
        sizes = getattr(get_env(), "axis_sizes", None)
        if sizes is not None:
            return {str(k): int(v) for k, v in dict(sizes).items()}
    # older spelling: thread-local AxisEnvFrame(name, size, ...) records
    tls = getattr(_core, "thread_local_state", None)
    frames = getattr(getattr(tls, "trace_state", None), "axis_env", None)
    if frames is not None:
        return {str(f.name): int(f.size) for f in frames
                if f.name is not None}
    raise RuntimeError(
        "cannot locate the jax axis env on this version — "
        "utils/compat.axis_env_sizes needs a new spelling")


def tpu_compiler_params(**kwargs):
    """Pallas TPU compiler params across the class rename
    (``pltpu.CompilerParams`` on new jax, ``pltpu.TPUCompilerParams`` on
    0.4.x); kwargs the installed class does not know are dropped rather
    than raising, so call sites can write the full new-API surface."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    fields = getattr(cls, "__dataclass_fields__", None)
    if fields is not None:
        kwargs = {k: v for k, v in kwargs.items() if k in fields}
    return cls(**kwargs)


def memory_space(space: str):
    """A ``jax.device_put`` target selecting host vs device memory.

    New jax spells it ``jax.memory.Space.Host/Device``; 0.4.x spells it
    ``TransferToMemoryKind('pinned_host'|'device')``. Both work inside jit
    (sharding-preserving memory-kind transfer)."""
    mem = getattr(jax, "memory", None)
    if mem is not None:
        return mem.Space.Host if space == "host" else mem.Space.Device
    from jax._src.sharding_impls import TransferToMemoryKind

    return TransferToMemoryKind("pinned_host" if space == "host" else "device")


def with_memory_kind(sharding, kind: str):
    """``sharding.with_memory_kind(kind)`` with a device-capability fallback.

    CPU devices on this jax address exactly ONE memory space
    (``unpinned_host``) — there is no pinned-host/device split to place
    into, and constructing a sharding with either kind raises ``ValueError:
    Could not find memory addressable by device cpu``. Offload placement
    (ZeRO-Inference's pinned-host weights, the stream-on-read device
    reads) degrades to the device-set's default kind there: every
    ``device_put`` through the returned sharding is a same-space no-op, so
    the code path stays exercised end-to-end on CPU instead of crashing,
    and real TPU/GPU backends get the requested kind unchanged."""
    try:
        return sharding.with_memory_kind(kind)
    except ValueError:
        # requested kind unaddressable on this backend: keep the sharding's
        # current (default) memory kind — placement becomes the identity
        return sharding


def shard_map(
    f: Callable,
    *,
    mesh: Any,
    in_specs: Any,
    out_specs: Any,
    axis_names: Any = None,
    check_vma: Optional[bool] = None,
    check_rep: Optional[bool] = None,
) -> Callable:
    """``jax.shard_map`` with the NEW keyword surface on every jax version.

    ``axis_names``: the axes the body handles manually (default: all mesh
    axes). ``check_vma`` (new spelling) and ``check_rep`` (old spelling) are
    the same knob; pass at most one.
    """
    if check_vma is not None and check_rep is not None:
        raise TypeError("pass only one of check_vma / check_rep")
    check = check_vma if check_vma is not None else check_rep

    if _NATIVE is not None:
        kwargs: dict = {}
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
        if check is not None:
            kwargs["check_vma"] = check
        return _NATIVE(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)

    kwargs = {}
    if check is not None:
        kwargs["check_rep"] = check
    if axis_names is not None:
        manual = set(axis_names)
        auto = frozenset(a for a in mesh.axis_names if a not in manual)
        if auto:
            kwargs["auto"] = auto
    return _EXPERIMENTAL(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


def device_put_unaliased(arr, sharding):
    """``jax.device_put`` of host numpy into buffers XLA owns EXCLUSIVELY.

    On the CPU backend, ``device_put`` of a 64-byte-aligned numpy array is
    ZERO-COPY: the resulting ``jax.Array`` (or its device-0 shard under a
    replicated sharding) aliases numpy-owned memory. A checkpoint-restored
    leaf flows straight into the engine's compiled steps, which DONATE
    their state buffers — XLA then reuses memory it does not exclusively
    own, and the glibc heap corrupts ("corrupted double-linked list" aborts
    / segfaults a few steps after restore, nondeterministic because it
    hinges on malloc returning a 64-byte-aligned block for that particular
    array). This is the PR-1 checkpoint landmine, root-caused by the PR-6
    fault-injection work. Copying through a deliberately misaligned staging
    buffer breaks the zero-copy precondition, so PJRT always copies into
    its own allocation. Every restore path places leaves through here.
    """
    import numpy as np

    if isinstance(arr, jax.Array):  # already XLA-owned: plain transfer is safe
        return jax.device_put(arr, sharding)
    arr = np.asarray(arr)
    if arr.nbytes:
        staging = np.empty(arr.nbytes + 64 + arr.itemsize, dtype=np.uint8)
        base = (-staging.ctypes.data) % 64
        off = base + arr.itemsize  # itemsize-aligned for the view, never 64-aligned
        view = staging[off:off + arr.nbytes].view(arr.dtype).reshape(arr.shape)
        np.copyto(view, arr)
        arr = view
    return jax.device_put(arr, sharding)


def host_copy_unaliased(tree):
    """``jax.device_get`` into host memory the CALLER owns exclusively.

    The D2H mirror of :func:`device_put_unaliased`. On the CPU backend
    ``device_get`` of a committed array is ZERO-COPY — the numpy result is a
    VIEW of the PJRT buffer. A donated step is supposed to copy rather than
    alias when the input buffer has live external references, but executables
    deserialized from the persistent compilation cache skip that protection
    on this jax/XLA build (observed under
    ``--xla_backend_optimization_level=1``, the test-harness setting): the
    step writes THROUGH the view, so any ``device_get`` result that outlives
    the next donated step — an async checkpoint writer's queued payload, the
    snapshot boundary copy, a caller-held "state before" reference — silently
    reads the LATER state. A torn/mutated host reference, not heap
    corruption: the memory is PJRT-owned either way. ``np.array(copy=True)``
    breaks the aliasing; every D2H that must stay frozen goes through here.
    """
    import numpy as np

    return jax.tree_util.tree_map(
        lambda x: np.array(jax.device_get(x), copy=True) if x is not None else x,
        tree)
