"""Pre-flight HBM-fit guard (VERDICT round-5 item 2).

The ~890M bench extra wedged the shared TPU relay for 9+ hours at param
materialization on a failure the existing memory math predicted — the init
RPC simply never returned, so nothing downstream could raise. This module
checks a byte estimate against the device's memory BEFORE anything is
materialized on chip, and either warns (default) or refuses with the
estimate in the error.

Device memory discovery: ``jax.devices()[0].memory_stats()['bytes_limit']``
where the backend reports it; the ``DSTPU_DEVICE_MEMORY_GB`` env var or an
explicit ``device_memory`` argument overrides (and is the only way to make
the guard bite on CPU backends, which report host RAM or nothing — that is
also what the unit tests use). With no budget discoverable the check is a
no-op: the guard must never block CPU smoke runs.
"""

from __future__ import annotations

import os
from typing import Optional

from deepspeed_tpu.utils.logging import logger


class HBMBudgetError(RuntimeError):
    """Raised (mode='refuse') when an estimate exceeds the device budget."""


# ------------------------------------------------------ quantized-serving math
# The serving-capacity byte formulas (ISSUE 10): KV bytes/token is the
# admission bottleneck the guard protects, so the guard, the engine's pool
# sizing, and the capacity benchmark must all agree on ONE definition of what
# a quantized block costs. Quantized storage (int8/fp8) holds 1 byte/element
# plus one fp32 scale per (layer, slot, kv-head) hd-vector block — the
# ``ops.quant`` block-math layout ``inference/paged.py`` writes.

KV_SCALE_BYTES = 4  # fp32 scale per (slot, head) quantization block


def kv_slot_bytes(num_layers: int, kv_heads: int, head_dim: int,
                  dtype_bytes: int = 2, kv_quant: Optional[str] = None) -> int:
    """Bytes ONE token slot occupies in the paged KV pool (k + v)."""
    if kv_quant is None:
        per_head = head_dim * dtype_bytes
    else:
        per_head = head_dim * 1 + KV_SCALE_BYTES
    return 2 * num_layers * kv_heads * per_head


def kv_pool_bytes(num_layers: int, num_slots: int, kv_heads: int, head_dim: int,
                  dtype_bytes: int = 2, kv_quant: Optional[str] = None) -> int:
    """Bytes of a paged pool holding ``num_slots`` token slots (pass
    ``num_blocks * block_size + 1`` to include the trash slot)."""
    return num_slots * kv_slot_bytes(num_layers, kv_heads, head_dim,
                                     dtype_bytes, kv_quant)


def kv_blocks_for_bytes(pool_bytes: int, num_layers: int, block_size: int,
                        kv_heads: int, head_dim: int, dtype_bytes: int = 2,
                        kv_quant: Optional[str] = None) -> int:
    """How many KV blocks fit a byte budget — the admission-capacity lever:
    at identical ``pool_bytes`` an int8 pool yields ~2x the blocks of a bf16
    pool (head_dim ≥ 64: ≥1.88x after the per-block scale), which is what the
    ``BlockedAllocator`` sizing then admits."""
    per_block = block_size * kv_slot_bytes(num_layers, kv_heads, head_dim,
                                           dtype_bytes, kv_quant)
    return max(int(pool_bytes) // per_block, 1)


def disagg_pool_bytes(total_bytes: int, roles, prefill_share: float = 0.25):
    """Split one serving tier's KV byte budget across phase-specialized
    replica pools (ISSUE 14 capacity math).

    Prefill pools hold a request's KV only TRANSIENTLY — from the prefill
    dispatch until its migration commits, bounded by ``migration_depth``
    concurrent exports times the longest prompt — while the decode pool
    holds EVERY in-flight request's full context for its whole generation.
    So the decode side gets the bulk: the prefill replicas share
    ``prefill_share`` of the budget evenly, decode (and mixed, which also
    decode) replicas share the rest. A roster with no specialized role
    splits evenly — the mixed baseline at equal hardware.

    Returns one byte budget per entry of ``roles``, summing to
    ``total_bytes`` (modulo integer division).
    """
    roles = list(roles)
    if not roles:
        raise ValueError("disagg_pool_bytes needs at least one role")
    if not 0.0 < prefill_share < 1.0:
        raise ValueError(f"prefill_share must be in (0, 1), got {prefill_share}")
    n_pre = sum(1 for r in roles if r == "prefill")
    n_rest = len(roles) - n_pre
    if n_pre == 0 or n_rest == 0:
        return [int(total_bytes) // len(roles)] * len(roles)
    pre_each = int(total_bytes * prefill_share) // n_pre
    rest_each = int(total_bytes - pre_each * n_pre) // n_rest
    return [pre_each if r == "prefill" else rest_each for r in roles]


def prefix_cache_capacity_blocks(num_blocks: int, fraction: float) -> int:
    """Cache-aware pool sizing (ISSUE 12): how many pool blocks the prefix
    cache may hold references to. The cap guarantees live sequences always
    have at least ``(1 - fraction)`` of the pool available after LRU
    eviction, and because cached blocks store QUANTIZED bytes, the same
    ``fraction`` of an int8 pool indexes ~1.9x the prefix tokens of a bf16
    pool at fixed HBM (the PR-10 byte shrink compounding with reuse)."""
    if not 0.0 <= fraction <= 1.0:
        raise ValueError(f"prefix-cache fraction must be in [0, 1], got {fraction}")
    return int(num_blocks * fraction)


def record_calibration(
    estimate_bytes: int,
    actual_peak_bytes: Optional[int],
    *,
    what: str,
    warn_factor: float = 1.2,
    registry=None,
) -> Optional[float]:
    """Reconcile a pre-flight estimate with XLA's own ``memory_analysis()``.

    The guard's whole value is refusing BEFORE a wedge — which it can only do
    if its byte math tracks reality. Every captured program's XLA peak
    (argument + output − alias + temp) is compared against the estimate the
    engine registered; the ratio lands as ``hbm/estimate_ratio`` (labelled
    per program, plus an unlabelled last-program gauge), and an
    under-estimate beyond ``warn_factor`` (default: actual >20% over the
    estimate) warns loudly — that is the guard flying blind. Ratios well
    below 1 are normal: the estimate covers the whole engine state while a
    single program's peak covers only its live set.

    Returns the ratio, or None when either side is unusable.
    """
    if not estimate_bytes or estimate_bytes <= 0 or not actual_peak_bytes:
        return None
    ratio = float(actual_peak_bytes) / float(estimate_bytes)
    if registry is None:
        from deepspeed_tpu.telemetry import get_tracer

        tracer = get_tracer()
        registry = tracer.registry if tracer.enabled else None
    if registry is not None:
        registry.gauge("hbm/estimate_ratio", program=what).set(ratio)
        registry.gauge("hbm/estimate_ratio").set(ratio)
    if ratio > warn_factor:

        def fmt(b: float) -> str:
            return (f"{b / (1 << 30):.2f} GiB" if b >= (1 << 28)
                    else f"{b / (1 << 20):.2f} MiB")

        logger.warning(
            f"HBM calibration: program {what!r} peaks at "
            f"{fmt(actual_peak_bytes)} per XLA memory_analysis but the "
            f"pre-flight guard estimated {fmt(estimate_bytes)} "
            f"({ratio:.2f}x) — the refuse-mode guard is under-estimating "
            "and may admit a run that wedges the device; revisit "
            "estimate_state_memory terms for this config.")
    return ratio


def device_memory_bytes(device=None) -> Optional[int]:
    """Best-effort per-device memory budget in bytes, or None if unknown.

    ``DSTPU_DEVICE_MEMORY_GB`` overrides backend discovery (set it to make
    the guard authoritative on backends with unreliable ``memory_stats``).
    CPU backends are treated as unknown — host RAM is not the budget the
    guard protects.
    """
    env = os.environ.get("DSTPU_DEVICE_MEMORY_GB")
    if env:
        return int(float(env) * (1 << 30))
    try:
        import jax

        dev = device if device is not None else jax.devices()[0]
        if dev.platform == "cpu":
            return None
        stats = dev.memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:  # noqa: BLE001 — discovery must never break init
        pass
    return None


def check_hbm_fit(
    need_bytes: int,
    *,
    what: str,
    mode: str = "warn",
    device_memory: Optional[int] = None,
    headroom: float = 0.92,
) -> bool:
    """Check ``need_bytes`` against the device budget BEFORE materializing.

    mode: 'warn' logs and proceeds; 'refuse' raises :class:`HBMBudgetError`;
    'off' is a no-op. Returns True when the estimate fits (or no budget is
    discoverable), False when it does not and mode permitted proceeding.
    """
    if mode not in ("warn", "refuse", "off"):
        raise ValueError(f"hbm guard mode must be warn|refuse|off, got {mode!r}")
    if mode == "off":
        return True
    budget = device_memory if device_memory is not None else device_memory_bytes()
    if budget is None:
        return True
    usable = int(budget * headroom)
    if need_bytes <= usable:
        return True

    def fmt(b: float) -> str:
        return (f"{b / (1 << 30):.2f} GiB" if b >= (1 << 28)
                else f"{b / (1 << 20):.2f} MiB")

    msg = (
        f"HBM pre-flight: {what} needs an estimated {fmt(need_bytes)} "
        f"but the device budget is {fmt(budget)} "
        f"({headroom:.0%} usable = {fmt(usable)}). "
        "Materializing anyway can wedge the device without raising (round-5 "
        "relay incident). Shrink the model/batch, raise ZeRO stage, or enable "
        "offload."
    )
    if mode == "refuse":
        raise HBMBudgetError(msg)
    logger.warning(msg)
    return False
