"""Debugging access to sharded training state by parameter path.

Reference analog: ``deepspeed/utils/tensor_fragment.py:132-199`` —
``safe_get_full_fp32_param`` / ``safe_get_full_optimizer_state`` /
``safe_get_full_grad`` and the ``safe_set_*`` writers, which reassemble a
ZeRO-partitioned tensor for inspection and scatter edits back to the shards.

On TPU the partitions are shardings, so "gather the fragments" is
``jax.device_get`` (XLA assembles the global array) and "scatter back" is
``jax.device_put`` with the leaf's sharding. Parameters are addressed by
pytree path — ``"embed/embedding"`` or ``("embed", "embedding")`` — instead
of a module attribute, because the engine state is a pytree, not a module
graph.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax
import numpy as np

PathLike = Union[str, Sequence[str]]

# reference state names (torch Adam) -> optax ScaleByAdamState fields
_OPT_STATE_ALIASES = {"exp_avg": "mu", "exp_avg_sq": "nu", "mu": "mu", "nu": "nu"}


def _path_parts(path: PathLike):
    if isinstance(path, str):
        return [p for p in path.replace(".", "/").split("/") if p]
    return list(path)


def _get_leaf(tree: Any, path: PathLike):
    node = tree
    for part in _path_parts(path):
        if isinstance(node, dict):
            if part not in node:
                raise KeyError(f"no parameter {'/'.join(_path_parts(path))!r}: "
                               f"{part!r} not in {sorted(node)[:10]}")
            node = node[part]
        else:
            node = getattr(node, part)
    return node


def _set_leaf(tree: Any, path: PathLike, value):
    parts = _path_parts(path)
    node = tree
    for part in parts[:-1]:
        node = node[part]
    node[parts[-1]] = value


def _replace_in_params(engine, path: PathLike, value) -> None:
    params = jax.tree_util.tree_map(lambda x: x, engine.state.params)  # shallow rebuild
    old = _get_leaf(params, path)
    new = jax.device_put(np.asarray(value, dtype=old.dtype).reshape(old.shape), old.sharding)
    _set_leaf(params, path, new)
    engine.state = engine.state._replace(params=params)


# ------------------------------------------------------------------ params
def safe_get_full_fp32_param(engine, path: PathLike) -> np.ndarray:
    """Gathered fp32 master parameter (reference :132)."""
    return np.asarray(jax.device_get(_get_leaf(engine.state.params, path)))


def safe_set_full_fp32_param(engine, path: PathLike, value) -> None:
    """Write a full tensor back into the (sharded) master (reference :180)."""
    _replace_in_params(engine, path, value)


# --------------------------------------------------------------- opt state
def _find_moment_trees(opt_state, field: str):
    """Every optax sub-state carrying ``field`` (mu/nu for Adam-family).

    Twin-Flow engines hold TWO masked partition states (host, device), each
    param-tree-shaped with ``optax.MaskedNode`` holes for the other
    partition — callers probe each tree until the leaf is a real array."""
    out = []
    for s in jax.tree_util.tree_leaves(
            opt_state, is_leaf=lambda x: hasattr(x, field)):
        if hasattr(s, field):
            out.append(getattr(s, field))
    return out


def _find_moment_tree(opt_state, field: str):
    trees = _find_moment_trees(opt_state, field)
    return trees[0] if trees else None


def safe_get_full_optimizer_state(engine, path: PathLike, state_name: str) -> Optional[np.ndarray]:
    """Gathered optimizer moment for a parameter (reference :141)."""
    field = _OPT_STATE_ALIASES.get(state_name)
    if field is None:
        raise ValueError(f"unknown optimizer state {state_name!r} (use exp_avg/exp_avg_sq)")
    for tree in _find_moment_trees(engine.state.opt_state, field):
        leaf = _get_leaf(tree, path)
        if hasattr(leaf, "shape"):  # skip a masked partition's MaskedNode hole
            return np.asarray(jax.device_get(leaf))
    return None


def safe_set_full_optimizer_state(engine, path: PathLike, state_name: str, value) -> None:
    """Write a full optimizer moment back to its shards (reference :190)."""
    field = _OPT_STATE_ALIASES.get(state_name)
    if field is None:
        raise ValueError(f"unknown optimizer state {state_name!r}")

    def rebuild(node):
        if hasattr(node, field):
            tree = jax.tree_util.tree_map(lambda x: x, getattr(node, field))
            old = _get_leaf(tree, path)
            if not hasattr(old, "dtype"):
                # a Twin-Flow masked partition whose hole sits at this path:
                # the real leaf lives in the OTHER partition's state
                return node
            new = jax.device_put(np.asarray(value, old.dtype).reshape(old.shape), old.sharding)
            _set_leaf(tree, path, new)
            return node._replace(**{field: tree})
        return node

    opt_state = jax.tree_util.tree_map(
        rebuild, engine.state.opt_state, is_leaf=lambda x: hasattr(x, field)
    )
    engine.state = engine.state._replace(opt_state=opt_state)


# ------------------------------------------------------------------- grads
def safe_get_full_grad(engine, path: PathLike) -> Optional[np.ndarray]:
    """Gathered gradient (reference :152). Only populated between
    ``backward()`` and ``step()`` on the fwd/bwd/step parity path — the fused
    ``train_batch`` consumes gradients inside one compiled program and never
    materializes them for the host (by design; that is the perf contract)."""
    pending = getattr(engine, "_pending_grads", None)
    if pending is None:
        return None
    return np.asarray(jax.device_get(_get_leaf(pending, path)))
