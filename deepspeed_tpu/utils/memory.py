"""Memory-usage reporting (reference ``runtime/utils.py:771 see_memory_usage``).

TPU-native form: device stats come from PJRT ``memory_stats()`` (HBM
bytes_in_use / peak) plus the live-buffer census from ``jax.live_arrays``;
host stats read ``/proc/meminfo`` (psutil is not a baked dependency).
"""

from __future__ import annotations

import gc
from typing import Dict, Optional

import jax

from deepspeed_tpu.utils.logging import log_dist, logger

_GB = 1024 ** 3


def memory_status() -> Dict[str, float]:
    """Snapshot of device + host memory in GB (best-effort per backend —
    CPU PJRT devices report no stats; TPU reports HBM in-use and peak)."""
    out: Dict[str, float] = {}
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:  # backend without stats support
        stats = {}
    if "bytes_in_use" in stats:
        out["device_in_use_gb"] = round(stats["bytes_in_use"] / _GB, 3)
    if "peak_bytes_in_use" in stats:
        out["device_peak_gb"] = round(stats["peak_bytes_in_use"] / _GB, 3)
    if "bytes_limit" in stats:
        out["device_limit_gb"] = round(stats["bytes_limit"] / _GB, 3)
    # live jax buffers (all backends; counts each shard once per process)
    live = jax.live_arrays()
    out["live_array_gb"] = round(
        sum(getattr(a, "nbytes", 0) for a in live) / _GB, 6)
    out["live_array_count"] = len(live)
    try:
        meminfo = {}
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, rest = line.partition(":")
                meminfo[k.strip()] = int(rest.split()[0]) * 1024  # kB -> B
        total, avail = meminfo.get("MemTotal", 0), meminfo.get("MemAvailable", 0)
        if total:
            out["host_used_gb"] = round((total - avail) / _GB, 2)
            out["host_total_gb"] = round(total / _GB, 2)
    except OSError:
        pass
    return out


def see_memory_usage(message: str, force: bool = False,
                     ranks: Optional[list] = None) -> Optional[Dict[str, float]]:
    """Log a memory snapshot (reference ``see_memory_usage`` — same
    force-gated, rank-0-only contract). Returns the stats dict when logged."""
    if not force:
        return None
    gc.collect()  # drop unreferenced buffers so live_arrays reflects reality
    stats = memory_status()
    parts = [f"{k}={v}" for k, v in stats.items()]
    log_dist(f"{message} | {' '.join(parts)}", ranks=ranks or [0])
    return stats
