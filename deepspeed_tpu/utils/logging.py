"""Rank-aware logging utilities.

TPU-native analog of the reference's ``deepspeed/utils/logging.py`` (``logger``,
``log_dist``): a process-level logger plus rank-filtered helpers. On TPU the
"rank" is the JAX process index (one process per host), so ``log_dist`` filters
on ``jax.process_index()`` instead of torch.distributed rank.
"""

from __future__ import annotations

import functools
import logging
import os
import sys
from typing import Iterable, Optional

LOG_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"


@functools.lru_cache(None)
def _create_logger(name: str = "deepspeed_tpu", level: int = logging.INFO) -> logging.Logger:
    lg = logging.getLogger(name)
    lg.setLevel(level)
    lg.propagate = False
    handler = logging.StreamHandler(stream=sys.stdout)
    handler.setFormatter(logging.Formatter(LOG_FORMAT))
    lg.addHandler(handler)
    return lg


logger = _create_logger(
    level=getattr(logging, os.environ.get("DSTPU_LOG_LEVEL", "INFO").upper(), logging.INFO)
)


def _process_index() -> int:
    """Current host-process index (0 when JAX is uninitialized/single-process)."""
    try:
        import jax

        return jax.process_index()
    except Exception:  # pragma: no cover - jax always importable in this env
        return 0


def log_dist(message: str, ranks: Optional[Iterable[int]] = None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the given process ranks (default: rank 0 only).

    ``ranks=[-1]`` (or None entry) means log on every process. Mirrors the
    reference API ``deepspeed/utils/logging.py:log_dist``.
    """
    my_rank = _process_index()
    ranks = list(ranks) if ranks is not None else [0]
    if -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def print_rank_0(message: str) -> None:
    if _process_index() == 0:
        print(message, flush=True)


def warning_once(message: str) -> None:
    """Warn once per distinct message — delegates to the shared warn-once
    helper (``telemetry/events.py``), which logs the line AND emits a typed
    ``logging/warning_once`` event, so warn-once coverage and event
    coverage cannot drift apart (ISSUE 20). Lazy import: this module is at
    the bottom of the import graph; telemetry imports it, not vice versa."""
    from deepspeed_tpu.telemetry.events import warn_once

    warn_once(message)
