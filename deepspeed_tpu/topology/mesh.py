"""Device mesh / parallelism topology.

TPU-native replacement for the reference's process-group plumbing
(``deepspeed/utils/groups.py``, ``deepspeed/runtime/pipe/topology.py``): one
``jax.sharding.Mesh`` with named axes carries every parallel dimension, and
"process groups" become axis names referenced by shardings and collectives.

Canonical axis order (outermost/slowest first)::

    ("pp", "dp", "fsdp", "ep", "sp", "tp")

``pp`` (pipeline) is outermost so multi-slice deployments can run it over DCN;
``tp`` is innermost so tensor-parallel collectives ride the fastest ICI links.
The data-parallel world (for batch sharding + the batch-size triad) is the
product ``dp * fsdp``: ZeRO-3/FSDP shards both parameters and batch over
``fsdp``. Ulysses sequence parallelism shards sequence over ``sp``; its ranks
also act as data-parallel for parameter purposes (reference
``seq_data_parallel_group``, ``runtime/engine.py:1296``).
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec

MESH_AXES: Tuple[str, ...] = ("pp", "dp", "fsdp", "ep", "sp", "tp")

# Axes over which a batch is sharded (each rank of these sees distinct samples).
BATCH_AXES: Tuple[str, ...] = ("dp", "fsdp")
# Axes over which gradients must be summed (all data-like axes incl. sequence).
GRAD_REDUCE_AXES: Tuple[str, ...] = ("dp", "fsdp", "sp")


def resolve_axis_sizes(axis_sizes: Dict[str, int], n_devices: int) -> Dict[str, int]:
    """Resolve -1 axis sizes: the single -1 axis absorbs remaining devices.

    Mirrors the reference's implicit ``dp = world // (pp*mp*ep)`` arithmetic
    (``runtime/pipe/topology.py`` / ``utils/groups.py:236``).
    """
    sizes = {ax: int(axis_sizes.get(ax, 1)) for ax in MESH_AXES}
    wildcard = [ax for ax, s in sizes.items() if s == -1]
    if len(wildcard) > 1:
        raise ValueError(f"At most one mesh axis may be -1, got {wildcard}")
    fixed = 1
    for ax, s in sizes.items():
        if s != -1:
            if s < 1:
                raise ValueError(f"Mesh axis {ax} must be >=1 or -1, got {s}")
            fixed *= s
    if wildcard:
        if n_devices % fixed != 0:
            raise ValueError(
                f"Device count {n_devices} not divisible by fixed axis product {fixed}"
            )
        sizes[wildcard[0]] = n_devices // fixed
    elif fixed != n_devices:
        raise ValueError(
            f"Mesh axis product {fixed} != device count {n_devices}; "
            f"set one axis to -1 to absorb remaining devices"
        )
    return sizes


def build_mesh(
    mesh_config=None,
    devices: Optional[Sequence] = None,
    axis_sizes: Optional[Dict[str, int]] = None,
) -> Mesh:
    """Construct the named device mesh.

    ``mesh_config`` is a ``MeshConfig`` (config section); ``axis_sizes`` may be
    passed directly for tests. Multi-slice (num_slices > 1) uses a hybrid
    ICI/DCN mesh with the configured ``dcn_axis`` spanning slices.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)

    if axis_sizes is None:
        if mesh_config is None:
            axis_sizes = {"dp": -1}
        else:
            axis_sizes = {ax: getattr(mesh_config, ax) for ax in MESH_AXES}
    sizes = resolve_axis_sizes(axis_sizes, n)
    shape = tuple(sizes[ax] for ax in MESH_AXES)

    num_slices = getattr(mesh_config, "num_slices", 1) if mesh_config is not None else 1
    if num_slices > 1:
        dcn_axis = getattr(mesh_config, "dcn_axis", "dp")
        ici_shape = list(shape)
        dcn_shape = [1] * len(MESH_AXES)
        idx = MESH_AXES.index(dcn_axis)
        if sizes[dcn_axis] % num_slices != 0:
            raise ValueError(f"dcn axis {dcn_axis}={sizes[dcn_axis]} not divisible by num_slices={num_slices}")
        ici_shape[idx] = sizes[dcn_axis] // num_slices
        dcn_shape[idx] = num_slices
        device_array = mesh_utils.create_hybrid_device_mesh(
            tuple(ici_shape), tuple(dcn_shape), devices=devices, allow_split_physical_axes=True
        )
    else:
        try:
            device_array = mesh_utils.create_device_mesh(shape, devices=devices, allow_split_physical_axes=True)
        except Exception:
            device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, MESH_AXES)


# ---------------------------------------------------------------------------
# Active-mesh registry (the analog of groups.initialize() global state)
# ---------------------------------------------------------------------------

_ACTIVE_MESH: Optional[Mesh] = None


def set_mesh(mesh: Mesh) -> None:
    global _ACTIVE_MESH
    _ACTIVE_MESH = mesh


def get_mesh() -> Mesh:
    if _ACTIVE_MESH is None:
        raise RuntimeError("No active mesh; call deepspeed_tpu.initialize() or set_mesh() first")
    return _ACTIVE_MESH


def has_mesh() -> bool:
    return _ACTIVE_MESH is not None


@contextlib.contextmanager
def mesh_context(mesh: Mesh):
    global _ACTIVE_MESH
    prev = _ACTIVE_MESH
    _ACTIVE_MESH = mesh
    try:
        yield mesh
    finally:
        _ACTIVE_MESH = prev


# ---------------------------------------------------------------------------
# World-size helpers (the groups.py accessor API surface)
# ---------------------------------------------------------------------------

def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def get_data_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    """Ranks that see distinct batches (reference ``groups._get_data_parallel_world_size``)."""
    mesh = mesh or get_mesh()
    return int(np.prod([mesh.shape[a] for a in BATCH_AXES]))


def get_model_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    return mesh.shape["tp"]


def get_pipeline_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    return mesh.shape["pp"]


def get_expert_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    return mesh.shape["ep"]


def get_sequence_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    return mesh.shape["sp"]


def get_world_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    return mesh.size


# ---------------------------------------------------------------------------
# Sharding helpers
# ---------------------------------------------------------------------------

def batch_pspec(mesh: Optional[Mesh] = None, seq_axis: bool = True) -> PartitionSpec:
    """PartitionSpec for a [batch, seq, ...] array: batch over (dp, fsdp), seq over sp."""
    mesh = mesh or get_mesh()
    batch_axes = tuple(a for a in BATCH_AXES if mesh.shape[a] > 1) or None
    if seq_axis and mesh.shape["sp"] > 1:
        return PartitionSpec(batch_axes, "sp")
    return PartitionSpec(batch_axes)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


class ProcessTopology:
    """Axis-coordinate bookkeeping (reference ``runtime/pipe/topology.py:12``).

    Maps a flat rank to named-axis coordinates and back, for launcher/debug
    tooling. The mesh itself is authoritative for placement; this exists for
    API parity and host-side logic (checkpoint naming, logging).
    """

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        if len(axes) != len(dims):
            raise ValueError("axes and dims must have equal length")
        self.axes = list(axes)
        self.dims = list(int(d) for d in dims)

    def get_rank(self, **coords) -> int:
        missing = set(self.axes) - set(coords)
        if missing:
            raise ValueError(f"Missing coordinates: {missing}")
        rank = 0
        for ax, dim in zip(self.axes, self.dims):
            c = coords[ax]
            if not 0 <= c < dim:
                raise ValueError(f"Coordinate {ax}={c} out of range [0,{dim})")
            rank = rank * dim + c
        return rank

    def get_coord(self, rank: int) -> Dict[str, int]:
        coords = {}
        for ax, dim in zip(reversed(self.axes), reversed(self.dims)):
            coords[ax] = rank % dim
            rank //= dim
        return {ax: coords[ax] for ax in self.axes}

    def get_dim(self, axis: str) -> int:
        return self.dims[self.axes.index(axis)]

    @property
    def world_size(self) -> int:
        return int(np.prod(self.dims))

    def filter_match(self, **coords) -> List[int]:
        """All ranks whose coordinates match the given values."""
        return [r for r in range(self.world_size) if all(self.get_coord(r)[a] == v for a, v in coords.items())]


def topology_from_mesh(mesh: Mesh) -> ProcessTopology:
    return ProcessTopology(list(mesh.axis_names), [mesh.shape[a] for a in mesh.axis_names])
