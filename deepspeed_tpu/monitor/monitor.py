"""Experiment monitoring fan-out.

TPU-native analog of ``deepspeed/monitor/monitor.py:30 MonitorMaster`` with the
TensorBoard/W&B/CSV backends (per-backend files in ``deepspeed/monitor/``).
Comet is not available in this environment and is gated off.
"""

from __future__ import annotations

import csv
import os
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import logger


class _Writer:
    def write_scalars(self, step: int, scalars: Dict[str, float]) -> None:  # pragma: no cover
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class CSVWriter(_Writer):
    """reference ``monitor/csv_monitor.py``: one CSV per metric name."""

    def __init__(self, output_path: str, job_name: str = "job"):
        self.dir = os.path.join(output_path or "csv_monitor", job_name)
        os.makedirs(self.dir, exist_ok=True)
        self._files = {}

    def write_scalars(self, step: int, scalars: Dict[str, float]) -> None:
        for name, value in scalars.items():
            fname = os.path.join(self.dir, name.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", name])
                w.writerow([step, float(value)])


class TensorBoardWriter(_Writer):
    def __init__(self, output_path: str, job_name: str = "job"):
        from torch.utils.tensorboard import SummaryWriter  # torch-cpu is baked in

        self.writer = SummaryWriter(log_dir=os.path.join(output_path or "tb_logs", job_name))

    def write_scalars(self, step: int, scalars: Dict[str, float]) -> None:
        for name, value in scalars.items():
            self.writer.add_scalar(name, float(value), step)

    def flush(self) -> None:
        self.writer.flush()

    def close(self) -> None:
        self.writer.close()


class WandbWriter(_Writer):
    def __init__(self, project: str, group: Optional[str] = None, team: Optional[str] = None):
        import wandb

        wandb.init(project=project, group=group, entity=team)
        self._wandb = wandb

    def write_scalars(self, step: int, scalars: Dict[str, float]) -> None:
        self._wandb.log(dict(scalars), step=step)


class MonitorMaster:
    """Fan-out writer (reference ``monitor/monitor.py:30``)."""

    def __init__(self, engine_config):
        self.writers: List[_Writer] = []
        if engine_config.csv_monitor.enabled:
            self.writers.append(
                CSVWriter(engine_config.csv_monitor.output_path, engine_config.csv_monitor.job_name)
            )
        if engine_config.tensorboard.enabled:
            try:
                self.writers.append(
                    TensorBoardWriter(engine_config.tensorboard.output_path, engine_config.tensorboard.job_name)
                )
            except Exception as e:
                logger.warning(f"tensorboard writer unavailable: {e}")
        if engine_config.wandb.enabled:
            try:
                self.writers.append(
                    WandbWriter(engine_config.wandb.project, engine_config.wandb.group, engine_config.wandb.team)
                )
            except Exception as e:
                logger.warning(f"wandb writer unavailable: {e}")

    @property
    def enabled(self) -> bool:
        return bool(self.writers)

    def write_scalars(self, step: int, scalars: Dict[str, float]) -> None:
        for w in self.writers:
            w.write_scalars(step, scalars)

    def flush(self) -> None:
        for w in self.writers:
            w.flush()
