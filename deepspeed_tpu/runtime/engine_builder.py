"""deepspeed_tpu.initialize() — the front door.

API parity with the reference ``deepspeed.initialize`` (``deepspeed/__init__.py:69``):
returns ``(engine, optimizer, training_dataloader, lr_scheduler)``. Dispatch to
the pipeline engine happens here when the model is a PipelineModule (reference
:209), mirroring the reference's selection logic.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from deepspeed_tpu.config.config import DeepSpeedTPUConfig
from deepspeed_tpu.runtime.engine import DeepSpeedTPUEngine
from deepspeed_tpu.runtime.model import ModelSpec, as_model_spec
from deepspeed_tpu.topology.mesh import build_mesh, get_data_parallel_world_size
from deepspeed_tpu.utils.logging import log_dist
from deepspeed_tpu.version import __version__


def initialize(
    args: Any = None,
    model: Any = None,
    optimizer: Any = None,
    model_parameters: Any = None,
    training_data: Any = None,
    lr_scheduler: Any = None,
    mesh: Any = None,
    dist_init_required: Optional[bool] = None,
    config: Any = None,
    config_params: Any = None,
    example_batch: Any = None,
    seed: Optional[int] = None,
) -> Tuple[DeepSpeedTPUEngine, Any, Any, Any]:
    """Create the training engine.

    model: ModelSpec, Flax module (with example_batch), or PipelineModule.
    optimizer: optional optax GradientTransformation (else from config).
    config: dict or path to JSON (``config_params`` accepted for parity).
    """
    log_dist(f"deepspeed_tpu {__version__} initialize", ranks=[0])
    if model is None:
        raise ValueError("model is required")
    if config is None:
        config = config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)
    if config is None:
        raise ValueError("config (dict or JSON path) is required")

    cfg = DeepSpeedTPUConfig(config)
    if mesh is None:
        # MiCS sub-grouping and any other config-driven mesh adjustments live
        # in the engine's mesh builder.
        mesh = DeepSpeedTPUEngine._build_engine_mesh(cfg)
    cfg = DeepSpeedTPUConfig(cfg.raw, dp_world_size=get_data_parallel_world_size(mesh))

    # Pipeline dispatch (reference __init__.py:209)
    from deepspeed_tpu.parallel.pipeline import PipelineModule  # local import: avoid cycle

    if isinstance(model, PipelineModule):
        from deepspeed_tpu.parallel.pipeline_engine import PipelineEngine

        engine = PipelineEngine(
            module=model,
            config=cfg,
            mesh=mesh,
            optimizer=optimizer,
            lr_scheduler=lr_scheduler,
            model_parameters=model_parameters,
            training_data=training_data,
            seed=seed,
        )
    else:
        spec = as_model_spec(model, example_batch=example_batch)
        engine = DeepSpeedTPUEngine(
            model=spec,
            config=cfg,
            mesh=mesh,
            optimizer=optimizer,
            lr_scheduler=lr_scheduler,
            model_parameters=model_parameters,
            training_data=training_data,
            seed=seed,
        )

    # Monitoring (reference engine.py:268 MonitorMaster)
    mc = cfg.model
    if mc.tensorboard.enabled or mc.csv_monitor.enabled or mc.wandb.enabled:
        from deepspeed_tpu.monitor.monitor import MonitorMaster

        engine.monitor = MonitorMaster(mc)

    return engine, getattr(engine, "tx", optimizer), engine.training_dataloader, lr_scheduler
