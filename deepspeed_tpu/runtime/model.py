"""Model abstraction handed to the engine.

The reference wraps a ``torch.nn.Module``; the TPU engine wraps a *pure
function pair* (init, loss). A Flax linen module whose ``__call__`` returns a
scalar loss (or ``(loss, aux)``) adapts directly — this matches the reference
convention where the client model's forward returns the loss
(``runtime/engine.py:2041`` forward → client module).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax


LossFn = Callable[[Any, Any, jax.Array], Any]  # (params, batch, rng) -> loss | (loss, aux)


@dataclasses.dataclass
class ModelSpec:
    """Pure-function model contract.

    init_fn(rng) -> params pytree
    loss_fn(params, batch, rng) -> scalar loss, or (loss, aux pytree)
    apply_fn(params, batch) -> model outputs (inference forward; optional)
    """

    init_fn: Callable[[jax.Array], Any]
    loss_fn: LossFn
    apply_fn: Optional[Callable[[Any, Any], Any]] = None
    name: str = "model"
    # Optional model-parallel placement rules (the AutoTP analog): maps a
    # parameter path string + shape to a PartitionSpec carrying e.g. 'tp'
    # entries, or None for default placement. ZeRO sharding composes on top.
    partition_rules: Optional[Callable[[str, tuple], Optional[Any]]] = None
    # Optional architecture config (e.g. TransformerConfig) so downstream
    # consumers (init_inference's training-engine path, the hybrid engine)
    # can rebuild an inference view without the caller re-passing it.
    model_config: Optional[Any] = None
    # Optional factory: rebuild this spec from an updated model_config. Set by
    # causal_lm_spec; used by the engine to honor DS-config flags that alter
    # the model's compiled graph (e.g. sparse_gradients -> sparse embedding
    # lookup) without the caller re-constructing the spec.
    rebuild: Optional[Callable[[Any], "ModelSpec"]] = None

    @property
    def transformer_config(self) -> Optional[Any]:
        """Alias read by ``init_inference`` when handed a training engine."""
        return self.model_config

    @classmethod
    def from_flax(
        cls,
        module,
        example_batch: Any,
        loss_output: bool = True,
        mutable: bool = False,
        name: Optional[str] = None,
    ) -> "ModelSpec":
        """Adapt a Flax linen module whose __call__(batch) returns loss/(loss, aux)."""

        def init_fn(rng):
            params_rng, dropout_rng = jax.random.split(rng)
            variables = module.init(
                {"params": params_rng, "dropout": dropout_rng}, example_batch, train=False
            )
            return variables["params"]

        def loss_fn(params, batch, rng):
            out = module.apply({"params": params}, batch, train=True, rngs={"dropout": rng})
            return out

        def apply_fn(params, batch):
            return module.apply({"params": params}, batch, train=False)

        if not loss_output:
            raise ValueError(
                "from_flax requires the module to return its loss; wrap it or "
                "construct ModelSpec directly with a custom loss_fn"
            )
        return cls(init_fn=init_fn, loss_fn=loss_fn, apply_fn=apply_fn, name=name or type(module).__name__)


def as_model_spec(model: Any, example_batch: Any = None) -> ModelSpec:
    if isinstance(model, ModelSpec):
        return model
    # Duck-type flax linen modules
    if hasattr(model, "init") and hasattr(model, "apply"):
        if example_batch is None:
            raise ValueError("example_batch is required to adapt a Flax module")
        return ModelSpec.from_flax(model, example_batch)
    raise TypeError(
        f"model must be a ModelSpec or a Flax module, got {type(model)}"
    )
