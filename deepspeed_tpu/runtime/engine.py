"""The training engine.

TPU-native analog of ``DeepSpeedEngine`` (reference ``runtime/engine.py:189``).
Where the reference wraps a torch module with Python-side hooks, streams, and
bucketed collectives, this engine compiles ONE SPMD program per train step:

  - master fp32 params + optimizer state placed per ZeRO stage (see zero.py)
  - micro-batch gradient accumulation via ``lax.scan`` (grad buffers sharded
    for stage >= 2, i.e. reduce-scatter per micro-batch)
  - mixed precision (bf16/fp16 compute, fp32 master) with a dynamic loss
    scaler and overflow-skip folded into the compiled step
  - gradient clipping by global norm
  - LR schedule evaluated inside the step

API parity: ``forward/backward/step`` (reference :2041/:2204/:2338) are
provided for drop-in ergonomics, and ``train_batch`` is the fused fast path
(one dispatch per global batch, as ``PipelineEngine.train_batch`` does).
"""

from __future__ import annotations

import functools
import os
import sys
import time
from typing import Any, Callable, Dict, Iterator, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.config.config import DeepSpeedTPUConfig
from deepspeed_tpu.runtime import zero as zero_mod
from deepspeed_tpu.runtime.lr_schedules import Schedule, constant_schedule, get_lr_schedule
from deepspeed_tpu.runtime.model import ModelSpec
from deepspeed_tpu.runtime.optimizers import get_optimizer
from deepspeed_tpu.runtime.precision import (
    LossScaleState,
    all_finite,
    cast_floating,
    clip_by_global_norm,
    global_norm,
    make_loss_scale_state,
    update_loss_scale,
)
from deepspeed_tpu.topology.mesh import (
    batch_pspec,
    build_mesh,
    get_data_parallel_world_size,
    set_mesh,
)
from deepspeed_tpu.telemetry.fleet import note_step as _fleet_note_step
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import ThroughputTimer

# Device-computed MoE dispatch gauges (parallel/moe.py gating stats): keys in
# the step metrics dict, monitor scalars, and registry gauges alike.
_MOE_METRIC_KEYS = ("moe/capacity_factor", "moe/token_drop_rate",
                    "moe/expert_load_balance", "moe/capacity_factor_applied")

# /metrics HTTP servers, one per configured port for the process lifetime
# (daemon threads over the process-global registry — engines come and go,
# the exposition endpoint stays; port 0 always binds a fresh free port).
_METRICS_SERVERS: dict = {}


# Fleet push clients, one per collector URL for the process lifetime (the
# registry and identity they push are process-global — a second engine with
# the same fleet_url must reuse the cadence thread, not double the traffic).
_FLEET_CLIENTS: dict = {}


def _get_fleet_client(url: str, interval_s: float):
    """Start (or reuse) the process-global fleet push client for ``url``."""
    from deepspeed_tpu.telemetry.collector import FleetClient

    client = _FLEET_CLIENTS.get(url)
    if client is not None:
        return client
    client = _FLEET_CLIENTS[url] = FleetClient(url)
    client.start(interval_s=interval_s)
    return client


def _get_metrics_server(port: int):
    """Start (or reuse) the process-global /metrics server for ``port``.
    Never raises — an unbindable port logs a warning and returns None."""
    from deepspeed_tpu import telemetry as telemetry_mod

    srv = _METRICS_SERVERS.get(port)
    if srv is not None and srv.port is not None:
        return srv
    try:
        srv = telemetry_mod.serve_metrics(port=port)
    except OSError as e:  # port taken by something that is not ours
        logger.warning(f"telemetry: could not bind /metrics on port {port}: {e}")
        return None
    if port != 0:  # every port-0 request gets its own fresh server
        _METRICS_SERVERS[port] = srv
    return srv


def _facade_grad_mean(g, live):
    """Mean-reduce an unsharded gradient leaf over the data axes through the
    comm facade: byte-identical ``lax.pmean`` lowering by default, but the
    ``collectives`` config block's routing (algorithmic/quantized/pallas
    remote-DMA backends) now reaches the shard_map grad paths (zeropp, LoCo,
    1-bit) — the GSPMD main step has no explicit collective to route. The
    loss pmean stays native: a scalar control value is never worth hops."""
    from deepspeed_tpu.comm import comm as comm_mod

    # A FORCED lossy wire reaches this path with NO error feedback (the
    # zeropp/LoCo/1-bit paths carry residuals; the plain grad mean does
    # not) — quantization error lands in the update every step. Warn once
    # (trace time only) and let the numerics wire probes, which see this
    # route via comm._observe_route, report the realized error.
    from deepspeed_tpu.collectives import selector as _coll_sel
    from deepspeed_tpu.telemetry import numerics as _numerics_mod

    _cfg = _coll_sel.get_config()
    _codec = getattr(_cfg, "facade_codec", None)
    # codec alone never routes — a lossy wire is live only when a facade
    # algorithm forces the grad mean off the native pmean lowering
    if (_codec in _numerics_mod.LOSSY_CODECS
            and getattr(_cfg, "facade_algorithm", None) not in (None, "lax")):
        _numerics_mod.warn_once(
            "facade_grad_mean_lossy",
            f"collectives: forced lossy codec {_codec!r} routes the "
            "shard_map grad mean-reductions WITHOUT error feedback "
            "(docs/collectives.md): quantization error accumulates into "
            "every update; enable numerics.enabled to measure the "
            "realized wire error (numerics/wire_rel_err)")
    return comm_mod.all_reduce(g, live, op="mean")


class TrainState(NamedTuple):
    """Entire training state — one pytree, placed once on the mesh."""

    step: jax.Array  # i32 global step (optimizer steps taken)
    params: Any  # fp32 master params
    opt_state: Any
    loss_scale: LossScaleState
    rng: jax.Array  # uint32 key data
    # 1-bit gradient compression error-feedback buffers (None unless
    # gradient_compression / a OneBit optimizer is active): per-dp-rank
    # residuals, leaves shaped [dp_world, *param.shape] sharded on dim 0
    # (reference runtime/comm/nccl.py worker_error).
    comm_error: Any = None
    # Training-health EMA state (diagnostics/health.py HealthState) — None
    # unless the diagnostics block enables in-step health probes, so the
    # disabled path compiles the identical program.
    health: Any = None
    # Cross-replica divergence-sentinel state (telemetry/numerics.py
    # NumericsState) — None unless the numerics block enables the in-jit
    # sentinel; same disabled-path identity contract as ``health``.
    numerics: Any = None


class DeepSpeedTPUEngine:
    """Training engine (reference ``DeepSpeedEngine`` runtime/engine.py:189)."""

    def __init__(
        self,
        model: ModelSpec,
        config: DeepSpeedTPUConfig,
        mesh: Optional[Mesh] = None,
        optimizer: Optional[optax.GradientTransformation] = None,
        lr_scheduler: Optional[Schedule] = None,
        model_parameters: Any = None,
        training_data: Any = None,
        seed: Optional[int] = None,
    ):
        self.model = model
        self.mesh = mesh if mesh is not None else self._build_engine_mesh(config)
        set_mesh(self.mesh)

        # Re-resolve the batch triad now that the true dp world is known.
        self.config = DeepSpeedTPUConfig(config.raw, dp_world_size=get_data_parallel_world_size(self.mesh))
        self.zero_config = self.config.zero_config
        self.compute_dtype = self.config.compute_dtype
        self.fp16 = self.config.fp16_enabled
        # Gradient-accumulation dtype (reference bf16_optimizer grad-accum
        # dtype knob): bf16.accumulate_grads_in_fp32=false carries the
        # micro-step accumulator in bf16 — half the grad-buffer HBM during
        # the scan; the optimizer math still runs fp32 (_update_math upcasts
        # at the accumulation boundary). fp16 keeps fp32 accumulation
        # (overflow detection semantics).
        bf16_cfg = self.config.model.bf16
        self._accum_dtype = (
            jnp.bfloat16
            if bf16_cfg.enabled and not bf16_cfg.accumulate_grads_in_fp32
            else jnp.float32)
        seed = seed if seed is not None else self.config.model.seed
        # resolved early: the step builders' closures read the overlap knob
        self._collectives_cfg = self.config.model.collectives
        self._configure_offload()

        # ---- optimizer + schedule ----------------------------------------
        self.lr_scheduler_fn, self._client_lr_scheduler = self._build_lr_schedule(lr_scheduler)
        if optimizer is not None:
            self.tx = optimizer
        else:
            opt_cfg = self.config.model.optimizer
            if opt_cfg is None:
                raise ValueError(
                    "No optimizer: pass an optax GradientTransformation to initialize() "
                    "or add an 'optimizer' section to the config"
                )
            self.tx, _ = get_optimizer(opt_cfg.type, opt_cfg.params, learning_rate=self.lr_scheduler_fn)

        # ZeRO++ knobs validate at construction (dead/lying knobs are worse
        # than errors); quantized collectives do not compose with the
        # split-backend offload step.
        if (self.config.model.prescale_gradients
                or self.config.model.gradient_predivide_factor != 1.0):
            # The compiled step computes the exact gradient mean inside ONE
            # fused program — there is no separate allreduce to pre/post-scale
            # around, so these knobs cannot change anything. Raising beats a
            # lying no-op (fp16 headroom is covered by dynamic loss scaling).
            raise NotImplementedError(
                "prescale_gradients / gradient_predivide_factor have no effect "
                "in the fused SPMD step; remove them (dynamic loss scaling "
                "handles fp16 overflow headroom)")
        self._zpp = self._zpp_config()
        if self._zpp and self.offload_mode in ("host-jit", "nvme"):
            raise NotImplementedError(
                "ZeRO++ quantized collectives (zero_quantized_weights/gradients) "
                "are not supported together with optimizer offload's split-"
                "backend step; drop one of the two"
            )
        self._onebit = self._onebit_config()

        # ---- sparse embedding gradients (must precede step compilation) --
        self._resolve_sparse_gradients()

        # ---- MoE dispatch gauges (must precede step compilation: the stats
        # are computed inside the jitted step) ------------------------------
        self._resolve_moe_metrics()
        # ---- capacity-factor autotuning (feeds on those gauges; must also
        # precede step compilation: it rebuilds the spec with the padded
        # static capacity ceiling the traced cutoff moves within) -----------
        self._resolve_moe_autotune()

        mcfg = getattr(self.model, "transformer_config", None)
        if (getattr(mcfg, "fpdt_offload", False)
                and int(np.prod(list(self.mesh.shape.values()))) > 1):
            raise NotImplementedError(
                "fpdt_offload on a multi-device mesh: XLA's SPMD partitioner "
                "rejects host-memory placement annotations (\"Side-effect HLO "
                "must have sharding\") in this version — run fpdt_offload "
                "single-chip, or use attn_impl='fpdt' without offload (or "
                "sp_impl='ring') for multi-chip long context")

        # MoE × TP (ISSUE 15): ep×tp meshes route the MoE block through the
        # explicit collective dispatch (parallel/moe.py collective_moe_apply
        # — the reference moe/mappings.py token gather/drop across the tp
        # group, with the [E, C, M] reshard as facade all_to_all over ep).
        # The old loud refusal is gone; an unservable shape (non-divisible
        # tokens/experts) still fails loudly at trace time inside
        # resolve_dispatch_mode rather than silently mis-routing.
        if (dict(self.mesh.shape).get("ep", 1) > 1
                and dict(self.mesh.shape).get("tp", 1) > 1
                and getattr(mcfg, "has_moe", False)):
            log_dist(
                f"MoE ep={self.mesh.shape['ep']} × tp={self.mesh.shape['tp']}: "
                "token dispatch/combine routed through the collective "
                "all_to_all (cross-tp gather/drop; moe_dispatch="
                f"{getattr(mcfg, 'moe_dispatch', 'auto')!r})", ranks=[0])

        # ---- pre-flight HBM-fit guard (BEFORE any device materialization:
        # an over-budget init on this platform wedges the device without
        # raising — round-5 relay incident) -------------------------------
        self._check_hbm_budget(mcfg)

        # ---- state init + placement --------------------------------------
        self._init_state(model_parameters, seed)

        # ---- diagnostics (before step compilation: the health probes trace
        # into the step and the recompile detector wraps the jitted fns) ----
        self._setup_diagnostics()

        # ---- numerics observatory (after diagnostics: the drift/divergence
        # alarms arm its profiler capture; before step compilation: the
        # divergence sentinel traces into the step) -----------------------
        self._setup_numerics()

        # ---- elastic snapshots (checkpoint/snapshot.py): cadenced async
        # sharded saves off the step clock; restore works onto any mesh ----
        self.snapshot_manager = None
        if self.config.model.snapshot.enabled:
            from deepspeed_tpu.checkpoint.snapshot import SnapshotManager

            self.snapshot_manager = SnapshotManager(self, self.config.model.snapshot)

        # ---- data --------------------------------------------------------
        self.training_dataloader = None
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(training_data)

        # ---- compiled steps ----------------------------------------------
        if self.offload_mode in ("host-jit", "nvme"):
            # Split program: device grad accumulation + compiled host update
            # (the DeepSpeedCPUAdam analog). ``_train_step`` stays None.
            self._train_step = None
            self._offload_grad_step = self._wrap_jit(
                "offload_grad_step", self._build_offload_grad_step(),
                ("params", "batch", "scale", "rng"))
            if self._twin_ratio is not None:
                self._build_twin_flow_steps()
            else:
                self._offload_update_step = self._wrap_jit(
                    "offload_update_step", self._build_offload_update_step(),
                    ("state", "grads"))
        else:
            self._train_step = self._wrap_jit(
                "train_step", self._build_train_step(), ("state", "batch"))
        self._grad_step = None  # built lazily for the forward/backward/step path
        self._apply_step = None
        self._eval_step = None
        self._pending_grads = None
        self._pending_losses: list = []
        self._micro_steps = 0

        # wall_clock_breakdown (reference engine timers): the fused TPU step
        # has no separable fwd/bwd/step phases, so the honest analog is a
        # per-step wall-clock window (note: with async dispatch an individual
        # window captures dispatch; true device rates appear at sync points)
        self.throughput_timer = ThroughputTimer(
            batch_size=self.config.train_batch_size,
            steps_per_output=(1 if self.config.model.wall_clock_breakdown
                              else self.config.model.steps_per_print),
        )
        self.losses = None
        self.monitor = None  # wired by engine_builder when monitoring configured
        # Host-side batch counter: drives print/profile gating and monitor
        # x-axis without reading device state (``int(self.state.step)`` blocks
        # the dispatch pipeline — the round-2 verdict's per-step-sync finding).
        # Equal to ``global_steps`` except under fp16 overflow skips.
        self._batch_count = 0
        # Buffered monitor writes: (batch_idx, device-metrics) pairs fetched in
        # one bulk transfer at flush time so logging never stalls the step.
        self._monitor_pending: list = []

        from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler

        self.flops_profiler = FlopsProfiler(engine=self)
        if self.config.model.memory_breakdown:
            # reference engine.py:257 logs phased see_memory_usage when the
            # memory_breakdown knob is set
            from deepspeed_tpu.utils.memory import see_memory_usage

            see_memory_usage("engine state initialized", force=True)
        if self.config.model.comms_logger.enabled:
            # reference comm/config.py CommsConfig -> comm logger wiring
            from deepspeed_tpu.comm import comm as comm_mod

            cl = self.config.model.comms_logger
            comm_mod.configure(enabled=True, verbose=cl.verbose, debug=cl.debug)
        # Telemetry (telemetry/): the config block configures the process-
        # global tracer; the engine keeps a direct handle for its hot-path
        # spans. When the block is absent the env var (DSTPU_TELEMETRY=1) may
        # still have enabled the tracer — every span call is a single
        # attribute check when it hasn't.
        from deepspeed_tpu import telemetry as telemetry_mod

        tcfg = self.config.model.telemetry
        self._metrics_server = None
        if tcfg.enabled:
            telemetry_mod.configure(
                enabled=True, sync_spans=tcfg.sync_spans,
                max_events=tcfg.max_events,
                memory_watermarks=tcfg.memory_watermarks,
                trace_path=tcfg.trace_path, jsonl_path=tcfg.jsonl_path,
                prometheus_path=tcfg.prometheus_path)
            # the process-global program registry follows the tracer unless
            # pinned; honor this engine's knob (last-constructed wins, the
            # collectives-selector convention)
            from deepspeed_tpu.telemetry import programs as programs_mod

            programs_mod.configure(enabled=None if tcfg.programs else False)
            if tcfg.http_port is not None:
                # scrapeable /metrics for the whole registry (training scalars
                # ride the same exposition the serving SLO metrics use). The
                # server is PROCESS-global state like the tracer it exposes:
                # one per configured port, reused by later engines (tests
                # build dozens; a second bind would EADDRINUSE).
                self._metrics_server = _get_metrics_server(tcfg.http_port)
                if self._metrics_server is not None:
                    log_dist(
                        f"telemetry: /metrics on port {self._metrics_server.port}",
                        ranks=[0])
        # Incident plane (telemetry/events.py + alerts.py): size the typed
        # event ring and wire the JSONL export next to the trace stream;
        # the alert engine's daemon-thread evaluation is its own opt-in.
        from deepspeed_tpu.telemetry import events as events_mod

        events_mod.configure_events(
            capacity=tcfg.events_capacity,
            dedup_window_s=tcfg.events_dedup_window_s,
            jsonl_path=(tcfg.events_jsonl_path
                        if tcfg.events_jsonl_path is not None
                        else (os.path.join(
                            telemetry_mod.default_output_dir(),
                            "event_log.jsonl") if tcfg.enabled else None)))
        self._alert_engine = None
        if tcfg.alerts_enabled:
            from deepspeed_tpu.telemetry import alerts as alerts_mod

            self._alert_engine = alerts_mod.configure_alerts(
                jsonl_path=tcfg.alerts_jsonl_path,
                webhook_url=tcfg.alerts_webhook_url,
                interval_s=tcfg.alerts_interval_s)
        self._fleet_client = None
        if tcfg.fleet_url:
            # fleet federation: register with the collector (identity +
            # clock handshake) and push snapshots/heartbeats on a daemon
            # cadence — push failures never reach the training step. The
            # client is PROCESS-global per URL like the /metrics server:
            # engines come and go, one cadence thread pushes the one
            # process-global registry.
            from deepspeed_tpu.telemetry import fleet as fleet_mod

            if tcfg.fleet_role is not None:
                fleet_mod.configure_identity(role=tcfg.fleet_role)
            self._fleet_client = _get_fleet_client(
                tcfg.fleet_url, tcfg.fleet_push_interval_s)
        self._tracer = telemetry_mod.get_tracer()
        # Collectives (collectives/): install the selector tunables so comm
        # facade calls with algorithm="auto" (and the zeropp overlap knob)
        # follow this engine's config. Process-global like the tracer;
        # disabled leaves the facade on the plain jax.lax lowering.
        ccfg = self._collectives_cfg
        from deepspeed_tpu.collectives import selector as coll_selector

        self._coll_observatory = None
        if not ccfg.enabled:
            # the selector is process-global: a disabled engine must restore
            # the plain-lax defaults or it would inherit a previous engine's
            # facade routing (the config block promises "disabled => the
            # compiled program is unchanged"). Last-constructed engine wins —
            # warn when this strips routing a live enabled engine installed.
            if coll_selector.get_config().facade_algorithm is not None:
                logger.warning(
                    "collectives: resetting process-global facade routing "
                    "installed by a previously constructed engine; set "
                    "collectives.enabled in this engine's config to keep it")
            coll_selector.configure()
            from deepspeed_tpu.collectives import fused_gemm as _fused_gemm

            _fused_gemm.configure(enabled=False)
        else:
            # Facade defaults inject ppermute hops into EVERY default-routed
            # collective — including ones traced inside partial-manual
            # shard_map regions (data axes manual, model axes auto), where
            # ppermute hard-fails on this jax 0.4.37/XLA (PartitionId
            # unsupported — see utils/compat.py). With nontrivial model
            # axes, keep the selector tunables (explicit algorithm= calls
            # still work in full-manual regions) but leave default routing
            # on the lax lowering.
            model_axes = [a for a in self.mesh.axis_names
                          if a not in ("dp", "fsdp") and self.mesh.shape[a] > 1]
            facade_alg = ccfg.algorithm
            if model_axes and facade_alg not in (None, "lax"):
                logger.warning(
                    f"collectives: mesh has nontrivial model axes {model_axes} "
                    f"(partial-manual shard_map regions; ppermute unsupported "
                    f"there on this jax/XLA) — facade default routing stays on "
                    f"the lax lowering; pass algorithm= explicitly inside "
                    f"full-manual regions instead")
                facade_alg = None
            ocfg = ccfg.observe
            decision_table = ccfg.decision_table
            if ocfg.enabled and not decision_table and ccfg.mode != "model":
                # warm-start measured mode from the table a previous run's
                # observatory persisted (collectives/observatory.py): the
                # online rows ARE sweep-schema rows, so the selector consumes
                # them exactly like a `benchmark --sweep` table
                from deepspeed_tpu.collectives import observatory as coll_obs

                # resolve THIS engine's path: the process-global observatory
                # still holds the previous engine's config at this point
                _table = ocfg.table_path or coll_obs.default_table_path()
                if os.path.exists(_table):
                    decision_table = _table
                    log_dist(f"collectives: warm-starting measured mode from "
                             f"the observatory table {_table}", ranks=[0])
            coll_selector.configure(
                mode=ccfg.mode, alpha_us=ccfg.alpha_us,
                beta_us_per_mb=ccfg.beta_us_per_mb,
                codecs=tuple(ccfg.codecs), block_size=ccfg.block_size,
                decision_table=decision_table,
                min_quant_bytes=ccfg.min_quant_bytes,
                min_algorithmic_bytes=ccfg.min_algorithmic_bytes,
                pallas_alpha_scale=ccfg.pallas_alpha_scale,
                compiled_search=ccfg.compiled_search,
                facade_algorithm=facade_alg,
                # "auto" = no forced codec: the selector picks among `codecs`;
                # a concrete name (incl. "none") pins that wire
                facade_codec=ccfg.codec if ccfg.codec != "auto" else None)
            # in-kernel compute-collective fusion (collectives/fused_gemm):
            # process-global knob like the selector; the zeropp sharded
            # matmuls and tp helpers consult it at trace time
            from deepspeed_tpu.collectives import fused_gemm as _fused_gemm

            _fused_gemm.configure(enabled=ccfg.fused_gemm_collectives)
            if ocfg.enabled:
                from deepspeed_tpu.collectives import observatory as coll_obs

                obs = coll_obs.configure(
                    enabled=True, sample_every=ocfg.sample_every,
                    probes_per_sample=ocfg.probes_per_sample,
                    iters=ocfg.iters, warmup=ocfg.warmup,
                    probe_alternatives=ocfg.probe_alternatives,
                    async_compile=ocfg.async_compile,
                    table_path=ocfg.table_path, persist=ocfg.persist,
                    ema=ocfg.ema, drift_ratio=ocfg.drift_ratio,
                    refit_every=ocfg.refit_every, fit_decay=ocfg.fit_decay,
                    max_probe_mb=ocfg.max_probe_mb,
                    max_programs=ocfg.max_programs)
                # drift arms the anomaly profiler capture when diagnostics
                # wired one (diagnostics are built before this section)
                pc = (self.diagnostics.profiler_capture
                      if self.diagnostics is not None else None)
                obs.install(mesh=self.mesh,
                            profiler_arm=pc.arm if pc is not None else None)
                self._coll_observatory = obs
        if self._coll_observatory is None:
            # observatory hygiene (process-global, like the selector reset
            # above): an engine that does not enable it must not inherit a
            # previous engine's probes/routes — but only when some earlier
            # engine actually imported+enabled the module
            _obs_mod = sys.modules.get("deepspeed_tpu.collectives.observatory")
            if _obs_mod is not None and _obs_mod.enabled():
                _obs_mod.configure(enabled=False)
        if self.config.model.dump_state:
            # reference engine.py dump_state: print the resolved config once
            log_dist(f"engine config: {self.config.model.model_dump()}", ranks=[0])
        log_dist(
            f"engine ready: mesh={dict(self.mesh.shape)} zero_stage={self.zero_config.stage} "
            f"dtype={self.compute_dtype.__name__} batch={self.config.train_batch_size} "
            f"micro={self.config.train_micro_batch_size_per_gpu} gas={self.config.gradient_accumulation_steps}",
            ranks=[0],
        )

    # ------------------------------------------------------------------ init
    def _resolve_sparse_gradients(self) -> None:
        """Honor ``sparse_gradients: true`` (reference runtime/sparse_tensor.py:69
        + engine sparse-grad allreduce paths, engine.py:2104): when the size
        heuristic says sparse sync wins, rebuild the model spec with the
        sparse-backward embedding lookup (``runtime/sparse_grad.sparse_lookup``)
        so the compiled step all-gathers compact (ids, rows) pairs instead of
        psum-ing the dense [V, H] embedding gradient."""
        if not self.config.model.sparse_gradients:
            return
        from deepspeed_tpu.runtime.sparse_grad import should_use_sparse_embedding_grad

        def keep_dense(why: str) -> None:
            log_dist(f"sparse_gradients: dense embedding-grad sync kept — {why}",
                     ranks=[0])

        mcfg = getattr(self.model, "transformer_config", None)
        if mcfg is None:
            return keep_dense("model spec carries no transformer_config")
        tokens = self.config.train_batch_size * mcfg.max_seq_len
        if not should_use_sparse_embedding_grad(mcfg.vocab_size, tokens):
            return keep_dense(
                f"heuristic: vocab={mcfg.vocab_size} vs global batch tokens "
                f"<={tokens}; sparse rows would not shrink the wire")
        if getattr(mcfg, "tie_embeddings", False):
            return keep_dense("tie_embeddings: the tied LM head grad is dense anyway")
        if getattr(mcfg, "sparse_embedding_grads", False):
            log_dist("sparse_gradients: model already built with sparse "
                     "embedding grads", ranks=[0])
            return
        if self.model.rebuild is None:
            return keep_dense(
                "model spec has no rebuild hook; construct the model with "
                "TransformerConfig(sparse_embedding_grads=True) to opt in")
        import dataclasses as _dc

        self.model = self.model.rebuild(
            _dc.replace(mcfg, sparse_embedding_grads=True))
        log_dist(
            f"sparse_gradients: sparse embedding-grad sync ENGAGED "
            f"(vocab={mcfg.vocab_size}, global batch tokens<={tokens}) — "
            "backward all-gathers (ids, rows) pairs, no dense [V, H] psum",
            ranks=[0])

    def _resolve_moe_metrics(self) -> None:
        """With telemetry on and an MoE model, rebuild the spec with
        ``moe_metrics=True`` so the gating math also emits its dispatch
        stats (capacity occupancy, token drops, expert load balance — ROADMAP
        item 4's instrumentation). The stats ride the step's metrics dict as
        ``moe/*`` scalars: device-computed, fetched only at the existing
        monitor/print sync points. Telemetry off ⇒ untouched spec ⇒
        byte-identical program."""
        self._moe_metrics = False
        if not self.config.model.telemetry.enabled:
            return
        mcfg = getattr(self.model, "transformer_config", None)
        if mcfg is None or not getattr(mcfg, "has_moe", False):
            return
        if self._zpp or self._onebit or self.offload_mode in ("host-jit", "nvme"):
            # those step builders compute their losses inside their own
            # micro fns — the stats side channel is not threaded through.
            # A silently-dead gauge is worse than a log line.
            log_dist(
                "moe metrics: not wired into the zero++/1-bit/offload step "
                "builders; moe/* gauges stay absent for this engine", ranks=[0])
            return
        if int(self.mesh.shape.get("pp", 1)) > 1:
            # the pipelined loss threads a scalar aux through the pp ring;
            # the stats dict can't ride it (pipelined_causal_lm_loss raises)
            log_dist("moe metrics: skipped on pp>1 meshes (stats side channel "
                     "not threaded through the pipeline ring)", ranks=[0])
            return
        if getattr(mcfg, "moe_metrics", False):
            self._moe_metrics = True
            return
        if self.model.rebuild is None:
            log_dist(
                "moe metrics: model spec has no rebuild hook; construct with "
                "TransformerConfig(moe_metrics=True) to opt in", ranks=[0])
            return
        import dataclasses as _dc

        self.model = self.model.rebuild(_dc.replace(mcfg, moe_metrics=True))
        self._moe_metrics = True
        log_dist("moe metrics: dispatch gauges ENGAGED "
                 "(moe/capacity_factor|token_drop_rate|expert_load_balance)",
                 ranks=[0])

    def _resolve_moe_autotune(self) -> None:
        """Arm the host-side capacity-factor controller (``moe_autotune``
        config block): the model spec is rebuilt with
        ``moe_capacity_factor_max = max_factor`` so every capacity array is
        padded to the static ceiling and the gate's drop cutoff follows a
        traced scalar (batch key ``moe_capacity_factor``); the controller
        then nudges that scalar between steps from the ``moe/*`` gauges it
        reads at the existing ``steps_per_print`` fetch — never a recompile,
        never an extra device sync."""
        self._moe_autotune = None
        self._moe_cap_leaf = None
        self._moe_cap_leaf_value = None
        cfg = self.config.model.moe_autotune
        if not cfg.enabled:
            return
        # bad bounds are a config error regardless of whether the controller
        # can arm — report them before any disarm path goes quiet
        if not (0 < cfg.min_factor <= cfg.max_factor):
            raise ValueError(
                f"moe_autotune: need 0 < min_factor <= max_factor, got "
                f"[{cfg.min_factor}, {cfg.max_factor}]")
        if not getattr(self, "_moe_metrics", False):
            # the gauges ARE the controller's sensor; every reason metrics
            # are unavailable (telemetry off, dense model, pp>1, zero++/
            # 1-bit/offload step builders) disables autotuning with it
            log_dist("moe_autotune: requires the moe/* dispatch gauges "
                     "(telemetry enabled + an MoE model on a non-pp mesh, "
                     "fused/zero step builders); controller disarmed", ranks=[0])
            return
        import dataclasses as _dc

        mcfg = self.model.transformer_config
        if not mcfg.moe_drop_tokens:
            log_dist("moe_autotune: drop_tokens=False has no capacity bound "
                     "to tune; controller disarmed", ranks=[0])
            return
        # the ceiling must never SHRINK the capacity below the static factor
        # the model was tuned with — arming the controller may only add
        # headroom, so the padded bound is max(max_factor, configured)
        ceiling = max(float(cfg.max_factor), float(mcfg.moe_capacity_factor))
        if ceiling > cfg.max_factor:
            log_dist(
                f"moe_autotune: max_factor={cfg.max_factor} below the "
                f"configured moe_capacity_factor={mcfg.moe_capacity_factor}; "
                f"raising the ceiling to {ceiling} (the controller never "
                "clamps a model below its static factor)", ranks=[0])
        if getattr(mcfg, "moe_capacity_factor_max", None) != ceiling:
            if self.model.rebuild is None:
                log_dist("moe_autotune: model spec has no rebuild hook; set "
                         "TransformerConfig(moe_capacity_factor_max=...) to "
                         "opt in", ranks=[0])
                return
            self.model = self.model.rebuild(
                _dc.replace(mcfg, moe_capacity_factor_max=ceiling))
            mcfg = self.model.transformer_config
        self._moe_autotune = cfg
        self._moe_cap_max = ceiling
        # the knob starts at the configured static factor, clipped in-bounds
        self._moe_cap_factor = float(
            min(max(mcfg.moe_capacity_factor, cfg.min_factor), ceiling))
        log_dist(
            f"moe_autotune: capacity-factor controller ENGAGED (start="
            f"{self._moe_cap_factor:.3f}, bounds=[{cfg.min_factor}, "
            f"{ceiling}], target_drop={cfg.target_drop_rate}, "
            f"cadence=every {self.config.model.steps_per_print} steps)",
            ranks=[0])

    def _moe_autotune_batch_key(self, placed):
        """Thread the controller's knob into the placed batch: a replicated
        ``[gas]`` fp32 leaf (one scalar per micro-step, so it rides the
        micro scan like every other leaf). Shape/dtype/sharding are
        identical every step — only the VALUE moves, the jit cache holds
        one program."""
        if self._moe_autotune is None or not isinstance(placed, dict):
            return placed
        leaf = self._moe_cap_leaf
        if leaf is None or self._moe_cap_leaf_value != self._moe_cap_factor:
            # the leaf only changes at controller ticks (steps_per_print
            # cadence) — cache the placed array so steady-state steps pay
            # no per-step host->device transfer for an unchanged knob
            gas = self.config.gradient_accumulation_steps
            leaf = jax.device_put(
                jnp.full((gas,), self._moe_cap_factor, jnp.float32),
                NamedSharding(self.mesh, PartitionSpec()))
            self._moe_cap_leaf = leaf
            self._moe_cap_leaf_value = self._moe_cap_factor
        placed = dict(placed)
        placed["moe_capacity_factor"] = leaf
        return placed

    def _moe_autotune_update(self, fetched: Dict[str, Any]) -> None:
        """One controller tick from the freshly fetched step metrics:
        drops above target raise the effective factor (fast), a balanced
        no-drop dispatch lowers it (slow decay) — always inside
        ``[min_factor, max_factor]``."""
        cfg = self._moe_autotune
        drop = fetched.get("moe/token_drop_rate")
        balance = fetched.get("moe/expert_load_balance")
        if drop is None:
            return
        drop = float(drop)
        prev = self._moe_cap_factor
        if drop > cfg.target_drop_rate:
            self._moe_cap_factor = min(prev + cfg.increase_step,
                                       self._moe_cap_max)
        elif balance is not None and float(balance) <= cfg.balance_threshold:
            self._moe_cap_factor = max(prev - cfg.decrease_step, cfg.min_factor)
        if self._tracer.enabled:
            # the controller's own breadcrumbs next to the gate gauges it
            # feeds on (moe/capacity_factor_applied confirms arrival)
            self._tracer.registry.gauge("moe/capacity_factor_target").set(
                self._moe_cap_factor)
        if self._moe_cap_factor != prev:
            log_dist(
                f"moe_autotune: drop_rate={drop:.4f} balance="
                f"{float(balance) if balance is not None else -1.0:.3f} -> "
                f"capacity factor {prev:.3f} -> {self._moe_cap_factor:.3f}",
                ranks=[0])

    def _configure_offload(self) -> None:
        """Resolve the ZeRO-Offload/Infinity mode from the config.

        Reference wiring: ``zero/stage3.py:2082`` (optimizer swap into the
        step) + ``swap_tensor/partitioned_optimizer_swapper.py:29`` +
        ``zero/offload_config.py``. TPU-native modes:

        - ``host-jit``: fp32 master + moments live committed to the host CPU
          backend; the optimizer update itself runs as a compiled CPU program
          (the DeepSpeedCPUAdam analog) and only bf16 compute params return to
          the accelerator. Used whenever a ``cpu`` JAX backend coexists with
          the accelerator (and always on CPU test meshes).
        - ``memories``: no CPU backend available (e.g. JAX_PLATFORMS pins the
          TPU plugin only) — master/opt shardings get
          ``memory_kind='pinned_host'`` and stay inside the ONE compiled step;
          XLA inserts the H2D/D2H streams (its latency-hiding scheduler
          overlaps them with compute).
        - ``nvme``: host-jit plus the AIO swapper — moments are written to
          disk after the update (async) and prefetched before the next one
          (ZeRO-Infinity; reference partitioned_optimizer_swapper).
        """
        self._offload_cfg = self.zero_config.offload_optimizer
        self._offload_param_cfg = self.zero_config.offload_param
        self.offload_mode: Optional[str] = None
        self._host_device = None
        self._opt_swapper = None
        self._twin_ratio: Optional[float] = None
        dev = self._offload_cfg.device if self._offload_cfg else "none"
        param_dev = self._offload_param_cfg.device if self._offload_param_cfg else "none"
        if dev not in ("cpu", "nvme"):
            if param_dev in ("cpu", "nvme"):
                # Param-only offload (reference supports it standalone): the
                # split path hosts the fp32 masters either way, so honor the
                # request by enabling it — moments ride along to the host,
                # a superset of the asked-for device-memory saving.
                log_dist(
                    "offload_param set without offload_optimizer: hosting fp32 "
                    "masters AND moments off-device (superset of the request)",
                    ranks=[0],
                )
                dev = "cpu"
            else:
                return
        try:
            self._host_device = jax.devices("cpu")[0]
        except Exception:
            self._host_device = None
        if dev == "nvme":
            if self._host_device is None:
                raise ValueError("offload_optimizer device='nvme' needs a host CPU backend for the update step")
            folder = self._offload_cfg.nvme_path if self._offload_cfg else None
            if not folder:
                # the reference requires nvme_path too; a shared default would
                # let concurrent jobs clobber each other's swapped moments
                raise ValueError("offload_optimizer device='nvme' requires 'nvme_path' in the config")
            from deepspeed_tpu.runtime.swap_tensor import OptimizerStateSwapper

            self._opt_swapper = OptimizerStateSwapper(os.path.join(folder, "opt_state"))
            self.offload_mode = "nvme"
        elif self._host_device is not None:
            self.offload_mode = "host-jit"
        else:
            self.offload_mode = "memories"
        # Twin-Flow partial offload (reference ZeRO-Offload++,
        # blogs/deepspeed-offloadpp: ``offload_optimizer.ratio`` = fraction of
        # parameters whose optimizer step runs on the CPU side; the rest
        # update on-accelerator and skip the host round-trip entirely).
        ratio = float(self._offload_cfg.ratio) if self._offload_cfg else 1.0
        self._twin_ratio = None
        if ratio > 1.0:
            raise ValueError(f"offload_optimizer.ratio={ratio}: must be in (0, 1]")
        if ratio < 1.0:
            if not 0.0 < ratio:
                raise ValueError(
                    f"offload_optimizer.ratio={ratio}: must be in (0, 1] — "
                    "for a fully on-device optimizer drop the offload_optimizer "
                    "section instead of ratio<=0")
            if self.offload_mode != "host-jit":
                raise ValueError(
                    f"offload_optimizer.ratio={ratio} (Twin-Flow partial offload) "
                    f"requires the host-jit cpu offload mode; mode={self.offload_mode!r} "
                    "(nvme swaps the whole state; 'memories' has no split step)")
            if self._offload_param_cfg and self._offload_param_cfg.device != "none":
                raise NotImplementedError(
                    "offload_param does not compose with Twin-Flow partial "
                    "optimizer offload (ratio < 1): param offload clears the "
                    "device bf16 copy every step, which the partial path keeps "
                    "resident — use ratio=1.0 with offload_param")
            self._twin_ratio = ratio
            if self._accum_dtype == jnp.bfloat16:
                # A silently-dead knob is worse than a warning (the
                # prescale_gradients stance): Twin-Flow's stats/partition
                # programs require fp32 gradients, so the bf16-accumulation
                # request cannot be honored on this path.
                logger.warning(
                    "bf16.accumulate_grads_in_fp32=false is ignored with "
                    f"Twin-Flow partial offload (offload_optimizer.ratio={ratio}): "
                    "the split stats/partition programs accumulate gradients in "
                    "fp32 — the host gradient transfer is NOT halved. Drop the "
                    "knob, or use ratio=1.0 (full offload) to keep bf16 "
                    "accumulation.")
        log_dist(
            f"ZeRO-Offload enabled: mode={self.offload_mode} device={dev}"
            + (f" twin_flow_ratio={ratio}" if self._twin_ratio is not None else ""),
            ranks=[0])

    # --------------------------------------------------------- diagnostics
    def _setup_diagnostics(self) -> None:
        """Build the DiagnosticsManager (``diagnostics`` config block) and
        fold the health-probe EMA state into the train state.

        Runs AFTER ``_init_state`` (it extends state/state_sharding) and
        BEFORE step compilation (the probes trace into the step; the
        recompile detector wraps the jitted callables). Disabled => the
        engine keeps ``diagnostics = None``, ``state.health = None``, and
        compiles a program identical to the no-diagnostics build."""
        self.diagnostics = None
        self._health = None
        dcfg = self.config.model.diagnostics
        if not dcfg.enabled:
            return
        from deepspeed_tpu.diagnostics.manager import DiagnosticsManager

        self.diagnostics = DiagnosticsManager(dcfg, fp16=self.fp16)
        if self._twin_ratio is not None and self.diagnostics.health is not None:
            # A silently-dead knob is worse than a warning (the
            # prescale_gradients stance): the Twin-Flow split update bypasses
            # the shared update math the probes live in.
            logger.warning(
                "diagnostics.health is not wired into the Twin-Flow split "
                "update (offload_optimizer.ratio < 1): health probes disabled "
                "for this engine; recompile/step-time/flight-recorder stay on")
            self.diagnostics.health = None
        self._health = self.diagnostics.health
        if self._health is not None:
            sh = self._health_sharding()
            hstate = jax.device_put(self._health.init_state(), sh)
            self.state = self.state._replace(health=hstate)
            self.state_sharding = self.state_sharding._replace(
                health=jax.tree_util.tree_map(lambda _: sh, hstate))
        if self.diagnostics.flight_recorder is not None:
            self.diagnostics.flight_recorder.set_context(
                mesh=dict(self.mesh.shape),
                zero_stage=self.zero_config.stage,
                dtype=self.compute_dtype.__name__,
                train_batch_size=self.config.train_batch_size,
                gradient_accumulation_steps=self.config.gradient_accumulation_steps,
                offload_mode=self.offload_mode,
            )
        log_dist(
            "diagnostics enabled: health="
            + (",".join(f"{s}={p}" for s, p in self._health.policies.items())
               if self._health else "off")
            + f" recompile={dcfg.recompile.enabled}"
            + f" step_time={dcfg.step_time.enabled}"
            + f" flight_recorder={dcfg.flight_recorder.enabled}",
            ranks=[0])

    def _health_sharding(self):
        """Placement of the health-probe EMA state (host-committed on the
        split offload paths, replicated on the mesh otherwise)."""
        if self.offload_mode in ("host-jit", "nvme"):
            from jax.sharding import SingleDeviceSharding

            return SingleDeviceSharding(self._host_device)
        return NamedSharding(self.mesh, PartitionSpec())

    def reset_health(self) -> None:
        """Re-arm the health monitor: fresh EMA baselines in ``state.health``.

        Called by the auto-recovery loop after a rewind — the restored run
        re-warms its spike statistics instead of being judged against the
        baselines that led up to the abort. No-op when health probes are off.
        """
        if self._health is None or self.state.health is None:
            return
        self.state = self.state._replace(
            health=jax.device_put(self._health.init_state(), self._health_sharding()))

    # ---------------------------------------------------- numerics observatory
    def _setup_numerics(self) -> None:
        """Configure the process-global numerics observatory (``numerics``
        config block) and fold the divergence-sentinel state into the train
        state. Runs AFTER ``_setup_diagnostics`` (drift/divergence arm its
        profiler capture) and BEFORE step compilation (the sentinel traces
        into the step). Disabled => ``state.numerics = None`` and the
        compiled program is identical to the no-numerics build."""
        self._numerics = None
        self._numerics_sentinel = None
        ncfg = self.config.model.numerics
        if not ncfg.enabled:
            # process-global hygiene (selector/observatory precedent): an
            # engine that does not enable it must not inherit a previous
            # engine's routes or alarms
            _num_mod = sys.modules.get("deepspeed_tpu.telemetry.numerics")
            if _num_mod is not None and _num_mod.enabled():
                _num_mod.configure(enabled=False)
            return
        from deepspeed_tpu.telemetry import numerics as numerics_mod

        obs = numerics_mod.configure(
            enabled=True, sample_every=ncfg.sample_every,
            sentinel=ncfg.sentinel,
            sentinel_sample_every=ncfg.sentinel_sample_every,
            divergence_policy=ncfg.divergence_policy,
            max_probe_elems=ncfg.max_probe_elems,
            drift_ratio=ncfg.drift_ratio,
            spec_accept_window=ncfg.spec_accept_window,
            spec_accept_mads=ncfg.spec_accept_mads,
            spec_accept_min_n=ncfg.spec_accept_min_n)
        pc = (self.diagnostics.profiler_capture
              if self.diagnostics is not None else None)
        obs.install(profiler_arm=pc.arm if pc is not None else None)
        self._numerics = obs
        sentinel_on = ncfg.sentinel
        if sentinel_on and self.offload_mode in ("host-jit", "nvme"):
            # the digest shard_map needs the device mesh; the split-offload
            # update runs on the host backend (Twin-Flow health precedent:
            # a silently-dead knob is worse than a warning)
            logger.warning(
                "numerics.sentinel is not wired into the host-offload "
                "update paths (offload device=cpu/nvme): divergence "
                "sentinel disabled for this engine; wire/serving probes "
                "stay on")
            sentinel_on = False
        if sentinel_on:
            specs = jax.tree_util.tree_map(
                lambda sh: getattr(sh, "spec", PartitionSpec()),
                self.param_sharding)
            self._numerics_sentinel = numerics_mod.DivergenceSentinel(
                self.mesh, specs,
                sample_every=ncfg.sentinel_sample_every)
            rep = NamedSharding(self.mesh, PartitionSpec())
            nstate = jax.device_put(
                numerics_mod.DivergenceSentinel.init_state(), rep)
            self.state = self.state._replace(numerics=nstate)
            self.state_sharding = self.state_sharding._replace(
                numerics=jax.tree_util.tree_map(lambda _: rep, nstate))
        log_dist(
            f"numerics observatory enabled: sample_every={ncfg.sample_every} "
            f"sentinel={'on' if self._numerics_sentinel is not None else 'off'}"
            f" (every {ncfg.sentinel_sample_every})"
            f" policy={ncfg.divergence_policy}",
            ranks=[0])

    def _numerics_on_step(self, step: int) -> None:
        """Sampled host plane of the numerics observatory: standalone wire
        probes, LoCo EF-residual gauges, and the sentinel's divergence fold
        (policy ``log`` | ``abort``). The sentinel's event counter is
        LATCHED in the carried state, so a host check can never miss a
        detection — only see it a sample late."""
        nm = self._numerics
        nm.on_step(step)
        ncfg = self.config.model.numerics
        st = self.state
        if st.numerics is not None:
            every = max(1, int(ncfg.sentinel_sample_every))
            # batch N runs the device probe at pre-increment step N-1
            if (step - 1) % every == 0:
                events, checksum = jax.device_get(
                    (st.numerics.events, st.numerics.checksum))
                new = nm.note_divergence_events(
                    step, int(events), int(checksum) & 0xFFFFFFFF)
                if new > 0 and ncfg.divergence_policy == "abort":
                    from deepspeed_tpu.diagnostics.manager import (
                        TrainingHealthError)

                    dump_path = (self.diagnostics.dump(
                        reason="numerics_divergence")
                        if self.diagnostics is not None else None)
                    raise TrainingHealthError(
                        f"numerics divergence abort at step {step}: "
                        f"cross-replica digest mismatch "
                        f"({int(events)} cumulative event(s))",
                        step, {"numerics/divergence_events": int(events)},
                        dump_path)
        if (ncfg.sample_every > 0 and step % ncfg.sample_every == 0
                and st.comm_error is not None):
            nm.note_ef_residuals(st.comm_error)

    def _wrap_jit(self, name: str, fn: Callable, arg_names=None) -> Callable:
        """Recompile-detector wrap for a jitted callable (identity when
        diagnostics/recompile checking is off).

        With diagnostics off but telemetry on, the compiled-program registry
        still wants the wrap point (telemetry/programs.py) — its watcher does
        the same two cache-size probes and captures only on compile. With
        both off the callable is returned untouched (byte-identical
        dispatch, the zero-overhead contract)."""
        if self.diagnostics is not None:
            return self.diagnostics.wrap_jit(name, fn, arg_names=arg_names)
        tcfg = self.config.model.telemetry
        if fn is not None and tcfg.programs:
            from deepspeed_tpu.telemetry.programs import get_program_registry

            registry = get_program_registry()
            if tcfg.enabled or registry.enabled:
                return registry.wrap(fn, name, hbm_scope="train")
        return fn

    @staticmethod
    def _build_engine_mesh(config) -> Mesh:
        """Mesh from config, with the MiCS sub-group split applied.

        ``mics_shard_size=m`` (reference ``zero/mics.py:64 MiCS_Init`` +
        ``zero/config.py:326``) shards params within groups of m devices and
        replicates across groups. On a mesh that IS a re-factoring of the
        fsdp axis: fsdp becomes m (the shard group) and the leftover factor
        folds into dp (pure replication + gradient averaging), so the
        hierarchical/2-hop gather machinery reduces to an allgather over a
        smaller, ICI-contiguous axis.
        """
        base = build_mesh(config.mesh_config)
        m = config.zero_config.mics_shard_size
        hpz = config.zero_config.zero_hpz_partition_size
        if m and m > 0 and hpz > 1:
            raise ValueError("mics_shard_size and zero_hpz_partition_size are mutually exclusive")
        if (m is None or m <= 0) and hpz > 1:
            # hpZ re-factors the mesh the same way (fsdp -> intra-node group);
            # the placement difference (masters stay sharded over the FULL
            # data world) is applied in _init_state.
            m = hpz
        if m is None or m <= 0:
            return base
        if config.zero_config.stage < 3:
            raise ValueError(
                "mics_shard_size / zero_hpz_partition_size require ZeRO stage 3 (sharded parameters)"
            )
        F = base.shape["fsdp"]
        if F == m:
            return base
        world = F * base.shape["dp"]  # the sub-group draws from the data world
        if world % m:
            raise ValueError(
                f"shard-group size {m} must divide the data world {world} (dp x fsdp)"
            )
        sizes = dict(base.shape)
        sizes["fsdp"] = m
        sizes["dp"] = world // m
        if config.zero_config.mics_hierarchical_params_gather:
            log_dist(
                "mics_hierarchical_params_gather: the intra-group allgather is "
                "inherent to the fsdp-subgroup mesh; no extra hop needed", ranks=[0],
            )
        # re-mesh through a copied MeshConfig so multi-slice handling
        # (num_slices / dcn_axis hybrid device order) survives: the MiCS shard
        # group must stay ICI-contiguous — that IS the point of the knob
        mics_cfg = config.mesh_config.model_copy(update=sizes)
        return build_mesh(mics_cfg)

    def _build_lr_schedule(self, client_sched) -> Tuple[Schedule, Any]:
        if client_sched is not None and callable(client_sched):
            return client_sched, client_sched
        sched_cfg = self.config.model.scheduler
        base_lr = None
        if self.config.model.optimizer is not None:
            base_lr = self.config.model.optimizer.params.get("lr")
        if sched_cfg is not None and sched_cfg.type:
            return get_lr_schedule(sched_cfg.type, sched_cfg.params, base_lr=base_lr), None
        return constant_schedule(base_lr if base_lr is not None else 1e-3), None

    def _check_hbm_budget(self, mcfg) -> None:
        """Pre-flight fit check: estimated per-device state bytes vs device
        memory, BEFORE ``_init_state`` materializes anything (VERDICT r5
        item 2 — the ~890M extra wedged the shared relay for 9+ hours at
        param init on a failure the existing math predicted).

        Warn-only by default; ``hbm_guard.enabled=true`` refuses with the
        estimate in the error. No-op when the device budget is undiscoverable
        (CPU backends) and no override is configured."""
        gcfg = self.config.model.hbm_guard
        self._hbm_estimate_bytes = None
        # the estimate is also the calibration baseline the compiled-program
        # registry reconciles XLA's memory_analysis against (hbm/estimate_
        # ratio) — compute it when either consumer is live
        want_calibration = (self.config.model.telemetry.enabled
                            and self.config.model.telemetry.programs)
        if not (gcfg.enabled or gcfg.warn or want_calibration):
            return
        from deepspeed_tpu.autotuning.autotuner import estimate_state_memory
        from deepspeed_tpu.ops.attention import resolves_to_flash
        from deepspeed_tpu.utils.hbm import check_hbm_fit

        try:
            shapes = jax.eval_shape(self.model.init_fn, jax.random.PRNGKey(0))
            n_params = int(sum(np.prod(x.shape)
                               for x in jax.tree_util.tree_leaves(shapes)))
        except Exception as e:  # noqa: BLE001 — the guard is best-effort
            logger.debug(f"hbm_guard: shape probe failed ({e}); skipping")
            return
        offloaded = self.offload_mode in ("host-jit", "nvme")
        compute_b = jnp.dtype(self.compute_dtype).itemsize
        need = estimate_state_memory(
            n_params,
            self.zero_config.stage,
            get_data_parallel_world_size(self.mesh),
            # offload keeps fp32 masters + moments on host; the device holds
            # only the compute-dtype copy + the gradient accumulator
            dtype_bytes=0 if offloaded else 4,
            opt_factor=0 if offloaded else 2,
            compute_dtype_bytes=compute_b,
            accum_dtype_bytes=jnp.dtype(self._accum_dtype).itemsize,
            micro_batch=self.config.train_micro_batch_size_per_gpu or 0,
            seq_len=getattr(mcfg, "max_seq_len", 0) or 0,
            hidden_size=getattr(mcfg, "hidden_size", 0) or 0,
            num_layers=getattr(mcfg, "num_layers", 0) or 0,
            vocab_size=getattr(mcfg, "vocab_size", 0) or 0,
            num_heads=getattr(mcfg, "num_heads", 0) or 0,
            remat=bool(getattr(mcfg, "remat", True)),
            fused_ce=bool(getattr(mcfg, "fused_ce", False)),
            # flash attention never materializes the score matrix, so the
            # attention temp-workspace term vanishes. Ask the ops registry
            # which implementation would actually dispatch for this
            # attn_impl — if the Pallas kernel cannot serve the config the
            # estimate must keep the score-matrix workspace term
            flash_attention=resolves_to_flash(
                getattr(mcfg, "attn_impl", "auto")),
        )
        self._hbm_estimate_bytes = int(need)
        from deepspeed_tpu.telemetry.programs import get_program_registry

        get_program_registry().set_hbm_estimate(need, scope="train")
        if not (gcfg.enabled or gcfg.warn):
            return  # calibration-only probe: the guard itself is off
        override = (int(gcfg.device_memory_gb * (1 << 30))
                    if gcfg.device_memory_gb else None)
        check_hbm_fit(
            need,
            what=f"engine init ({n_params / 1e6:.0f}M params, "
                 f"zero_stage={self.zero_config.stage})",
            mode="refuse" if gcfg.enabled else "warn",
            device_memory=override,
            headroom=gcfg.headroom,
        )

    def _init_state(self, model_parameters, seed: int) -> None:
        mesh = self.mesh
        rng = jax.random.PRNGKey(seed)

        init_rng = None
        if model_parameters is None:
            init_rng, rng = jax.random.split(rng)
            # Sharded construction (the zero.Init analog,
            # partition_parameters.py:825): shapes come from eval_shape (no
            # compute), shardings are derived from them, and the actual init
            # runs ONCE under jit with out_shardings — every leaf materializes
            # directly in its target placement, so models larger than host RAM
            # can be constructed. The eager init-then-place path remains for
            # caller-provided params (e.g. HF ingestion) and host offload.
            param_shapes = jax.eval_shape(
                lambda r: cast_floating(self.model.init_fn(r), jnp.float32), init_rng
            )
            master_f32 = None
        else:
            master_f32 = cast_floating(model_parameters, jnp.float32)
            param_shapes = jax.eval_shape(lambda: master_f32)

        # Model-parallel base placements (AutoTP rules) — ZeRO composes on top.
        base_specs = self._build_base_specs(param_shapes)
        self._base_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), base_specs
        )
        self._hpz_compute_sharding = None
        if self.zero_config.stage >= 3 and self.zero_config.zero_hpz_partition_size > 1:
            # ZeRO++ hpZ (zero/config.py:294, utils/groups.py:650): masters
            # keep the FULL data-world partition (dp x fsdp jointly — maximal
            # ZeRO-3 memory win); compute params constrain to a SECONDARY
            # partition over the (re-meshed, ICI-local) fsdp axis only. One
            # cross-group gather materializes the secondary copy per step;
            # every per-layer allgather then rides the intra-node axis.
            self.param_sharding = zero_mod.master_sharding(param_shapes, mesh, self.zero_config, base_specs)
            self._hpz_compute_sharding = zero_mod.params_sharding(
                param_shapes, mesh, self.zero_config, base_specs
            )
        elif self.zero_config.stage >= 3:
            # Stage 3: master params use the fsdp param placement so compute
            # params inherit it without an extra reshard.
            self.param_sharding = zero_mod.params_sharding(param_shapes, mesh, self.zero_config, base_specs)
        elif self.zero_config.stage >= 1:
            self.param_sharding = zero_mod.master_sharding(param_shapes, mesh, self.zero_config, base_specs)
        else:
            self.param_sharding = self._base_shardings

        # Device placement of the bf16 COMPUTE params (also the master
        # placement unless offload moves the masters off-device).
        self._device_param_sharding = self.param_sharding
        if self.offload_mode == "memories":
            # Masters + moments live in host memory inside the one compiled
            # step; XLA streams them (reference: CPU optimizer partition).
            self.param_sharding = jax.tree_util.tree_map(
                lambda sh: sh.with_memory_kind("pinned_host"), self.param_sharding
            )
        elif self.offload_mode in ("host-jit", "nvme"):
            from jax.sharding import SingleDeviceSharding

            host_sh = SingleDeviceSharding(self._host_device)
            if self._twin_ratio is not None:
                # Twin-Flow: the first `ratio` fraction of master bytes (in
                # stable tree-flatten order) updates host-side; the rest
                # keeps its on-mesh master placement and updates in a fused
                # device program (reference ZeRO-Offload++ Twin-Flow).
                leaves, treedef = jax.tree_util.tree_flatten(param_shapes)
                sizes = [int(np.prod(l.shape)) if l.shape else 1 for l in leaves]
                total = sum(sizes)
                flags, cum = [], 0
                for s in sizes:
                    flags.append(cum < self._twin_ratio * total)
                    cum += s
                self._tf_host_mask = jax.tree_util.tree_unflatten(treedef, flags)
                self.param_sharding = jax.tree_util.tree_map(
                    lambda m, sh: host_sh if m else sh,
                    self._tf_host_mask, self._device_param_sharding)
                n_host = sum(s for s, m in zip(sizes, flags) if m)
                log_dist(
                    f"Twin-Flow split: {n_host / max(total, 1):.1%} of "
                    f"{total / 1e6:.1f}M master params update host-side "
                    f"(ratio={self._twin_ratio})", ranks=[0])
            else:
                self.param_sharding = jax.tree_util.tree_map(lambda _: host_sh, param_shapes)

        if master_f32 is not None:
            # unaliased: user-supplied initial params are often host numpy;
            # zero-copy device_put + the donated step is the PR-1 landmine
            from deepspeed_tpu.utils.compat import device_put_unaliased

            params = jax.tree_util.tree_map(
                device_put_unaliased, master_f32, self.param_sharding)
        elif self.offload_mode in ("host-jit", "nvme"):
            # host-resident masters: eager init lands on host anyway
            params = jax.device_put(
                cast_floating(self.model.init_fn(init_rng), jnp.float32), self.param_sharding
            )
        else:
            # sharded construction: leaves materialize pre-placed (zero.Init)
            params = jax.jit(
                lambda r: cast_floating(self.model.init_fn(r), jnp.float32),
                out_shardings=self.param_sharding,
            )(init_rng)

        opt_shapes = jax.eval_shape(self.tx.init, params)
        if self.offload_mode in ("host-jit", "nvme"):
            from jax.sharding import SingleDeviceSharding

            host_sh = SingleDeviceSharding(self._host_device)
            if self._twin_ratio is not None:
                # Two structure-preserving masked views of the ONE optimizer:
                # each partition's state keeps the param-tree shape with
                # optax.MaskedNode holes for the other partition, so the
                # fragment/checkpoint walkers still see param-shaped moment
                # trees. Out-of-partition leaves are fed as 0-d dummies the
                # masked transform never reads.
                self._tf_dev_mask = jax.tree_util.tree_map(
                    lambda m: not m, self._tf_host_mask)
                self._tf_tx_host = optax.masked(self.tx, self._tf_host_mask)
                self._tf_tx_dev = optax.masked(self.tx, self._tf_dev_mask)
                host_sub = self._tf_partition(params, host_side=True)
                dev_sub = self._tf_partition(params, host_side=False)
                opt_host = jax.jit(self._tf_tx_host.init)(host_sub)  # cpu backend
                opt_dev = jax.jit(self._tf_tx_dev.init)(dev_sub)
                opt_state = (opt_host, opt_dev)
                self.opt_sharding = jax.tree_util.tree_map(
                    lambda x: x.sharding, opt_state)
            else:
                self.opt_sharding = jax.tree_util.tree_map(lambda _: host_sh, opt_shapes)
                # out_shardings COMMITS the moments to the host device. A bare
                # jit leaves its outputs uncommitted, while every later
                # offload_update_step output is committed — that placement
                # flip recompiled the host update once on call 2 (found by
                # the PR-2 RecompileDetector).
                opt_state = jax.jit(
                    self.tx.init, out_shardings=self.opt_sharding
                )(params)  # inputs committed to host => runs on the cpu backend
            ls_state = make_loss_scale_state(
                enabled=self.fp16,
                initial_scale_power=self.config.model.fp16.initial_scale_power,
                static_loss_scale=self.config.model.fp16.loss_scale,
                hysteresis=self.config.model.fp16.hysteresis,
            )
            ls_state = jax.device_put(ls_state, host_sh)
            self.state = TrainState(
                step=jax.device_put(jnp.zeros((), jnp.int32), host_sh),
                params=params,
                opt_state=opt_state,
                loss_scale=ls_state,
                rng=jax.device_put(jax.random.key_data(rng), host_sh),
            )
            self.state_sharding = TrainState(
                step=host_sh,
                params=self.param_sharding,
                opt_state=self.opt_sharding,
                loss_scale=jax.tree_util.tree_map(lambda _: host_sh, ls_state),
                rng=host_sh,
            )
            self.grad_sharding = zero_mod.grads_sharding(param_shapes, mesh, self.zero_config, base_specs)
            self._compute_dev = None  # bf16 device params, materialized lazily
            self._opt_on_nvme = False
            return

        replicated_sh = NamedSharding(mesh, PartitionSpec())
        try:
            # Optimizer moments inherit their parameter's placement exactly
            # (no resharding in the update); non-param leaves replicate.
            self.opt_sharding = optax.tree_map_params(
                self.tx,
                lambda _leaf, sh: sh,
                opt_shapes,
                self.param_sharding,
                transform_non_params=lambda _leaf: replicated_sh,
            )
        except Exception as e:
            # Custom client transforms that tree_map_params cannot traverse:
            # fall back to the shape-based data-axes rule. This loses any
            # model-parallel (tp) placement for the moments (opt-state tree
            # structure differs from params, so base specs cannot be mapped),
            # costing a reshard per update — make it visible.
            logger.warning(
                f"optimizer-state placement fell back to the shape-based rule "
                f"(tree_map_params failed: {type(e).__name__}: {e}); tp placements "
                f"are not propagated to optimizer moments"
            )
            self.opt_sharding = zero_mod.master_sharding(opt_shapes, mesh, self.zero_config)
        if self.offload_mode == "memories":
            self.opt_sharding = jax.tree_util.tree_map(
                lambda sh: sh.with_memory_kind("pinned_host"), self.opt_sharding
            )
        opt_state = jax.jit(self.tx.init, out_shardings=self.opt_sharding)(params)

        ls_state = make_loss_scale_state(
            enabled=self.fp16,
            initial_scale_power=self.config.model.fp16.initial_scale_power,
            static_loss_scale=self.config.model.fp16.loss_scale,
            hysteresis=self.config.model.fp16.hysteresis,
        )
        replicated = NamedSharding(mesh, PartitionSpec())
        ls_state = jax.device_put(ls_state, replicated)

        self.state = TrainState(
            step=jax.device_put(jnp.zeros((), jnp.int32), replicated),
            params=params,
            opt_state=opt_state,
            loss_scale=ls_state,
            rng=jax.device_put(jax.random.key_data(rng), replicated),
        )
        self.state_sharding = TrainState(
            step=replicated,
            params=self.param_sharding,
            opt_state=self.opt_sharding,
            loss_scale=jax.tree_util.tree_map(lambda _: replicated, ls_state),
            rng=replicated,
        )
        self.grad_sharding = zero_mod.grads_sharding(param_shapes, mesh, self.zero_config, base_specs)

        err_live = None
        if getattr(self, "_onebit", None):
            err_live = self._onebit
        elif getattr(self, "_zpp", None) and self._zpp[3]:
            err_live = self._zpp[0]  # ZeRO++ LoCo residuals, same layout
        if err_live:
            # per-rank error-feedback residuals: [dp_world, *shape], dim 0
            # sharded over the live data axes (each rank owns its own slice)
            live = err_live
            live_entry = live if len(live) > 1 else live[0]
            W = 1
            for a in live:
                W *= mesh.shape[a]
            err_sharding = jax.tree_util.tree_map(
                lambda _: NamedSharding(mesh, PartitionSpec(live_entry)), param_shapes
            )
            errors = jax.jit(
                lambda: jax.tree_util.tree_map(
                    lambda l: jnp.zeros((W,) + tuple(l.shape), jnp.float32), param_shapes
                ),
                out_shardings=err_sharding,
            )()
            self.state = self.state._replace(comm_error=errors)
            self.state_sharding = self.state_sharding._replace(comm_error=err_sharding)

    def _build_base_specs(self, param_shapes) -> Any:
        """Per-param model-parallel PartitionSpecs from the model's rules."""
        rules = self.model.partition_rules
        if rules is None:
            return jax.tree_util.tree_map(lambda _: PartitionSpec(), param_shapes)

        def one(key_path, leaf):
            spec = rules(jax.tree_util.keystr(key_path), tuple(leaf.shape))
            return spec if spec is not None else PartitionSpec()

        return jax.tree_util.tree_map_with_path(one, param_shapes)

    # ----------------------------------------------------------- train step
    def _loss_and_aux(self, params, batch, rng):
        loss_fn = self.model.loss_fn
        ac_cfg = self.config.model.activation_checkpointing
        if ac_cfg.enabled:
            # remat policy applied to the whole loss: XLA re-schedules the
            # recompute (reference activation_checkpointing/checkpointing.py:948)
            from deepspeed_tpu.runtime.activation_checkpointing import (
                apply_activation_checkpointing,
            )

            loss_fn = apply_activation_checkpointing(loss_fn, ac_cfg)
        out = loss_fn(params, batch, rng)
        if isinstance(out, tuple):
            return out[0], out[1:]
        return out, ()

    def _compute_params(self, master_params):
        compute = cast_floating(master_params, self.compute_dtype)
        if self.offload_mode == "memories":
            # Masters live in pinned host memory: pin the bf16 copies to
            # DEVICE memory explicitly so the whole forward doesn't try to
            # consume host-resident buffers.
            compute = jax.lax.with_sharding_constraint(compute, self._device_param_sharding)
        if self.zero_config.stage in (1, 2):
            # Updated shards -> full weights: the stage-1/2 post-step allgather
            # (reference stage_1_and_2.py:1835ff), done in 16-bit. Model-
            # parallel (tp) placements are preserved; only data-axis shards
            # gather.
            compute = jax.lax.with_sharding_constraint(compute, self._base_shardings)
        elif self._hpz_compute_sharding is not None:
            # hpZ secondary partition: one gather across the dp groups here;
            # per-layer gathers downstream ride only the intra-node fsdp axis
            compute = jax.lax.with_sharding_constraint(compute, self._hpz_compute_sharding)
        return compute

    def _zpp_config(self):
        """(live_axes, qw, qg) when ZeRO++ collectives should be active."""
        from deepspeed_tpu.topology.mesh import BATCH_AXES

        zc = self.zero_config
        qw, qg = zc.zero_quantized_weights, zc.zero_quantized_gradients
        if zc.zero_hpz_partition_size > 1 and (qw or qg):
            raise NotImplementedError(
                "hpZ (zero_hpz_partition_size) + quantized collectives "
                "(qwZ/qgZ) are not composed yet: the quantized gather path "
                "bypasses the secondary-partition constraint; enable one"
            )
        if not (qw or qg):
            if zc.loco_param:
                raise ValueError("loco_param requires zero_quantized_gradients: true "
                                 "(LoCo compensates the qgZ wire)")
            return None
        if qg and zc.stage < 2:
            raise ValueError("zero_quantized_gradients requires ZeRO stage >= 2 (sharded gradients)")
        loco = dict(zc.loco_param) if zc.loco_param else None
        if loco and not qg:
            raise ValueError("loco_param requires zero_quantized_gradients: true")
        live = tuple(a for a in BATCH_AXES if self.mesh.shape[a] > 1)
        if not live:
            logger.warning("ZeRO++ quantized collectives requested but no data-parallel axis > 1; ignored")
            return None
        return live, qw, qg, loco

    def _build_zpp_micro_fn(self, live, qw: bool, qg: bool, loco=None) -> Callable:
        """Micro-batch gradient fn with addressable (quantized) collectives.

        Runs the loss inside a partial-manual shard_map (data axes manual,
        model axes auto): weights enter as their master-layout shards, are
        gathered through ``sharded_weight_gather`` (int8 when qwZ), and its
        custom VJP reduce-scatters the gradients back (int8 all-to-all when
        qgZ). Reference: coalesced_collectives.py:31, partition_parameters.py:1200.

        ``loco`` ({"err_beta": float, ...}) switches qgZ to the LoCo
        error-feedback reduce (reference coalesced_collectives.py:81): the fn
        then takes/returns per-rank residual buffers (``state.comm_error``),
        stored in TRUE gradient units so loss-scale changes can't corrupt them.
        """
        from deepspeed_tpu.parallel import zeropp

        mesh = self.mesh

        def _manual_only(spec: PartitionSpec) -> PartitionSpec:
            entries = []
            for e in spec:
                if e is None:
                    entries.append(None)
                    continue
                names = e if isinstance(e, tuple) else (e,)
                keep = tuple(a for a in names if a in live)
                entries.append(keep if len(keep) > 1 else (keep[0] if keep else None))
            return PartitionSpec(*entries)

        master_specs = jax.tree_util.tree_map(lambda sh: sh.spec, self.param_sharding)
        param_in_specs = jax.tree_util.tree_map(_manual_only, master_specs)
        plans = jax.tree_util.tree_map(lambda s: zeropp.leaf_comm_plan(s, live), param_in_specs)
        grad_out_specs = jax.tree_util.tree_map(
            lambda p: PartitionSpec(*[
                (p.axes if len(p.axes) > 1 else p.axes[0]) if d == p.dim else None
                for d in range(p.dim + 1)
            ]) if p.sharded else PartitionSpec(),
            plans,
        )
        batch_spec = PartitionSpec(live if len(live) > 1 else live[0])

        from deepspeed_tpu.utils.compat import shard_map

        if loco:
            err_beta = float(loco.get("err_beta", 0.8))
            live_entry = live if len(live) > 1 else live[0]
            err_specs = jax.tree_util.tree_map(
                lambda _: PartitionSpec(live_entry), plans)

            def local_fn_loco(param_shards, err_blocks, micro, scale, inv, step_rng):
                r = jax.random.fold_in(
                    jax.random.wrap_key_data(step_rng), jax.lax.axis_index(live)
                )
                errs = jax.tree_util.tree_map(lambda e: e[0], err_blocks)

                def scaled_loss(shards_errs, b, rr):
                    shards, errs_ = shards_errs
                    full = zeropp.gather_params_for_compute(
                        shards, plans, qw, qg, live_axes=live,
                        errors=errs_, err_beta=err_beta, inv=inv,
                        overlap_chunks=self._overlap_chunks())
                    loss, _aux = self._loss_and_aux(full, b, rr)
                    return (loss.astype(jnp.float32) * scale).astype(
                        self.compute_dtype if self.fp16 else jnp.float32), loss

                (_, loss), (grads, new_errs) = jax.value_and_grad(
                    scaled_loss, has_aux=True)((param_shards, errs), micro, r)
                grads = cast_floating(grads, jnp.float32)
                grads = jax.tree_util.tree_map(
                    lambda g, p: g if p.sharded else _facade_grad_mean(g, live),
                    grads, plans
                )
                new_errs = jax.tree_util.tree_map(lambda e: e[None].astype(jnp.float32),
                                                  new_errs)
                return grads, new_errs, jax.lax.pmean(loss, live)

            return shard_map(
                local_fn_loco,
                mesh=mesh,
                in_specs=(param_in_specs, err_specs, batch_spec,
                          PartitionSpec(), PartitionSpec(), PartitionSpec()),
                out_specs=(grad_out_specs, err_specs, PartitionSpec()),
                axis_names=set(live),
                check_vma=False,
            )

        def local_fn(param_shards, micro, scale, step_rng):
            # de-correlate dropout across data ranks
            r = jax.random.fold_in(
                jax.random.wrap_key_data(step_rng), jax.lax.axis_index(live)
            )

            def scaled_loss(shards, b, rr):
                full = zeropp.gather_params_for_compute(
                    shards, plans, qw, qg, live_axes=live,
                    overlap_chunks=self._overlap_chunks())
                loss, _aux = self._loss_and_aux(full, b, rr)
                return (loss.astype(jnp.float32) * scale).astype(self.compute_dtype if self.fp16 else jnp.float32), loss

            (_, loss), grads = jax.value_and_grad(scaled_loss, has_aux=True)(param_shards, micro, r)
            grads = cast_floating(grads, jnp.float32)
            grads = jax.tree_util.tree_map(
                lambda g, p: g if p.sharded else _facade_grad_mean(g, live), grads, plans
            )
            return grads, jax.lax.pmean(loss, live)

        return shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(param_in_specs, batch_spec, PartitionSpec(), PartitionSpec()),
            out_specs=(grad_out_specs, PartitionSpec()),
            axis_names=set(live),
            check_vma=False,
        )

    def _overlap_chunks(self) -> int:
        """zeropp gather chunking, honored only when the collectives block
        is enabled (disabled must compile the identical program)."""
        cfg = self._collectives_cfg
        return cfg.overlap_chunks if cfg.enabled else 1

    def _onebit_config(self):
        """Live data axes when 1-bit compressed gradient allreduce is active.

        Triggered by ``gradient_compression.enabled`` or a OneBit optimizer
        name (reference OnebitAdam/OnebitLamb/ZeroOneAdam,
        ``runtime/comm/nccl.py compressed_allreduce``). Validates composition
        at construction — dead/lying knobs are worse than errors."""
        from deepspeed_tpu.topology.mesh import BATCH_AXES

        gc = self.config.model.gradient_compression
        opt = self.config.model.optimizer
        opt_name = opt.type.lower().replace("_", "") if opt else ""
        onebit_opt = opt_name in ("onebitadam", "onebitlamb", "zerooneadam")
        if not (gc.enabled or onebit_opt):
            return None
        if gc.enabled and gc.bits != 1:
            raise NotImplementedError("gradient_compression.bits must be 1 (sign compression)")
        if self.zero_config.stage >= 2:
            raise ValueError(
                "gradient_compression / OneBit optimizers need full local gradients: "
                "use ZeRO stage <= 1 (the reference 1-bit optimizers have the same constraint)"
            )
        if self._zpp:
            raise ValueError("gradient_compression does not compose with ZeRO++ quantized collectives")
        if self.offload_mode in ("host-jit", "nvme", "memories"):
            raise ValueError("gradient_compression does not compose with optimizer offload")
        live = tuple(a for a in BATCH_AXES if self.mesh.shape[a] > 1)
        if not live:
            logger.warning("gradient_compression enabled but only one data rank; compression is a no-op")
            return None
        return live

    def _build_onebit_fn(self, live) -> Callable:
        """shard_map program: local grad accumulation + sign-compressed exact-
        mean allreduce with error feedback (parallel/onebit.py)."""
        from deepspeed_tpu.utils.compat import shard_map

        from deepspeed_tpu.parallel import onebit as onebit_mod

        mesh = self.mesh

        def _manual_only(spec: PartitionSpec) -> PartitionSpec:
            entries = []
            for e in spec:
                if e is None:
                    entries.append(None)
                    continue
                names = e if isinstance(e, tuple) else (e,)
                keep = tuple(a for a in names if a in live)
                entries.append(keep if len(keep) > 1 else (keep[0] if keep else None))
            return PartitionSpec(*entries)

        base_specs = jax.tree_util.tree_map(lambda sh: sh.spec, self._base_shardings)
        param_in_specs = jax.tree_util.tree_map(_manual_only, base_specs)
        live_entry = live if len(live) > 1 else live[0]
        err_specs = jax.tree_util.tree_map(lambda _: PartitionSpec(live_entry), base_specs)
        batch_spec = PartitionSpec(None, live_entry)

        def local_fn(params, batch, scale, inv, step_rng, errors):
            r0 = jax.random.wrap_key_data(step_rng)
            rank = jax.lax.axis_index(live)

            def scaled_loss(p, b, rr):
                loss, _aux = self._loss_and_aux(p, b, rr)
                return (loss.astype(jnp.float32) * scale).astype(
                    self.compute_dtype if self.fp16 else jnp.float32
                ), loss

            grad_fn = jax.value_and_grad(scaled_loss, has_aux=True)

            def micro_step(carry, xs):
                acc, i = carry
                r = jax.random.fold_in(jax.random.fold_in(r0, i), rank)
                (_, loss), g = grad_fn(params, xs, r)
                g = cast_floating(g, jnp.float32)
                acc = jax.tree_util.tree_map(lambda a, b: a + b, acc, g)
                return (acc, i + 1), loss

            zero = jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
            (acc, _), losses = jax.lax.scan(micro_step, (zero, 0), batch)
            # compress in TRUE gradient units (unscale first): the residuals
            # stay valid across dynamic loss-scale changes
            acc = jax.tree_util.tree_map(lambda g: g * inv, acc)
            mean_grads, new_err = onebit_mod.compressed_grad_mean(acc, errors, live)
            return mean_grads, new_err, jax.lax.pmean(losses, live)

        return shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(param_in_specs, batch_spec, PartitionSpec(), PartitionSpec(), PartitionSpec(), err_specs),
            out_specs=(
                jax.tree_util.tree_map(lambda _: PartitionSpec(), base_specs),
                err_specs,
                PartitionSpec(),
            ),
            axis_names=set(live),
            check_vma=False,
        )

    def _build_train_step(self) -> Callable:
        gas = self.config.gradient_accumulation_steps
        clip = self.config.gradient_clipping
        fp16_cfg = self.config.model.fp16
        dynamic = self.fp16 and fp16_cfg.dynamic
        grad_pspecs = self.grad_sharding  # NamedShardings: usable without a context mesh

        zpp_fn = self._build_zpp_micro_fn(*self._zpp) if self._zpp else None
        zpp_loco = self._zpp[3] if self._zpp else None
        ob_fn = self._build_onebit_fn(self._onebit) if self._onebit else None
        # ZeRO++ micro-grads come back fp32 from the quantized collectives —
        # a bf16 carry would flip dtypes mid-scan
        accum_dtype = jnp.float32 if zpp_fn is not None else self._accum_dtype

        def train_step(state: TrainState, batch):
            rng = jax.random.wrap_key_data(state.rng)
            rng, step_rng = jax.random.split(rng)
            scale = state.loss_scale.loss_scale

            if ob_fn is not None:
                compute_params = self._compute_params(state.params)
                # inv: residuals are stored in TRUE gradient units, so a
                # dynamic-loss-scale change between steps cannot corrupt the
                # carried error feedback.
                inv = 1.0 / (gas * scale)
                grads, new_err, losses = ob_fn(
                    compute_params, batch, scale, inv, jax.random.key_data(step_rng), state.comm_error
                )
                loss_mean = jnp.mean(losses.astype(jnp.float32))
                new_state, metrics = self._update_math(
                    state, grads, jax.random.key_data(rng), grads_are_unscaled=True,
                    loss=loss_mean,
                )
                # fp16 overflow: a non-finite step would store NaN residuals
                # and poison every later step — keep the previous buffers
                # (the reference skips its error-feedback update on overflow
                # the same way). A health-policy skip keeps them too: the
                # residual update belongs to an update that never applied.
                keep = ~metrics["overflow"]
                if "health/skip" in metrics:
                    keep = keep & ~metrics["health/skip"]
                new_err = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(keep, n, o), new_err, state.comm_error
                )
                new_state = new_state._replace(comm_error=new_err)
                metrics["loss"] = loss_mean
                return new_state, metrics

            if zpp_fn is not None:
                # ZeRO++ path: compute params stay in master layout; the
                # (quantized) gather happens inside the micro fn's shard_map.
                compute_params = jax.lax.with_sharding_constraint(
                    cast_floating(state.params, self.compute_dtype), self._device_param_sharding
                )
            else:
                compute_params = self._compute_params(state.params)

            moe_stats_on = getattr(self, "_moe_metrics", False)

            def scaled_loss(p, micro, r):
                loss, _aux = self._loss_and_aux(p, micro, r)
                # MoE dispatch stats ride the grad aux (parallel/moe.py;
                # model contract: the last aux element is a dict of scalars)
                stats = (_aux[-1] if moe_stats_on and _aux
                         and isinstance(_aux[-1], dict) else None)
                return (loss.astype(jnp.float32) * scale).astype(self.compute_dtype if self.fp16 else jnp.float32), (loss, stats)

            grad_fn = jax.value_and_grad(scaled_loss, has_aux=True)

            def micro_step(carry, micro_batch):
                acc, i = carry
                if zpp_fn is not None:
                    grads, loss = zpp_fn(
                        compute_params, micro_batch, scale, jax.random.key_data(jax.random.fold_in(step_rng, i))
                    )
                    stats = None
                else:
                    (_, (loss, stats)), grads = grad_fn(compute_params, micro_batch, jax.random.fold_in(step_rng, i))
                    grads = cast_floating(grads, accum_dtype)
                acc = jax.tree_util.tree_map(lambda a, g: (a + g).astype(accum_dtype), acc, grads)
                # shard the accumulator (stage>=2 => reduce-scatter per micro-batch)
                acc = jax.lax.with_sharding_constraint(acc, grad_pspecs)
                return (acc, i + 1), (loss, stats)

            zero_grads = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, accum_dtype), state.params
            )
            zero_grads = jax.lax.with_sharding_constraint(zero_grads, grad_pspecs)

            if zpp_loco is not None:
                # LoCo (reference coalesced_collectives.py:81): residuals ride
                # the micro-step carry; reset every reset_T steps (reference
                # loco_idx > reset_T re-zeroes the buffers).
                inv_s = 1.0 / scale
                err0 = state.comm_error
                reset_T = int(zpp_loco.get("reset_T", 0) or 0)
                if reset_T:
                    do_reset = (state.step % reset_T == 0) & (state.step > 0)
                    err0 = jax.tree_util.tree_map(
                        lambda e: jnp.where(do_reset, jnp.zeros_like(e), e), err0)

                def micro_step_loco(carry, micro_batch):
                    acc, err, i = carry
                    grads, err, loss = zpp_fn(
                        compute_params, err, micro_batch, scale, inv_s,
                        jax.random.key_data(jax.random.fold_in(step_rng, i)))
                    acc = jax.tree_util.tree_map(lambda a, g: a + g, acc, grads)
                    acc = jax.lax.with_sharding_constraint(acc, grad_pspecs)
                    return (acc, err, i + 1), loss

                if gas == 1:
                    (grads, new_err, _), losses = micro_step_loco(
                        (zero_grads, err0, 0),
                        jax.tree_util.tree_map(lambda x: x[0], batch))
                    losses = losses[None]
                else:
                    (grads, new_err, _), losses = jax.lax.scan(
                        micro_step_loco, (zero_grads, err0, 0), batch)

                loss_mean = jnp.mean(losses.astype(jnp.float32))
                new_state, metrics = self._update_math(
                    state, grads, jax.random.key_data(rng), loss=loss_mean)
                # overflow/health skip => keep the previous residuals (as the
                # 1-bit path)
                keep = ~metrics["overflow"]
                if "health/skip" in metrics:
                    keep = keep & ~metrics["health/skip"]
                new_err = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(keep, n, o), new_err, state.comm_error)
                new_state = new_state._replace(comm_error=new_err)
                metrics["loss"] = loss_mean
                return new_state, metrics

            if gas == 1:
                (grads, _), (losses, moe_stats) = micro_step(
                    (zero_grads, 0), jax.tree_util.tree_map(lambda x: x[0], batch))
                losses = losses[None]
            else:
                (grads, _), (losses, moe_stats) = jax.lax.scan(
                    micro_step, (zero_grads, 0), batch)

            loss_mean = jnp.mean(losses.astype(jnp.float32))
            new_state, metrics = self._update_math(
                state, grads, jax.random.key_data(rng), loss=loss_mean)
            metrics["loss"] = loss_mean
            if moe_stats is not None:
                # mean over micro-batches (scan stacked them); scalar per key
                metrics.update({
                    k: jnp.mean(jnp.asarray(v).astype(jnp.float32))
                    for k, v in moe_stats.items()})
            return new_state, metrics

        return jax.jit(
            train_step,
            in_shardings=(self.state_sharding, None),
            out_shardings=(self.state_sharding, None),
            donate_argnums=(0,),
        )

    def _update_math(self, state: TrainState, grads, new_rng_data,
                     grads_are_unscaled: bool = False,
                     loss: Any = None) -> Tuple[TrainState, Dict[str, Any]]:
        """Scale / clip / optimizer update / overflow-skip / loss-scale step.

        The ONE copy of the update semantics, traced into the fused step, the
        forward/backward/step apply program, and the offload host program —
        so the three paths cannot drift (reference ``FP16_Optimizer.step``).
        ``loss`` (optional step-mean loss) feeds the loss-spike health probe
        on paths that have it (the fused step; the offload host program and
        the apply path receive gradients only)."""
        gas = self.config.gradient_accumulation_steps
        clip = self.config.gradient_clipping
        fp16_cfg = self.config.model.fp16
        dynamic = self.fp16 and fp16_cfg.dynamic
        scale = state.loss_scale.loss_scale

        # bf16-accumulated grads upcast here, at the accumulation boundary:
        # norm/clip/optimizer math is always fp32 (no-op for fp32 grads)
        grads = cast_floating(grads, jnp.float32)
        if not grads_are_unscaled:
            inv = 1.0 / (gas * scale)
            grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
        finite = all_finite(grads) if self.fp16 else jnp.asarray(True)
        gnorm = global_norm(grads)
        # Health probes (diagnostics/health.py) on the raw unscaled/unclipped
        # gradients — extends the finite/gnorm this step already computes,
        # never a second fetch. skip_step-policy signals gate the update off
        # inside the program, exactly like the fp16 overflow skip.
        health_metrics: Dict[str, Any] = {}
        new_health = state.health
        apply_ok = finite
        if self._health is not None and state.health is not None:
            new_health, health_metrics, hskip, _habort = self._health.probe(
                state.health, grads, gnorm, loss=loss, finite=finite)
            apply_ok = finite & ~hskip
        if clip and clip > 0:
            grads, gnorm = clip_by_global_norm(grads, clip, norm=gnorm)

        updates, new_opt = self.tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)

        # overflow / unhealthy => skip the update (reference
        # FP16_Optimizer.step overflow path, extended to health verdicts)
        def sel(new, old):
            return jax.tree_util.tree_map(lambda n, o: jnp.where(apply_ok, n, o), new, old)

        new_ls, new_step, metrics = self._post_update_bookkeeping(
            finite, gnorm, state.step, state.loss_scale, apply_ok=apply_ok)
        metrics.update(health_metrics)
        sel_params = sel(new_params, state.params)
        # Divergence sentinel (telemetry/numerics.py) on the COMMITTED input
        # params, not the freshly computed update: the inputs are at-rest
        # device buffers, bit-replicated by construction, so a digest
        # mismatch is real corruption — mid-step values are whatever GSPMD's
        # chosen collective schedule rounds them to per device (observed:
        # per-device reduction-order jitter flagging healthy steps). A
        # lax.cond samples 1-in-N steps; disabled traces no digest
        # (jaxpr-identical).
        new_numerics = state.numerics
        if (getattr(self, "_numerics_sentinel", None) is not None
                and state.numerics is not None):
            new_numerics, numerics_metrics = self._numerics_sentinel.probe(
                state.numerics, state.params, state.step)
            metrics.update(numerics_metrics)
        new_state = TrainState(
            step=new_step,
            params=sel_params,
            opt_state=sel(new_opt, state.opt_state),
            loss_scale=new_ls,
            rng=new_rng_data,
            comm_error=state.comm_error,
            health=new_health,
            numerics=new_numerics,
        )
        return new_state, metrics

    def _post_update_bookkeeping(self, finite, gnorm, step, ls_state, apply_ok=None):
        """Loss-scale advance + step counter + step metrics — shared by
        ``_update_math`` (fused / host-jit / apply paths) AND the Twin-Flow
        host program, so the overflow/bookkeeping semantics cannot drift
        between full and partial offload.

        ``apply_ok`` (default ``finite``) is whether the update actually
        applied — a health-policy skip advances neither the step counter nor
        the loss scale's notion of success... the loss scale stays keyed on
        ``finite`` alone: a healthy-but-skipped step is not an fp16 overflow
        and must not shrink the scale."""
        fp16_cfg = self.config.model.fp16
        dynamic = self.fp16 and fp16_cfg.dynamic
        apply_ok = finite if apply_ok is None else apply_ok
        new_ls = update_loss_scale(
            ls_state,
            finite,
            dynamic=dynamic,
            scale_window=fp16_cfg.loss_scale_window,
            min_scale=fp16_cfg.min_loss_scale,
            init_hysteresis=fp16_cfg.hysteresis,
            consecutive_hysteresis=fp16_cfg.consecutive_hysteresis,
        ) if self.fp16 else ls_state
        new_step = step + jnp.where(apply_ok, 1, 0).astype(jnp.int32)
        metrics = {
            "grad_norm": gnorm,
            "lr": jnp.asarray(self.lr_scheduler_fn(step), jnp.float32),
            "loss_scale": ls_state.loss_scale,
            "overflow": ~finite,
        }
        return new_ls, new_step, metrics

    # ----------------------------------------------------- offload split path
    def _build_offload_grad_step(self) -> Callable:
        """Device program: micro-batch grad accumulation only (no optimizer).

        Mirrors ``_build_train_step``'s accumulation exactly so offload runs
        match non-offload trajectories; the update happens on the host
        (reference ``zero/stage3.py:2082`` optimizer-swap step boundary)."""
        gas = self.config.gradient_accumulation_steps
        grad_pspecs = self.grad_sharding
        # Twin-Flow's stats/partition programs assume fp32 grads; plain
        # offload honors the bf16-accumulation knob (upcast in _update_math)
        accum_dtype = jnp.float32 if self._twin_ratio is not None else self._accum_dtype

        def grad_step(compute_params, batch, scale, step_rng):
            step_rng = jax.random.wrap_key_data(step_rng)

            def scaled_loss(p, micro, r):
                loss, _aux = self._loss_and_aux(p, micro, r)
                return (loss.astype(jnp.float32) * scale).astype(self.compute_dtype if self.fp16 else jnp.float32), loss

            grad_fn = jax.value_and_grad(scaled_loss, has_aux=True)

            def micro_step(carry, micro_batch):
                acc, i = carry
                (_, loss), grads = grad_fn(compute_params, micro_batch, jax.random.fold_in(step_rng, i))
                grads = cast_floating(grads, accum_dtype)
                acc = jax.tree_util.tree_map(lambda a, g: (a + g).astype(accum_dtype), acc, grads)
                acc = jax.lax.with_sharding_constraint(acc, grad_pspecs)
                return (acc, i + 1), loss

            zero_grads = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, accum_dtype), compute_params
            )
            zero_grads = jax.lax.with_sharding_constraint(zero_grads, grad_pspecs)
            if gas == 1:
                (grads, _), losses = micro_step((zero_grads, 0), jax.tree_util.tree_map(lambda x: x[0], batch))
                losses = losses[None]
            else:
                (grads, _), losses = jax.lax.scan(micro_step, (zero_grads, 0), batch)
            return grads, losses

        return jax.jit(grad_step)

    def _build_offload_update_step(self) -> Callable:
        """Host program: scale/clip/update on the CPU-committed master state.

        Emits the next step's bf16 compute params so only 2 bytes/param
        return to the accelerator (the reference ships fp16 params back from
        the CPU optimizer the same way)."""
        def update(state: TrainState, grads):
            rng = jax.random.wrap_key_data(state.rng)
            rng, _ = jax.random.split(rng)  # same key advance as the fused step
            new_state, metrics = self._update_math(state, grads, jax.random.key_data(rng))
            compute_16 = cast_floating(new_state.params, self.compute_dtype)
            return new_state, compute_16, metrics

        return jax.jit(update)  # inputs committed to the host device => runs on the cpu backend

    def _dev_replicated(self, x):
        """Commit a small host scalar/key to the mesh (explicit target — a
        bare device_put is a NO-OP for arrays already committed to the host
        device)."""
        return jax.device_put(x, NamedSharding(self.mesh, PartitionSpec()))

    def _swapped_in_state(self) -> TrainState:
        """Engine state with NVMe-resident optimizer moments read back in."""
        state = self.state
        if self._opt_on_nvme:
            state = state._replace(opt_state=self._opt_swapper.swap_in_opt_state(device_put=False))
        return state

    # ------------------------------------------------ Twin-Flow (partial) --
    def _tf_partition(self, tree, host_side: bool):
        """One partition's view of a params-shaped tree: out-of-partition
        leaves become 0-d numpy zeros (uncommitted, never read by the masked
        optimizer) so each program's inputs live on ONE backend."""
        keep = self._tf_host_mask if host_side else self._tf_dev_mask
        return jax.tree_util.tree_map(
            lambda m, x: x if m else np.zeros((), x.dtype), keep, tree)

    def _tf_merge(self, host_tree, dev_tree):
        """Re-assemble a full params-shaped tree from the two partition
        views (dummy leaves from each side are dropped)."""
        return jax.tree_util.tree_map(
            lambda m, h, d: h if m else d, self._tf_host_mask, host_tree, dev_tree)

    def _tf_refresh_compute(self, host_16, dev_16):
        """Merged on-accelerator bf16 compute params: the host partition's
        refresh crosses H2D into its mesh placement; the device partition's
        is already there."""
        host16_dev = jax.tree_util.tree_map(
            lambda m, x, sh: jax.device_put(x, sh) if m else x,
            self._tf_host_mask, host_16, self._device_param_sharding)
        return self._tf_merge(host16_dev, dev_16)

    def _build_twin_flow_steps(self) -> None:
        """The three Twin-Flow programs (reference ZeRO-Offload++): a device
        stats pass (finite + global norm over the FULL gradient, so clipping
        stays mathematically identical to the fused step), a fused on-device
        update for the device partition, and the host-jit update + bookkeeping
        for the host partition."""
        gas = self.config.gradient_accumulation_steps
        clip = self.config.gradient_clipping

        def stats(grads, inv):
            finite = all_finite(grads) if self.fp16 else jnp.asarray(True)
            # norm is 1-homogeneous: norm(g * inv) == norm(g) * inv
            return finite, global_norm(grads) * inv

        def _clipped(grads_sub, inv, gnorm):
            g = jax.tree_util.tree_map(lambda x: x * inv, grads_sub)
            if clip and clip > 0:
                g, _ = clip_by_global_norm(g, clip, norm=gnorm)
            return g

        def dev_update(params_sub, opt_dev, grads_sub, inv, finite, gnorm):
            g = _clipped(grads_sub, inv, gnorm)
            updates, new_opt = self._tf_tx_dev.update(g, opt_dev, params_sub)
            new_params = optax.apply_updates(params_sub, updates)
            sel = lambda n, o: jax.tree_util.tree_map(  # noqa: E731
                lambda a, b: jnp.where(finite, a, b), n, o)
            new_params = sel(new_params, params_sub)
            new_opt = sel(new_opt, opt_dev)
            return new_params, new_opt, cast_floating(new_params, self.compute_dtype)

        def host_update(params_sub, opt_host, grads_sub, step, ls_state, rng_data,
                        finite, gnorm):
            rng = jax.random.wrap_key_data(rng_data)
            rng, _ = jax.random.split(rng)  # same key advance as the fused step
            inv = 1.0 / (gas * ls_state.loss_scale)
            g = _clipped(grads_sub, inv, gnorm)
            updates, new_opt = self._tf_tx_host.update(g, opt_host, params_sub)
            new_params = optax.apply_updates(params_sub, updates)
            sel = lambda n, o: jax.tree_util.tree_map(  # noqa: E731
                lambda a, b: jnp.where(finite, a, b), n, o)
            new_params = sel(new_params, params_sub)
            new_opt = sel(new_opt, opt_host)
            new_ls, new_step, metrics = self._post_update_bookkeeping(
                finite, gnorm, step, ls_state)
            return (new_params, new_opt, new_step, new_ls,
                    jax.random.key_data(rng), metrics,
                    cast_floating(new_params, self.compute_dtype))

        self._tf_stats = jax.jit(stats)
        self._tf_dev_update = jax.jit(dev_update)
        self._tf_host_update = jax.jit(host_update)  # host-committed inputs => cpu backend

    def _tf_apply_update(self, state: TrainState, grads) -> Dict[str, Any]:
        """Twin-Flow step tail: device partition updates on-accelerator; only
        the host partition's gradients cross to the CPU and only its bf16
        refresh crosses back (the Twin-Flow win over full offload)."""
        from jax.sharding import SingleDeviceSharding

        host_sh = SingleDeviceSharding(self._host_device)
        scale = float(jax.device_get(state.loss_scale.loss_scale))
        inv = 1.0 / (self.config.gradient_accumulation_steps * scale)
        finite, gnorm = self._tf_stats(grads, inv)

        dev_grads = self._tf_partition(grads, host_side=False)
        host_grads = jax.tree_util.tree_map(
            lambda m, x: jax.device_put(x, host_sh) if m else np.zeros((), x.dtype),
            self._tf_host_mask, grads)

        opt_host, opt_dev = state.opt_state
        new_dev_params, new_opt_dev, dev_16 = self._tf_dev_update(
            self._tf_partition(state.params, host_side=False), opt_dev,
            dev_grads, inv, finite, gnorm)
        finite_h = jax.device_get(finite)
        gnorm_h = jax.device_get(gnorm)
        (new_host_params, new_opt_host, new_step, new_ls, new_rng, metrics,
         host_16) = self._tf_host_update(
            self._tf_partition(state.params, host_side=True), opt_host,
            host_grads, state.step, state.loss_scale, state.rng,
            finite_h, gnorm_h)

        overflow = bool(jax.device_get(metrics["overflow"]))
        if not overflow:
            self._compute_dev = self._tf_refresh_compute(host_16, dev_16)
        self.state = TrainState(
            step=new_step,
            params=self._tf_merge(new_host_params, new_dev_params),
            opt_state=(new_opt_host, new_opt_dev),
            loss_scale=new_ls,
            rng=new_rng,
            comm_error=state.comm_error,
            health=state.health,
            numerics=state.numerics,
        )
        return metrics

    def _offload_apply_update(self, state: TrainState, grads) -> Dict[str, Any]:
        """Host update + device bf16 refresh + NVMe swap-out (shared by the
        train_batch fast path and the forward/backward/step parity path)."""
        if self._twin_ratio is not None:
            return self._tf_apply_update(state, grads)
        from jax.sharding import SingleDeviceSharding

        host_sh = SingleDeviceSharding(self._host_device)
        grads_host = jax.device_put(grads, jax.tree_util.tree_map(lambda _: host_sh, grads))
        new_state, compute_16, metrics = self._offload_update_step(state, grads_host)
        overflow = bool(jax.device_get(metrics["overflow"]))
        if not overflow:
            self._compute_dev = jax.device_put(compute_16, self._device_param_sharding)
        if self.offload_mode == "nvme":
            self._opt_swapper.swap_out_opt_state(new_state.opt_state)
            new_state = new_state._replace(opt_state=None)
            self._opt_on_nvme = True
        self.state = new_state
        if self._offload_param_cfg and self._offload_param_cfg.device != "none":
            # ZeRO-Infinity param offload: nothing persists on the device
            # between steps; bf16 params re-stream next step.
            self._compute_dev = None
        return metrics

    def _offload_train_batch(self, placed) -> Dict[str, Any]:
        state = self._swapped_in_state()
        # same split as the fused step: step_rng drives dropout, rng advances
        step_rng = jax.random.split(jax.random.wrap_key_data(state.rng))[1]
        self._materialize_compute_dev()
        scale = self._dev_replicated(jnp.float32(jax.device_get(state.loss_scale.loss_scale)))
        # the split step HAS separable phases: device grad program vs host
        # optimizer update — the telemetry spans reflect that
        with self._tracer.span("fwd_bwd", offload=True):
            grads, losses = self._offload_grad_step(
                self._compute_dev, placed, scale, self._dev_replicated(jax.random.key_data(step_rng))
            )
        with self._tracer.span("step", offload=True):
            metrics = dict(self._offload_apply_update(state, grads))
        metrics["loss"] = jnp.mean(losses.astype(jnp.float32))
        return metrics

    def _materialize_compute_dev(self):
        """Ensure bf16 compute params exist on the accelerator; returns them."""
        if self._compute_dev is None:
            cast = jax.jit(functools.partial(cast_floating, dtype=self.compute_dtype))
            if self._twin_ratio is not None:
                # mixed master placement: one jit per partition's backend
                host_16 = cast(self._tf_partition(self.state.params, host_side=True))
                dev_16 = cast(self._tf_partition(self.state.params, host_side=False))
                self._compute_dev = self._tf_refresh_compute(host_16, dev_16)
            else:
                self._compute_dev = jax.device_put(
                    cast(self.state.params), self._device_param_sharding)
        return self._compute_dev

    def materialize_state(self) -> None:
        """Bring NVMe-swapped optimizer state back into ``self.state`` (for
        checkpointing or direct inspection)."""
        if self.offload_mode == "nvme" and self._opt_on_nvme:
            self.state = self.state._replace(opt_state=self._opt_swapper.swap_in_opt_state(device_put=False))
            self._opt_on_nvme = False

    # ------------------------------------- checkpoint-canonical opt_state --
    def canonical_opt_state(self, opt_state: Any = None) -> Any:
        """Checkpoint-boundary canonical form of ``opt_state``.

        Twin-Flow stores the optimizer state as a tuple of two
        ``optax.masked`` partition states whose ``MaskedNode`` hole placement
        depends on ``offload_optimizer.ratio`` and tree-flatten order — a
        partitioning artifact that must never leak into checkpoints (the
        reference's universal format is partitioning-independent fp32 atoms).
        This merges the two complementary partitions back into the single
        param-shaped moment tree ``self.tx.init(params)`` would produce, so a
        checkpoint saved under any ratio restores under any other ratio or
        into a non-Twin-Flow engine. Identity for non-Twin-Flow engines.
        """
        opt_state = self.state.opt_state if opt_state is None else opt_state
        if self._twin_ratio is None:
            return opt_state
        opt_host, opt_dev = opt_state
        hole = lambda x: isinstance(x, optax.MaskedNode)  # noqa: E731
        return jax.tree_util.tree_map(
            lambda h, d: d if isinstance(h, optax.MaskedNode) else h,
            opt_host.inner_state, opt_dev.inner_state, is_leaf=hole)

    def opt_state_from_canonical(self, canonical: Any) -> Any:
        """Inverse of ``canonical_opt_state``: re-partition a param-shaped
        moment tree into this engine's Twin-Flow ``(host, device)`` masked
        pair (hole placement taken from the live state, so the split follows
        THIS engine's ratio, not the saving engine's). Identity when
        Twin-Flow is off."""
        if self._twin_ratio is None:
            return canonical
        from jax.sharding import SingleDeviceSharding

        host_sh = SingleDeviceSharding(self._host_device)
        hole = lambda x: isinstance(x, optax.MaskedNode)  # noqa: E731

        def refill(template, host_side):
            def fill(t, c):
                if isinstance(t, optax.MaskedNode):
                    return t
                # The live partition states come from jit-ing the masked
                # inits, whose outputs are UNCOMMITTED — the device program
                # mixes mesh-committed params with them, which only composes
                # while the moments stay uncommitted. Restored arrays arrive
                # committed (orbax places them), so rebuild each leaf the way
                # init placed it: host partition committed to the host
                # backend, device partition uncommitted on the default device.
                v = jnp.asarray(np.asarray(jax.device_get(c)))
                return jax.device_put(v, host_sh) if host_side else v

            inner = jax.tree_util.tree_map(
                fill, template.inner_state, canonical, is_leaf=hole)
            return optax.MaskedState(inner)

        opt_host, opt_dev = self.state.opt_state
        return (refill(opt_host, True), refill(opt_dev, False))

    # ------------------------------------------------------------- data path
    def _leaf_batch_sharding(self, x, leading_none: int = 0) -> NamedSharding:
        """Rank-aware batch sharding for one array leaf.

        The batch dim shards over (dp, fsdp); the following (sequence) dim
        shards over sp only when the leaf has one and it divides evenly.
        """
        from deepspeed_tpu.topology.mesh import BATCH_AXES

        mesh = self.mesh
        batch_axes = tuple(a for a in BATCH_AXES if mesh.shape[a] > 1)
        entries: list = [None] * leading_none + [batch_axes if batch_axes else None]
        sp = mesh.shape["sp"]
        seq_dim = leading_none + 1
        if sp > 1 and x.ndim > seq_dim and x.shape[seq_dim] % sp == 0 and x.shape[seq_dim] > 1:
            entries.append("sp")
        return NamedSharding(mesh, PartitionSpec(*entries))

    def _place_batch(self, batch, leading_none: int = 0) -> Any:
        return jax.device_put(
            batch,
            jax.tree_util.tree_map(lambda x: self._leaf_batch_sharding(x, leading_none), batch),
        )

    def _shard_global_batch(self, batch) -> Any:
        """[global_batch, ...] -> [gas, micro*dp, ...] placed on the mesh."""
        gas = self.config.gradient_accumulation_steps

        def reshape(x):
            x = jnp.asarray(x)
            if x.shape[0] != self.config.train_batch_size:
                raise ValueError(
                    f"batch leading dim {x.shape[0]} != train_batch_size {self.config.train_batch_size}"
                )
            return x.reshape((gas, x.shape[0] // gas) + x.shape[1:])

        return self._place_batch(jax.tree_util.tree_map(reshape, batch), leading_none=1)

    def _stack_micro_batches(self, data_iter: Iterator) -> Any:
        gas = self.config.gradient_accumulation_steps
        micros = [next(data_iter) for _ in range(gas)]
        batch = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *micros)
        return self._place_batch(batch, leading_none=1)

    # ------------------------------------------------------------ public API
    def train_batch(self, batch: Any = None, data_iter: Optional[Iterator] = None) -> Dict[str, Any]:
        """One full optimizer step over ``train_batch_size`` samples.

        Pass either a global batch (leading dim = train_batch_size) or an
        iterator yielding micro-batches (leading dim = micro*dp_world), the
        reference ``PipelineEngine.train_batch(data_iter)`` convention.
        """
        with self._tracer.span("train_batch", step=self._batch_count):
            return self._train_batch_inner(batch, data_iter)

    def _train_batch_inner(self, batch: Any, data_iter: Optional[Iterator]) -> Dict[str, Any]:
        if (batch is None) == (data_iter is None):
            raise ValueError("provide exactly one of batch= or data_iter=")
        set_mesh(self.mesh)  # models read the active mesh at trace time
        with self._tracer.span("data"):
            if batch is not None:
                placed = self._shard_global_batch(batch)
            else:
                placed = self._stack_micro_batches(data_iter)
            if getattr(self, "_moe_autotune", None) is not None:
                placed = self._moe_autotune_batch_key(placed)
        prof = self.flops_profiler
        fp_cfg = prof.config
        config_fire = (fp_cfg.enabled and prof.result is None
                       and self._batch_count >= fp_cfg.profile_step)
        # step wall-clock for the anomaly detector (same honesty caveat as the
        # spans: dispatch time under async dispatch unless sync_spans drains)
        diag_t0 = time.perf_counter() if self.diagnostics is not None else None
        if self.diagnostics is not None:
            # an armed profiler-capture window starts here so the device
            # trace brackets whole step dispatches
            self.diagnostics.before_step(self._batch_count + 1)
        if self._train_step is None:  # offload split path
            if (prof.armed or config_fire) and not getattr(self, "_offload_prof_warned", False):
                logger.warning(
                    "flops profiler is not supported with optimizer offload "
                    "(the step is split across backends); skipping profile"
                )
                prof.armed = False
                self._offload_prof_warned = True
            self.throughput_timer.start()
            metrics = self._offload_train_batch(placed)
            self.throughput_timer.stop()
        elif prof.armed or config_fire:
            # profile this step's compiled program (reference FlopsProfiler
            # hooks the fwd at profile_step; here it is XLA cost analysis).
            # `result is None` guard: fires once even if global_steps stalls
            # on fp16 overflow-skipped steps. The profiled execution IS the
            # training step for this batch (no double-step, no state copy);
            # the throughput timer skips it — compile/analysis time would
            # poison the samples/sec history.
            self.state, metrics = prof.profile_engine_step(placed)
            prof.print_model_profile(top=fp_cfg.top_modules)
        else:
            self.throughput_timer.start()
            # the fused program has no separable fwd/bwd/step phases — this
            # span is the whole optimizer step (dispatch time unless
            # telemetry.sync_spans drains the device queue)
            with self._tracer.span("step", fused=True):
                self.state, metrics = self._train_step(self.state, placed)
            self.throughput_timer.stop()
        # Metrics stay device-side: fetching them here would block the host on
        # the step and break JAX async dispatch (measured 743 ms -> 102 ms per
        # step on v5e for the 125M bench). Callers that want numbers call
        # ``float()``/``np.asarray`` on the returned leaves.
        self.losses = metrics["loss"]
        self._batch_count += 1
        step = self._batch_count
        # /healthz + fleet-heartbeat liveness breadcrumb (two plain writes)
        _fleet_note_step(step)
        if self.diagnostics is not None:
            # flight-recorder ring append (device refs, no fetch) + step-time
            # anomaly observe + the abort-policy check (which may raise)
            self.diagnostics.after_step(
                step, metrics, step_time_s=time.perf_counter() - diag_t0)
        if self.snapshot_manager is not None:
            # AFTER the abort check: a step the health policy aborted must
            # never become the snapshot the recovery loop rewinds to
            self.snapshot_manager.after_step(step)
        if self._coll_observatory is not None:
            # sampled (1-in-N) timed probes of the routed collective
            # signatures — standalone dispatches, the step program untouched
            self._coll_observatory.on_step(step)
        if self._numerics is not None:
            # sampled wire-fidelity probes + the divergence-sentinel fold
            # (which may raise under the abort policy)
            self._numerics_on_step(step)
        if self.monitor is not None:
            scalars = {
                "Train/loss": metrics["loss"],
                "Train/lr": metrics["lr"],
                **({"Train/loss_scale": metrics["loss_scale"]} if self.fp16 else {}),
            }
            if self._health is not None:
                scalars.update({
                    f"Health/{k[len('health/'):]}": metrics[k]
                    for k in ("health/skip", "health/grad_zscore",
                              "health/nonfinite_total")
                    if k in metrics})
            # MoE dispatch gauges (device-computed inside the step; ride the
            # buffered bulk fetch with every other monitor scalar)
            scalars.update({
                f"Moe/{k[len('moe/'):]}": metrics[k]
                for k in _MOE_METRIC_KEYS if k in metrics})
            if self._tracer.enabled:
                # host-side floats only (counter deltas, memory watermarks,
                # last phase wall times) — never a device fetch
                scalars.update(self._tracer.step_scalars())
            self._monitor_pending.append((step, scalars))
        if step % self.config.model.steps_per_print == 0:
            # periodic sync point: one fetch per steps_per_print batches
            fetched = jax.device_get(metrics)
            if getattr(self, "_moe_autotune", None) is not None:
                # controller tick: the fetch already paid the sync, the
                # adjustment is pure host arithmetic on the step's gauges
                self._moe_autotune_update(fetched)
            if self._tracer.enabled:
                # moe/* registry gauges refresh at the existing sync cadence
                # (ROADMAP item 4 instrumentation: capacity/drops/balance in
                # the same exposition as every other subsystem)
                for k in _MOE_METRIC_KEYS:
                    if k in fetched:
                        self._tracer.registry.gauge(k).set(float(fetched[k]))
            log_dist(
                f"step={step} loss={float(fetched['loss']):.4f} lr={float(fetched['lr']):.3e} "
                f"grad_norm={float(fetched['grad_norm']):.3f}",
                ranks=[0],
            )
            self.flush_monitor()
            if self.config.model.memory_breakdown:
                from deepspeed_tpu.utils.memory import see_memory_usage

                see_memory_usage(f"after step {step}", force=True)
        return metrics

    def flush_monitor(self) -> None:
        """Write buffered scalars to the monitor (one bulk device fetch) and
        any configured telemetry exports."""
        if self._tracer.enabled:
            self._tracer.maybe_export()
        if self.monitor is None or not self._monitor_pending:
            self._monitor_pending = []
            return
        with self._tracer.span("flush_monitor"):
            pending, self._monitor_pending = self._monitor_pending, []
            for step, scalars in jax.device_get(pending):
                self.monitor.write_scalars(int(step), {k: float(v) for k, v in scalars.items()})

    def __del__(self):  # pragma: no cover - interpreter teardown ordering
        try:
            self.flush_monitor()
        except Exception:
            pass

    # --- forward / backward / step parity path ----------------------------
    def forward(self, batch: Any) -> Any:
        """Inference/eval forward returning model outputs (loss by default)."""
        with self._tracer.span("fwd"):
            return self._forward_inner(batch)

    def _forward_inner(self, batch: Any) -> Any:
        set_mesh(self.mesh)
        offload_split = self._train_step is None
        if self._eval_step is None:
            if offload_split:
                def eval_fn(params, batch, rng):
                    loss, aux = self._loss_and_aux(params, batch, jax.random.wrap_key_data(rng))
                    return (loss, *aux) if aux else loss

                self._eval_step = self._wrap_jit(
                    "eval_step", jax.jit(eval_fn), ("params", "batch", "rng"))
            else:
                def eval_fn(params, batch, rng):
                    loss, aux = self._loss_and_aux(self._compute_params(params), batch, jax.random.wrap_key_data(rng))
                    return (loss, *aux) if aux else loss

                self._eval_step = self._wrap_jit(
                    "eval_step",
                    jax.jit(eval_fn, in_shardings=(self.param_sharding, None, None)),
                    ("params", "batch", "rng"))
        placed = self._place_batch(jax.tree_util.tree_map(jnp.asarray, batch))
        self._last_batch = placed
        if offload_split:
            params = self._materialize_compute_dev()
            return self._eval_step(params, placed, self._dev_replicated(self.state.rng))
        return self._eval_step(self.state.params, placed, self.state.rng)

    def eval_batch(self, batch: Any) -> Any:
        return self.forward(batch)

    def backward(self, loss: Any = None, batch: Any = None) -> None:
        """Accumulate gradients for one micro-batch.

        JAX cannot differentiate "backward from a returned loss value", so this
        recomputes forward+backward for the micro-batch (``batch`` or the one
        passed to the last ``forward``). ``train_batch`` is the efficient path.
        """
        with self._tracer.span("bwd", micro_step=self._micro_steps):
            return self._backward_inner(loss, batch)

    def _backward_inner(self, loss: Any, batch: Any) -> None:
        if self._onebit:
            raise NotImplementedError(
                "1-bit compressed gradients are only wired into train_batch "
                "(the error-feedback state lives in the fused step); use "
                "train_batch with gradient_compression"
            )
        if self._zpp and self._zpp[3]:
            raise NotImplementedError(
                "ZeRO++ LoCo is only wired into train_batch (the residual "
                "state lives in the fused step); use train_batch or drop "
                "loco_param"
            )
        set_mesh(self.mesh)
        if batch is None:
            batch = getattr(self, "_last_batch", None)
            if batch is None:
                raise RuntimeError("backward() needs a batch= or a preceding forward(batch)")
        else:
            batch = self._place_batch(jax.tree_util.tree_map(jnp.asarray, batch))
        offload_split = self._train_step is None
        if self._grad_step is None:
            grad_pspecs = self.grad_sharding

            if self._zpp:
                zpp_fn = self._build_zpp_micro_fn(*self._zpp)

                def micro_grads(params, scale, micro, rng):
                    compute = jax.lax.with_sharding_constraint(
                        cast_floating(params, self.compute_dtype), self._device_param_sharding
                    )
                    grads, loss = zpp_fn(compute, micro, scale, jax.random.key_data(rng))
                    grads = jax.lax.with_sharding_constraint(grads, grad_pspecs)
                    return loss, grads
            else:
                def micro_grads(params, scale, micro, rng):
                    def scaled(p, b, r):
                        p = p if offload_split else self._compute_params(p)
                        loss, _ = self._loss_and_aux(p, b, r)
                        return loss.astype(jnp.float32) * scale, loss

                    (_, loss), grads = jax.value_and_grad(scaled, has_aux=True)(params, micro, rng)
                    # same dtype rule as the compiled steps (Twin-Flow stays fp32)
                    acc_dt = (jnp.float32 if self._twin_ratio is not None
                              else self._accum_dtype)
                    grads = jax.lax.with_sharding_constraint(
                        cast_floating(grads, acc_dt), grad_pspecs)
                    return loss, grads

            if offload_split:
                self._grad_step = self._wrap_jit(
                    "grad_step", jax.jit(micro_grads),
                    ("params", "scale", "batch", "rng"))
            else:
                self._grad_step = self._wrap_jit(
                    "grad_step",
                    jax.jit(micro_grads, in_shardings=(self.param_sharding, None, None, None)),
                    ("params", "scale", "batch", "rng"))
            self._accum_add = jax.jit(
                lambda a, b: jax.tree_util.tree_map(jnp.add, a, b), donate_argnums=(0, 1)
            )
        rng = jax.random.fold_in(jax.random.wrap_key_data(self.state.rng), self._micro_steps)
        params_arg = self._materialize_compute_dev() if offload_split else self.state.params
        scale_arg = self.state.loss_scale.loss_scale
        if offload_split:
            rng = self._dev_replicated(rng)
            scale_arg = self._dev_replicated(jnp.float32(jax.device_get(scale_arg)))
        loss_val, grads = self._grad_step(params_arg, scale_arg, batch, rng)
        if self._pending_grads is None:
            self._pending_grads = grads
        else:
            self._pending_grads = self._accum_add(self._pending_grads, grads)
        self._pending_losses.append(loss_val)
        self._micro_steps += 1

    def step(self) -> Dict[str, Any]:
        """Apply accumulated gradients at the accumulation boundary
        (reference ``engine.step`` :2338 — no-op until gas micro-batches seen)."""
        if self._micro_steps < self.config.gradient_accumulation_steps:
            return {}
        with self._tracer.span("step"):
            return self._step_inner()

    def _step_inner(self) -> Dict[str, Any]:
        if self._pending_grads is None:
            raise RuntimeError("step() called with no accumulated gradients")
        if self._train_step is None:  # offload split: update runs on the host
            metrics = self._offload_apply_update(self._swapped_in_state(), self._pending_grads)
        else:
            if self._apply_step is None:
                self._apply_step = self._wrap_jit(
                    "apply_step", self._build_apply_step(), ("state", "grads"))
            self.state, metrics = self._apply_step(self.state, self._pending_grads)
        metrics = {k: np.asarray(v) for k, v in metrics.items()}
        if self._pending_losses:
            metrics["loss"] = np.mean([np.asarray(l, dtype=np.float32) for l in self._pending_losses])
        self._pending_grads = None
        self._pending_losses = []
        self._micro_steps = 0
        return metrics

    def _build_apply_step(self) -> Callable:
        def apply_step(state: TrainState, grads):
            # advance the key so the next accumulation cycle gets fresh dropout
            new_rng = jax.random.key_data(jax.random.split(jax.random.wrap_key_data(state.rng))[0])
            return self._update_math(state, grads, new_rng)

        return jax.jit(
            apply_step,
            in_shardings=(self.state_sharding, self.grad_sharding),
            out_shardings=(self.state_sharding, None),
            donate_argnums=(0, 1),
        )

    # ------------------------------------------------------------ accessors
    @property
    def global_steps(self) -> int:
        return int(self.state.step)

    @property
    def cur_scale(self) -> float:
        return float(self.state.loss_scale.loss_scale)

    @property
    def skipped_steps(self) -> int:
        return int(self.state.loss_scale.skipped_steps)

    def get_lr(self) -> float:
        return float(jnp.asarray(self.lr_scheduler_fn(self.state.step)))

    def get_global_grad_norm(self) -> Optional[float]:
        return None  # populated from last metrics by callers if needed

    @property
    def train_batch_size(self) -> int:
        return self.config.train_batch_size

    @property
    def train_micro_batch_size_per_gpu(self) -> int:
        return self.config.train_micro_batch_size_per_gpu

    @property
    def gradient_accumulation_steps_value(self) -> int:
        return self.config.gradient_accumulation_steps

    def module_state_dict(self) -> Any:
        """Full (gathered) fp32 params — reference ``module_state_dict``."""
        if self.offload_mode in ("host-jit", "nvme"):
            return jax.device_get(self.state.params)  # already host-resident + unsharded
        gather = jax.jit(
            lambda p: p,
            out_shardings=jax.tree_util.tree_map(
                lambda _: NamedSharding(self.mesh, PartitionSpec()), self.state.params
            ),
        )
        return jax.device_get(gather(self.state.params))

    # ------------------------------------------------------------------ I/O
    def deepspeed_io(self, dataset, batch_size: Optional[int] = None) -> Any:
        """Build the training dataloader (reference ``deepspeed_io``
        engine.py:1854). Consults the ``data_efficiency`` config: an enabled
        curriculum (``data_sampling.curriculum_learning``) installs the
        difficulty-filtered ``DeepSpeedDataSampler``."""
        from deepspeed_tpu.runtime.dataloader import DeepSpeedTPUDataLoader

        bs = batch_size or self.config.train_micro_batch_size_per_gpu * get_data_parallel_world_size(self.mesh)
        sampler = self._build_data_efficiency_sampler(dataset, bs)
        if sampler is not None and isinstance(dataset, dict) and "difficulties" in dataset:
            dataset = {k: v for k, v in dataset.items() if k != "difficulties"}
        return DeepSpeedTPUDataLoader(
            dataset,
            batch_size=bs,
            seed=self.config.model.seed,
            sampler=sampler,
        )

    def _build_data_efficiency_sampler(self, dataset, batch_size: int):
        de = self.config.model.data_efficiency
        if not de.enabled:
            return None
        ds_cfg = de.data_sampling or {}
        cl = ds_cfg.get("curriculum_learning", {})
        if not ds_cfg.get("enabled", True) or not cl.get("enabled", False):
            return None
        from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler
        from deepspeed_tpu.runtime.data_pipeline.data_sampler import DeepSpeedDataSampler

        sched = CurriculumScheduler(cl)
        difficulties = getattr(dataset, "difficulties", None)
        if difficulties is None and isinstance(dataset, dict):
            difficulties = dataset.get("difficulties")
        if difficulties is None and sched.metric == "seqlen" and isinstance(dataset, dict) \
                and "input_ids" in dataset:
            # seqlen metric default: per-sample non-pad length (the reference
            # precomputes this into an index map, data_analyzer.py)
            ids = np.asarray(dataset["input_ids"])
            mask = dataset.get("attention_mask")
            difficulties = (np.asarray(mask).sum(-1) if mask is not None
                            else np.full(len(ids), ids.shape[-1]))
        if difficulties is None:
            raise ValueError(
                "curriculum_learning needs per-sample difficulties: provide "
                "dataset.difficulties / a 'difficulties' column, or use the "
                "'seqlen' metric with an input_ids column"
            )
        n = len(np.asarray(difficulties))
        return DeepSpeedDataSampler(
            n, batch_size, difficulties=np.asarray(difficulties),
            curriculum=sched, seed=de.seed,
        )

    @functools.cached_property
    def checkpoint_engine(self):
        """Engine selected by the config ``checkpoint.engine`` key
        ('orbax' | 'async'/'nebula'; reference ``_configure_checkpointing``
        engine.py:354)."""
        from deepspeed_tpu.checkpoint.engine import get_checkpoint_engine

        return get_checkpoint_engine(self.config.model.checkpoint.get("engine", "orbax"))

    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None, client_state: Optional[Dict] = None,
                        save_latest: bool = True) -> None:
        from deepspeed_tpu.checkpoint.checkpointing import save_checkpoint as _save

        self.flush_monitor()
        self.materialize_state()
        _save(self, save_dir, tag=tag, client_state=client_state or {}, save_latest=save_latest,
              checkpoint_engine=self.checkpoint_engine)

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_optimizer_states: bool = True,
                        load_universal: bool = False) -> Tuple[Optional[str], Dict]:
        """Restore state. ``load_universal=True`` reads the mesh-independent
        atom format instead (reference ``load_universal_checkpoint`` flag).
        A directory holding only elastic snapshots (``<dir>/snapshots/``, no
        orbax ``latest``) routes to the snapshot restore path — manifest
        checksums validated before any device state is touched, previous tag
        on corruption."""
        self.materialize_state()
        if load_universal:
            from deepspeed_tpu.checkpoint.universal import load_universal as _loadu

            out = _loadu(self, load_dir, tag=tag,
                         placement=self.config.model.checkpoint.get("restore", "fresh")), {}
        elif (not os.path.exists(os.path.join(load_dir, "latest"))
              and os.path.isdir(os.path.join(load_dir, "snapshots"))):
            out = self.restore_snapshot(load_dir, tag=tag), {}
        else:
            from deepspeed_tpu.checkpoint.checkpointing import load_checkpoint as _load

            out = _load(self, load_dir, tag=tag, load_optimizer_states=load_optimizer_states)
        if self.offload_mode in ("host-jit", "nvme"):
            self._compute_dev = None  # params changed: bf16 view re-materializes
        return out

    def restore_snapshot(self, base_dir: Optional[str] = None,
                         tag: Optional[str] = None, fallback: bool = True) -> str:
        """Restore an elastic snapshot (``checkpoint/snapshot.py``) into this
        engine — any mesh, fresh committed buffers, checksum-validated with
        previous-tag fallback. Returns the tag restored."""
        self.materialize_state()
        if self.snapshot_manager is not None and (
                base_dir is None
                or os.path.abspath(base_dir)
                == os.path.abspath(self.snapshot_manager.base_dir)):
            return self.snapshot_manager.restore(tag=tag, fallback=fallback)
        if base_dir is None:
            raise ValueError("restore_snapshot needs a base_dir (no snapshot "
                             "manager configured on this engine)")
        from deepspeed_tpu.checkpoint.snapshot import restore_snapshot as _restore

        return _restore(self, base_dir, tag=tag, fallback=fallback)

    def save_universal_checkpoint(self, save_dir: str, tag: Optional[str] = None) -> str:
        """Write the mesh-independent atom checkpoint (reference
        ``checkpoint/ds_to_universal.py`` done online — no offline pass)."""
        from deepspeed_tpu.checkpoint.universal import save_universal as _saveu

        self.materialize_state()  # NVMe-swapped moments must be in the state
        return _saveu(self, save_dir, tag=tag)
