"""Sparse embedding gradients for data parallelism.

Reference analog: ``runtime/sparse_tensor.py:69 SparseTensor`` + the engine's
sparse-grad allreduce paths (``engine.py`` sparse_gradients_enabled) — for a
vocab-size embedding, a batch touches at most B*S unique rows, so syncing the
dense [V, H] gradient across DP replicas wastes ``V / (dp * B*S)`` of the
wire. The reference ships (indices, values) pairs through allgather instead.

TPU-native design: inside the jitted step, the embedding's row gradient is
computed directly as a segment-sum over the batch's token ids (never
materializing [V, H] per microbatch), and DP sync all-gathers the compact
``(ids [T], rows [T, H])`` pair over the ``dp`` axis inside shard_map; each
replica scatter-adds the gathered rows into the dense update exactly once at
the optimizer boundary. Comm volume: ``dp * T * (H + 1)`` vs ``V * H`` —
a win whenever the global batch token count is below the vocab size.

These are COMPOSABLE BUILDING BLOCKS for custom training loops (the recipe:
compute the cotangent of the embedding lookup, call
``sparse_embedding_grad_allreduce`` inside your step, feed the dense result
to the optimizer). The engine's own compiled step keeps the dense psum —
XLA fuses it and the uniform-sharding math stays one program — but it reads
``sparse_gradients: true`` and logs the :func:`should_use_sparse_embedding_grad`
verdict with this module as the pointer, so the config flag is honored with
guidance rather than silently ignored.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def embedding_row_grads(ids: jax.Array, g_x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-occurrence embedding gradient rows WITHOUT the [V, H] scatter.

    ids: [B, S] token ids; g_x: [B, S, H] cotangent of the embedding lookup.
    Returns (flat_ids [T], rows [T, H]) with T = B*S — the sparse
    representation the reference calls SparseTensor (duplicate ids allowed;
    the consumer scatter-ADDS, so duplicates sum exactly like segment-sum).
    """
    T = ids.shape[0] * ids.shape[1]
    return ids.reshape(T), g_x.reshape(T, -1)


def sparse_allgather_rows(ids: jax.Array, rows: jax.Array, axis: str = "dp"
                          ) -> Tuple[jax.Array, jax.Array]:
    """All-gather the (ids, rows) pairs over a mesh axis (must be called
    inside shard_map / under a mesh context with ``axis`` manual).

    The dense-grad equivalent would be ``psum(scatter(ids, rows))``; gathering
    the compact pairs first moves ``dp*T*(H+1)`` elements instead of ``V*H``.
    Routed through the comm facade so the telemetry/busbw log sees exactly
    the volume this path exists to shrink.
    """
    from deepspeed_tpu.comm import comm

    gids = comm.all_gather(ids, axis, concat_axis=0, tiled=True)
    grows = comm.all_gather(rows, axis, concat_axis=0, tiled=True)
    return gids, grows


def scatter_rows(ids: jax.Array, rows: jax.Array, vocab_size: int,
                 mean_over: Optional[int] = None) -> jax.Array:
    """Materialize the dense [V, H] gradient from sparse rows (one fused
    scatter-add at the optimizer boundary). ``mean_over`` divides by the
    replica count to match the mean-reduced dense-grad convention."""
    dense = jnp.zeros((vocab_size, rows.shape[-1]), rows.dtype)
    dense = dense.at[ids].add(rows)
    if mean_over:
        dense = dense / mean_over
    return dense


def sparse_embedding_grad_allreduce(ids: jax.Array, g_x: jax.Array,
                                    vocab_size: int, mesh: Mesh,
                                    axis: str = "dp") -> jax.Array:
    """The reference's sparse-grad allreduce as one shard_map program:
    local (ids, rows) -> all-gather over ``axis`` -> scatter-add -> mean.

    ids: [B_local, S]; g_x: [B_local, S, H] (batch sharded over ``axis``).
    Returns the DP-mean dense [V, H] gradient, replicated over ``axis`` —
    bitwise-comparable (up to reduction order) to ``psum`` of the dense
    per-replica gradient divided by the axis size.
    """
    dp = mesh.shape[axis]

    def f(ids_l, gx_l):
        fids, rows = embedding_row_grads(ids_l, gx_l)
        gids, grows = sparse_allgather_rows(fids, rows, axis)
        return scatter_rows(gids, grows, vocab_size, mean_over=dp)

    from deepspeed_tpu.utils.compat import shard_map

    return shard_map(
        f, mesh=mesh, axis_names={axis},
        in_specs=(P(axis), P(axis)), out_specs=P(),
        check_vma=False,
    )(ids, g_x)


# ------------------------------------------------------- compiled-step wiring

def sparse_lookup(table: jax.Array, ids: jax.Array) -> jax.Array:
    """Embedding lookup whose BACKWARD ships sparse rows through the sync.

    The engine-wired form of this module (round-5; reference
    ``runtime/sparse_tensor.py:69`` + engine sparse-grad paths
    ``runtime/engine.py:2104``): a custom-VJP around ``take`` whose backward
    runs local-rows → all-gather of the compact ``(ids [T], rows [T, H])``
    pairs over every token-sharding mesh axis → one scatter-add, replicated.
    The SPMD partitioner therefore never sees a sharded [V, H] scatter and
    inserts NO dense all-reduce — comm drops from ``V*H`` to ``T*(H+1)``
    elements. Token-sharding axes are captured from the active mesh at trace
    time; with no mesh (or a 1-device mesh) the backward degenerates to the
    plain local scatter-add.
    """
    from deepspeed_tpu.topology.mesh import get_mesh, has_mesh

    axes: Tuple[str, ...] = ()
    if has_mesh():
        mesh = get_mesh()
        axes = tuple(a for a in ("dp", "fsdp", "sp") if mesh.shape.get(a, 1) > 1)
    return _sparse_lookup(table, ids, axes)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _sparse_lookup(table, ids, token_axes):
    return jnp.take(table, ids, axis=0)


def _sparse_lookup_fwd(table, ids, token_axes):
    return jnp.take(table, ids, axis=0), (table, ids)


def _sparse_lookup_bwd(token_axes, res, g):
    table, ids = res
    V, Hd = table.shape
    ids_zero = np.zeros(ids.shape, dtype=jax.dtypes.float0)

    def local_scatter(fids, rows):
        return jnp.zeros((V, Hd), jnp.float32).at[fids].add(rows)

    if not token_axes:
        dense = local_scatter(ids.reshape(-1),
                              g.reshape(-1, Hd).astype(jnp.float32))
        return dense.astype(table.dtype), ids_zero

    from deepspeed_tpu.comm import comm
    from deepspeed_tpu.topology.mesh import get_mesh

    def gather_scatter(ids_l, g_l, axes):
        fids = ids_l.reshape(-1)
        rows = g_l.reshape(fids.shape[0], -1).astype(jnp.float32)
        for ax in axes:  # compact pairs ride the wire, not [V, H]
            fids = comm.all_gather(fids, ax, concat_axis=0, tiled=True)
            rows = comm.all_gather(rows, ax, concat_axis=0, tiled=True)
        return local_scatter(fids, rows)

    # Already inside a manual shard_map over the token axes (the ZeRO++/1-bit
    # micro fn traces the loss there)? The axis names are bound — gather
    # directly instead of nesting another shard_map. The engine's manual
    # convention is per-rank LOCAL grads that a downstream pmean / mean-RS
    # averages; our gather-scatter is already the GLOBAL sum, so divide by
    # the gathered world so that average reproduces the sum exactly.
    from jax._src import mesh as mesh_lib

    manual = set(getattr(mesh_lib.get_abstract_mesh(), "manual_axes", ()) or ())
    bound = tuple(a for a in token_axes if a in manual)
    if bound:
        from deepspeed_tpu.utils.compat import axis_size

        world = 1
        for ax in bound:
            world *= axis_size(ax)
        dense = gather_scatter(ids, g, bound) / world
        return dense.astype(table.dtype), ids_zero

    batch_axes = tuple(a for a in token_axes if a != "sp") or None
    seq_axis = "sp" if "sp" in token_axes else None
    from deepspeed_tpu.utils.compat import shard_map

    dense = shard_map(
        lambda i, gg: gather_scatter(i, gg, token_axes),
        mesh=get_mesh(),
        in_specs=(P(batch_axes, seq_axis), P(batch_axes, seq_axis, None)),
        out_specs=P(), check_vma=False,
    )(ids, g)
    return dense.astype(table.dtype), ids_zero


_sparse_lookup.defvjp(_sparse_lookup_fwd, _sparse_lookup_bwd)


def should_use_sparse_embedding_grad(vocab_size: int, global_batch_tokens: int,
                                     margin: float = 2.0) -> bool:
    """Size heuristic: sparse sync wins when the gathered rows are
    ``margin``x smaller than the dense [V, H] gradient (the +1 per row for
    ids is noise at real H)."""
    return global_batch_tokens * margin < vocab_size


def sparse_grad_comm_volume(vocab_size: int, hidden: int, dp: int,
                            local_tokens: int) -> Tuple[int, int]:
    """(dense_elems, sparse_elems) moved per sync — the reference's
    motivation table, for logging/autotuning."""
    dense = vocab_size * hidden
    sparse = dp * local_tokens * (hidden + 1)
    return dense, sparse
