"""Curvature eigenvalue estimation (power iteration).

Reference: ``runtime/eigenvalue.py:13 Eigenvalue`` — estimates the dominant
Hessian eigenvalue per layer block to schedule MoQ quantization periods. The
reference does repeated ``torch.autograd.grad`` double-backprops; in JAX the
Hessian-vector product is one ``jvp``-of-``grad`` composition and the whole
power iteration jit-compiles into a single program.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


def _normalize(tree):
    flat = jax.tree_util.tree_leaves(tree)
    norm = jnp.sqrt(sum(jnp.sum(x.astype(jnp.float32) ** 2) for x in flat))
    norm = jnp.maximum(norm, 1e-12)
    return jax.tree_util.tree_map(lambda x: x / norm, tree), norm


def hvp(loss_fn: Callable, params, vec, *batch_args):
    """Hessian-vector product: jvp of grad (forward-over-reverse)."""
    grad_fn = lambda p: jax.grad(loss_fn)(p, *batch_args)
    _, tangent = jax.jvp(grad_fn, (params,), (vec,))
    return tangent


def dominant_eigenvalue(
    loss_fn: Callable,
    params,
    *batch_args,
    iters: int = 10,
    seed: int = 0,
    tol: float = 1e-2,
) -> Tuple[float, Any]:
    """Power iteration for the dominant Hessian eigenvalue of ``loss_fn`` at
    ``params`` (reference ``Eigenvalue.compute_eigenvalue``).

    Returns (eigenvalue, eigenvector pytree). The loop is ``lax.scan`` inside
    one jit — no per-iteration dispatch.
    """
    rng = jax.random.PRNGKey(seed)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.random.split(rng, len(leaves))
    v0 = jax.tree_util.tree_unflatten(
        treedef, [jax.random.normal(k, x.shape, jnp.float32) for k, x in zip(keys, leaves)]
    )

    @jax.jit
    def run(params, v0, *args):
        v0, _ = _normalize(v0)

        def step(v):
            hv = hvp(loss_fn, params, v, *args)
            v_next, norm = _normalize(hv)
            # Rayleigh quotient == norm when converged; sign from alignment
            align = sum(
                jnp.sum(a.astype(jnp.float32) * b.astype(jnp.float32))
                for a, b in zip(jax.tree_util.tree_leaves(v), jax.tree_util.tree_leaves(v_next))
            )
            return v_next, norm * jnp.sign(align)

        def cond(carry):
            _, eig, prev, i = carry
            unconverged = jnp.abs(eig - prev) > tol * jnp.maximum(jnp.abs(eig), 1e-12)
            return (i < iters) & ((i < 2) | unconverged)

        def body(carry):
            v, eig, _, i = carry
            v_next, eig_next = step(v)
            return (v_next, eig_next, eig, i + 1)

        v, eig, _, _ = jax.lax.while_loop(
            cond, body, (v0, jnp.float32(0), jnp.float32(jnp.inf), jnp.int32(0))
        )
        return eig, v

    eig, v = run(params, v0, *batch_args)
    return float(eig), v


class Eigenvalue:
    """Config-carrying wrapper (reference ``Eigenvalue`` runtime/eigenvalue.py:13)."""

    def __init__(self, verbose: bool = False, max_iter: int = 100, tol: float = 1e-2,
                 stability: float = 1e-6, gas_boundary_resolution: int = 1,
                 layer_name: str = "", layer_num: int = 0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    def compute_eigenvalue(self, loss_fn: Callable, params, *batch_args, seed: int = 0) -> Dict[str, float]:
        """Per-block dominant eigenvalues: one power iteration per top-level
        subtree of ``params`` (the reference's per-layer blocks)."""
        out: Dict[str, float] = {}
        if isinstance(params, dict) and self.layer_num != 1:
            for name in params:
                sub = {name: params[name]}

                def sub_loss(sp, *args, _name=name):
                    full = dict(params)
                    full[_name] = sp[_name]
                    return loss_fn(full, *args)

                eig, _ = dominant_eigenvalue(
                    sub_loss, sub, *batch_args, iters=min(self.max_iter, 20), seed=seed
                )
                out[name] = abs(eig) + self.stability
        else:
            eig, _ = dominant_eigenvalue(
                loss_fn, params, *batch_args, iters=min(self.max_iter, 20), seed=seed
            )
            out["model"] = abs(eig) + self.stability
        if self.verbose:
            logger.info(f"eigenvalues: {out}")
        return out
