"""Mixed precision: dtype policy + dynamic loss scaling.

TPU-native analog of the reference fp16/bf16 wrappers
(``runtime/fp16/loss_scaler.py:91 DynamicLossScaler``,
``runtime/fp16/fused_optimizer.py:33``, ``runtime/bf16_optimizer.py:35``).
The master-fp32-copy + overflow-check + skip-step machinery is expressed as a
functional state threaded through the compiled train step: master params stay
fp32, compute happens in bf16/fp16, the scaler state updates with
``lax``-friendly arithmetic so the whole thing lives under one ``jit``.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    """Dynamic loss-scaler state (reference ``DynamicLossScaler`` semantics)."""

    loss_scale: jax.Array  # f32 scalar
    growth_tracker: jax.Array  # i32: consecutive good steps
    hysteresis: jax.Array  # i32: overflows tolerated before backoff
    skipped_steps: jax.Array  # i32: total skipped updates


def make_loss_scale_state(
    enabled: bool,
    initial_scale_power: int = 16,
    static_loss_scale: float = 0.0,
    hysteresis: int = 2,
) -> LossScaleState:
    if not enabled:
        scale = 1.0
    elif static_loss_scale and static_loss_scale > 0:
        scale = float(static_loss_scale)
    else:
        scale = float(2**initial_scale_power)
    return LossScaleState(
        loss_scale=jnp.asarray(scale, jnp.float32),
        growth_tracker=jnp.asarray(0, jnp.int32),
        hysteresis=jnp.asarray(hysteresis, jnp.int32),
        skipped_steps=jnp.asarray(0, jnp.int32),
    )


def all_finite(tree: Any) -> jax.Array:
    """True iff every element of every leaf is finite (overflow check).

    Analog of the reference's ``_has_inf_or_nan`` scan
    (``zero/stage_1_and_2.py:2038``), fused by XLA into the backward pass.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(True)
    return jnp.all(jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves]))


def update_loss_scale(
    state: LossScaleState,
    grads_finite: jax.Array,
    *,
    dynamic: bool,
    scale_window: int = 1000,
    scale_factor: float = 2.0,
    min_scale: float = 1.0,
    max_scale: float = 2.0**32,
    init_hysteresis: int = 2,
    consecutive_hysteresis: bool = False,
) -> LossScaleState:
    """One scaler update. jit-safe (no Python branching on traced values)."""
    if not dynamic:
        return state._replace(
            skipped_steps=state.skipped_steps + jnp.where(grads_finite, 0, 1).astype(jnp.int32)
        )

    # --- overflow branch ---------------------------------------------------
    hysteresis_exhausted = state.hysteresis <= 1
    overflow_scale = jnp.where(
        hysteresis_exhausted,
        jnp.maximum(state.loss_scale / scale_factor, min_scale),
        state.loss_scale,
    )
    overflow_hyst = jnp.where(hysteresis_exhausted, state.hysteresis, state.hysteresis - 1)

    # --- good-step branch --------------------------------------------------
    new_tracker = state.growth_tracker + 1
    grow = new_tracker >= scale_window
    good_scale = jnp.where(grow, jnp.minimum(state.loss_scale * scale_factor, max_scale), state.loss_scale)
    good_tracker = jnp.where(grow, 0, new_tracker).astype(jnp.int32)
    good_hyst = (
        jnp.asarray(init_hysteresis, jnp.int32) if consecutive_hysteresis else state.hysteresis
    )

    return LossScaleState(
        loss_scale=jnp.where(grads_finite, good_scale, overflow_scale).astype(jnp.float32),
        growth_tracker=jnp.where(grads_finite, good_tracker, 0).astype(jnp.int32),
        hysteresis=jnp.where(grads_finite, good_hyst, overflow_hyst).astype(jnp.int32),
        skipped_steps=state.skipped_steps + jnp.where(grads_finite, 0, 1).astype(jnp.int32),
    )


def cast_floating(tree: Any, dtype) -> Any:
    """Cast float leaves to ``dtype`` (int/bool leaves untouched)."""

    def _cast(x):
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            return jnp.asarray(x, dtype)
        return x

    return jax.tree_util.tree_map(_cast, tree)


def global_norm(tree: Any) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))


def clip_by_global_norm(tree: Any, max_norm: float, norm: jax.Array = None) -> Tuple[Any, jax.Array]:
    """Global-norm gradient clipping (reference ``runtime/utils.py clip_grad_norm_``)."""
    if norm is None:
        norm = global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda x: (x.astype(jnp.float32) * scale).astype(x.dtype), tree), norm
