"""Hybrid engine: one model flipping between training and generation (RLHF).

Reference: ``runtime/hybrid_engine.py:30 DeepSpeedHybridEngine`` — for the
DeepSpeed-Chat actor model, wraps a ZeRO-3 training engine so ``generate()``
(:168) runs through inference containers reusing the training parameters
(``_zero3_forward`` :362 gathers them), with LoRA fuse/unfuse (:135) around
the generate phase.

TPU design: the training state's master params ARE the model — ``generate``
re-places them with the inference partition rules (device-to-device reshard,
no host round-trip) and runs the v1 KV-cache generation path; ``train_batch``
delegates to the wrapped engine untouched. LoRA merge happens functionally on
the reshard (the original params are never mutated, so there is no "unfuse"
step to get wrong).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from deepspeed_tpu.inference.config import InferenceConfig
from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.utils.logging import log_dist


class DeepSpeedTPUHybridEngine:
    """Train + generate over shared parameters (reference ``DeepSpeedHybridEngine``)."""

    def __init__(
        self,
        engine,  # DeepSpeedTPUEngine
        model_config,  # TransformerConfig of the wrapped CausalLM
        inference_config: Optional[Dict] = None,
        lora_scaling: Optional[float] = None,
    ):
        self.engine = engine
        self.model_config = model_config
        self.lora_scaling = lora_scaling
        cfg = dict(inference_config or {})
        cfg.setdefault("dtype", "bf16")
        self.inference_config = InferenceConfig(**cfg)
        self._infer: Optional[InferenceEngine] = None
        self._infer_step = -1  # train step the cached view was built from
        self.total_generate_calls = 0

    # -------------------------------------------------------------- training
    def train_batch(self, *args, **kwargs):
        out = self.engine.train_batch(*args, **kwargs)
        return out

    def backward(self, *args, **kwargs):
        return self.engine.backward(*args, **kwargs)

    def step(self, *args, **kwargs):
        return self.engine.step(*args, **kwargs)

    @property
    def state(self):
        return self.engine.state

    # -------------------------------------------------------------- generate
    def _refresh_inference_view(self) -> InferenceEngine:
        """Sync the inference view to the CURRENT training params (reference:
        hybrid engine reuses training tensors in inference containers). The
        engine is built ONCE; later refreshes only re-place parameter values
        into the existing shardings so compiled generate functions stay
        cached (no retrace per RLHF iteration)."""
        params = self.engine.state.params
        if self.lora_scaling is not None:
            from deepspeed_tpu.linear.optimized_linear import lora_merge

            params = lora_merge(params, self.lora_scaling)
        if self._infer is None:
            self._infer = InferenceEngine(
                self.model_config, params, self.inference_config, mesh=self.engine.mesh
            )
        else:
            self._infer.refresh_params(params)
        self._infer_step = self.engine.global_steps
        return self._infer

    def generate(self, input_ids, **kwargs) -> np.ndarray:
        """Generate with the newest weights (reference ``generate`` :168)."""
        if self._infer is None or self._infer_step != self.engine.global_steps:
            self._refresh_inference_view()
        self.total_generate_calls += 1
        return self._infer.generate(input_ids, **kwargs)

    def eval(self):  # torch-API parity no-ops (reference flips module modes)
        return self

    def train(self):
        return self
