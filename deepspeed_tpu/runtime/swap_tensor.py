"""Tensor swapping to disk (ZeRO-Infinity NVMe offload).

Reference: ``runtime/swap_tensor/`` — ``AsyncTensorSwapper``
(async_swapper.py:19), ``PartitionedOptimizerSwapper``
(partitioned_optimizer_swapper.py:29), ``AsyncPartitionedParameterSwapper``
(partitioned_param_swapper.py:37). The capability: keep optimizer state (or
params) on NVMe, stream them in/out around the step, overlap IO with compute.

TPU design: a pytree swapper over the native AIO pool (``ops/aio.py``).
Swap-out is fully async (device→host copy on the caller thread — cheap with
JAX async dispatch — then background pwrite); swap-in prefetch is async with
a blocking ``wait``. One file per pytree leaf under a swap folder, float
leaves optionally stored bf16 (the reference's fp16 NVMe buffers).
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.aio import AioHandle
from deepspeed_tpu.utils.logging import log_dist, logger


def _leaf_path(folder: str, key: str) -> str:
    return os.path.join(folder, "leaf_" + "".join(c if c.isalnum() else "_" for c in key) + ".bin")


class AsyncTensorSwapper:
    """Swap pytrees between device/host and disk (reference async_swapper)."""

    def __init__(self, swap_folder: str, num_threads: int = 4):
        self.swap_folder = swap_folder
        os.makedirs(swap_folder, exist_ok=True)
        self.handle = AioHandle(num_threads=num_threads)
        self._pending: Dict[str, list] = {}  # tag -> [req ids]
        self._meta: Dict[str, Any] = {}  # tag -> (treedef, [(key, shape, dtype)])

    # ------------------------------------------------------------ swap out
    def swap_out(self, tag: str, tree: Any, wait: bool = False) -> None:
        """Write a pytree to disk under ``tag`` (async unless wait=True)."""
        if tag in self._pending:
            # a previous swap_out of this tag may still be writing the same
            # files — drain it or the two writes could land out of order
            self.wait(tag)
        folder = os.path.join(self.swap_folder, tag)
        os.makedirs(folder, exist_ok=True)
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        reqs, meta = [], []
        host = jax.device_get(tree)  # one batched transfer
        for (path, _), leaf in zip(flat, jax.tree_util.tree_leaves(host)):
            key = jax.tree_util.keystr(path)
            arr = np.ascontiguousarray(leaf)
            fpath = _leaf_path(folder, key)
            reqs.append(self.handle.async_pwrite(arr, fpath))
            # keep the dtype OBJECT: ml_dtypes (bfloat16) have no portable str
            meta.append((key, arr.shape, arr.dtype, fpath))
        self._pending[tag] = reqs
        self._meta[tag] = (treedef, meta)
        if wait:
            self.wait(tag)

    # ------------------------------------------------------------ swap in
    def swap_in_begin(self, tag: str) -> Any:
        """Issue the async reads for ``tag``; returns an opaque token for
        ``swap_in_end``. The double-buffered prefetch primitive (reference
        ``partitioned_param_swapper`` prefetch path): begin layer l+1's reads
        while the device computes layer l."""
        if tag not in self._meta:
            raise KeyError(f"no swapped state under tag {tag!r}")
        self.wait(tag)  # writes must be durable before reading
        treedef, meta = self._meta[tag]
        bufs, reqs = [], []
        for key, shape, dtype, fpath in meta:
            buf = np.empty(shape, dtype=dtype)
            reqs.append(self.handle.async_pread(buf, fpath))
            bufs.append(buf)
        return (treedef, bufs, reqs)

    def swap_in_end(self, token: Any, like: Any = None, device_put: bool = True) -> Any:
        """Block until the reads issued by ``swap_in_begin`` complete; returns
        the pytree (device-placed per ``like``/``device_put``)."""
        treedef, bufs, reqs = token
        for r in reqs:
            self.handle.wait(r)
        tree = jax.tree_util.tree_unflatten(treedef, bufs)
        if like is not None:
            tree = jax.tree_util.tree_map(
                lambda host, ref: jax.device_put(jnp.asarray(host, ref.dtype), ref.sharding)
                if isinstance(ref, jax.Array) else host,
                tree, like,
            )
        elif device_put:
            tree = jax.tree_util.tree_map(jnp.asarray, tree)
        return tree

    def swap_in(self, tag: str, like: Any = None, device_put: bool = True) -> Any:
        """Read the pytree stored under ``tag``; shardings taken from ``like``
        when given (reference swap-in re-pins to the gpu buffers)."""
        return self.swap_in_end(self.swap_in_begin(tag), like=like, device_put=device_put)

    def wait(self, tag: str) -> None:
        for r in self._pending.pop(tag, []):
            self.handle.wait(r)

    def release(self, tag: str) -> None:
        """Free the disk space for ``tag``."""
        self.wait(tag)
        self._meta.pop(tag, None)
        shutil.rmtree(os.path.join(self.swap_folder, tag), ignore_errors=True)

    def close(self) -> None:
        for tag in list(self._pending):
            self.wait(tag)
        self.handle.close()


class OptimizerStateSwapper:
    """Keep optimizer state on disk between steps (reference
    ``PartitionedOptimizerSwapper``/``PipelinedOptimizerSwapper``).

    Usage around a step:
        opt_state = swapper.swap_in_opt_state(like=shapes)
        new_state, ... = step(params, opt_state, ...)
        swapper.swap_out_opt_state(new_state)   # async; overlaps next fwd
    """

    TAG = "optimizer_state"

    def __init__(self, swap_folder: str, num_threads: int = 4):
        self.swapper = AsyncTensorSwapper(swap_folder, num_threads)
        self._has_state = False

    def swap_out_opt_state(self, opt_state: Any, wait: bool = False) -> None:
        self.swapper.swap_out(self.TAG, opt_state, wait=wait)
        self._has_state = True

    def swap_in_opt_state(self, like: Any = None, device_put: bool = True) -> Any:
        """``device_put=False`` returns host (numpy) leaves — what a
        host-committed optimizer update wants (ZeRO-Offload CPU step)."""
        if not self._has_state:
            raise RuntimeError("no optimizer state swapped out yet")
        return self.swapper.swap_in(self.TAG, like=like, device_put=device_put)

    def close(self) -> None:
        self.swapper.close()
