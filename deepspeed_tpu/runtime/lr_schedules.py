"""LR schedules with the reference's parameter surface.

TPU-native analog of ``deepspeed/runtime/lr_schedules.py`` (``VALID_LR_SCHEDULES``
:23 — LRRangeTest, OneCycle, WarmupLR, WarmupDecayLR, WarmupCosineLR). Each
schedule is a jit-safe ``step -> lr`` callable (an optax schedule), so it can
live inside the compiled train step instead of mutating optimizer state from
Python each iteration.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp

Schedule = Callable[[Any], Any]

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR, WARMUP_COSINE_LR]


def warmup_lr(
    warmup_min_lr: float = 0.0,
    warmup_max_lr: float = 0.001,
    warmup_num_steps: int = 1000,
    warmup_type: str = "log",
    **_: Any,
) -> Schedule:
    """Warmup then constant (reference ``WarmupLR``)."""
    warmup_num_steps = max(2, warmup_num_steps)
    log_den = math.log(warmup_num_steps)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        if warmup_type == "log":
            frac = jnp.log(jnp.maximum(step, 1.0)) / log_den
        else:
            frac = step / warmup_num_steps
        frac = jnp.clip(frac, 0.0, 1.0)
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * frac

    return schedule


def warmup_decay_lr(
    total_num_steps: int,
    warmup_min_lr: float = 0.0,
    warmup_max_lr: float = 0.001,
    warmup_num_steps: int = 1000,
    warmup_type: str = "log",
    **_: Any,
) -> Schedule:
    """Warmup then linear decay to 0 (reference ``WarmupDecayLR``)."""
    warm = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        decay = jnp.clip(
            (total_num_steps - step) / jnp.maximum(float(total_num_steps - warmup_num_steps), 1.0),
            0.0,
            1.0,
        )
        return jnp.where(step < warmup_num_steps, warm(step), warmup_max_lr * decay)

    return schedule


def warmup_cosine_lr(
    total_num_steps: int,
    warmup_min_ratio: float = 0.0,
    warmup_num_steps: int = 1000,
    cos_min_ratio: float = 0.0001,
    warmup_type: str = "log",
    base_lr: float = 0.001,
    **_: Any,
) -> Schedule:
    """Warmup (ratio of base lr) then cosine decay (reference ``WarmupCosineLR``)."""
    warm = warmup_lr(warmup_min_ratio * base_lr, base_lr, warmup_num_steps, warmup_type)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        progress = jnp.clip(
            (step - warmup_num_steps) / jnp.maximum(float(total_num_steps - warmup_num_steps), 1.0),
            0.0,
            1.0,
        )
        cos = cos_min_ratio + (1 - cos_min_ratio) * 0.5 * (1 + jnp.cos(jnp.pi * progress))
        return jnp.where(step < warmup_num_steps, warm(step), base_lr * cos)

    return schedule


def one_cycle(
    cycle_min_lr: float,
    cycle_max_lr: float,
    cycle_first_step_size: int = 2000,
    cycle_second_step_size: Optional[int] = None,
    decay_step_size: int = 0,
    decay_lr_rate: float = 0.0,
    cycle_first_stair_count: int = 0,
    cycle_second_stair_count: Optional[int] = None,
    **_: Any,
) -> Schedule:
    """Triangular cycle then optional decay (reference ``OneCycle``)."""
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    cycle_len = cycle_first_step_size + second

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        up = jnp.clip(step / cycle_first_step_size, 0.0, 1.0)
        down = jnp.clip((step - cycle_first_step_size) / max(second, 1), 0.0, 1.0)
        in_cycle_lr = jnp.where(
            step <= cycle_first_step_size,
            cycle_min_lr + (cycle_max_lr - cycle_min_lr) * up,
            cycle_max_lr - (cycle_max_lr - cycle_min_lr) * down,
        )
        if decay_step_size > 0:
            decay_steps = jnp.maximum(step - cycle_len, 0.0) / decay_step_size
            decayed = cycle_min_lr / (1.0 + decay_steps * decay_lr_rate)
        else:
            decayed = jnp.asarray(cycle_min_lr, jnp.float32)
        return jnp.where(step <= cycle_len, in_cycle_lr, decayed)

    return schedule


def lr_range_test(
    lr_range_test_min_lr: float = 0.001,
    lr_range_test_step_size: int = 2000,
    lr_range_test_step_rate: float = 1.0,
    lr_range_test_staircase: bool = False,
    **_: Any,
) -> Schedule:
    """Increasing-LR sweep for tuning (reference ``LRRangeTest`` :273)."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        interval = step / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = jnp.floor(interval)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return schedule


_FACTORIES: Dict[str, Callable[..., Schedule]] = {
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
    WARMUP_COSINE_LR: warmup_cosine_lr,
    ONE_CYCLE: one_cycle,
    LR_RANGE_TEST: lr_range_test,
}


def get_lr_schedule(name: str, params: Dict[str, Any], base_lr: Optional[float] = None) -> Schedule:
    if name not in _FACTORIES:
        raise ValueError(f"Unknown scheduler {name!r}; valid: {VALID_LR_SCHEDULES}")
    params = dict(params)
    if name == WARMUP_COSINE_LR and base_lr is not None:
        params.setdefault("base_lr", base_lr)
    return _FACTORIES[name](**params)


def constant_schedule(lr: float) -> Schedule:
    return lambda step: jnp.asarray(lr, jnp.float32)
