"""Data loading: distributed sampling + repeating loader.

TPU-native analog of ``deepspeed/runtime/dataloader.py`` (``DeepSpeedDataLoader``
:17, ``RepeatingLoader`` :41). In SPMD JAX there is no per-rank sampler: every
host feeds its local slice of a *globally consistent* batch order. This loader
produces global micro-batches (leading dim = micro_batch * dp_world) from an
indexable dataset with a seeded per-epoch shuffle, matching the reference's
``DistributedSampler`` semantics when restricted to one host.
"""

from __future__ import annotations

from typing import Any, Iterator, Optional, Sequence

import numpy as np

from deepspeed_tpu import telemetry


class RepeatingLoader:
    """Wrap an iterable to restart on StopIteration (reference :41)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedTPUDataLoader:
    """Batches an indexable dataset into global micro-batches.

    ``dataset`` may be: a dict/pytree of equal-length numpy arrays, a sequence
    of samples (each a pytree), or anything with ``__len__``/``__getitem__``.
    """

    def __init__(
        self,
        dataset: Any,
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        collate_fn=None,
        sampler=None,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn
        # external index-batch sampler (e.g. the curriculum
        # DeepSpeedDataSampler — reference data_sampling/data_sampler.py:36)
        self.sampler = sampler
        self.epoch = 0
        self._arrays = self._as_arrays(dataset)
        n = self._length()
        self.num_batches = n // batch_size if drop_last else -(-n // batch_size)

    @staticmethod
    def _as_arrays(dataset) -> Optional[Any]:
        """If the dataset is a pytree of arrays (columnar), keep it as such."""
        if isinstance(dataset, dict):
            return {k: np.asarray(v) for k, v in dataset.items()}
        if isinstance(dataset, np.ndarray):
            return dataset
        return None

    def _length(self) -> int:
        if isinstance(self._arrays, dict):
            return len(next(iter(self._arrays.values())))
        if self._arrays is not None:
            return len(self._arrays)
        return len(self.dataset)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        if self.sampler is not None and hasattr(self.sampler, "__len__"):
            # the curriculum sampler may serve fewer batches early on
            return len(self.sampler)
        return self.num_batches

    def _materialize(self, idx) -> Any:
        with telemetry.span("data:materialize", cat="data", batch_size=len(idx)):
            return self._materialize_inner(idx)

    def _materialize_inner(self, idx) -> Any:
        if self._arrays is not None:
            if isinstance(self._arrays, dict):
                return {k: v[idx] for k, v in self._arrays.items()}
            return self._arrays[idx]
        samples = [self.dataset[int(i)] for i in idx]
        if self.collate_fn is not None:
            return self.collate_fn(samples)
        if isinstance(samples[0], dict):
            return {k: np.stack([s[k] for s in samples]) for k in samples[0]}
        return np.stack(samples)

    def __iter__(self) -> Iterator[Any]:
        if self.sampler is not None:
            self.sampler.set_epoch(self.epoch)
            for idx in self.sampler:
                yield self._materialize(np.asarray(idx))
            self.epoch += 1
            return
        n = self._length()
        order = np.arange(n)
        if self.shuffle:
            order = np.random.default_rng(self.seed + self.epoch).permutation(n)
        for b in range(self.num_batches):
            idx = order[b * self.batch_size : (b + 1) * self.batch_size]
            yield self._materialize(idx)
        self.epoch += 1
