"""Optimizer factory.

TPU-native analog of the reference's basic-optimizer selection
(``deepspeed/runtime/engine.py:1428-1524`` — FusedAdam/CPUAdam/FusedLamb/
FusedLion/Adagrad/OneBit variants). On TPU there is no separate "fused" CUDA
path: optax update trees are fused by XLA into a handful of kernels over the
(sharded) parameter pytree, which is exactly what multi-tensor-apply buys on
GPU. The 1-bit compressed optimizers are expressed as a gradient-compression
wrapper (sign + error feedback) around Adam/Lamb rather than custom collectives
(see ``runtime/comm`` in the reference).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple, Union

import optax

from deepspeed_tpu.utils.logging import logger

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
LION_OPTIMIZER = "lion"
ADAGRAD_OPTIMIZER = "adagrad"
SGD_OPTIMIZER = "sgd"
RMSPROP_OPTIMIZER = "rmsprop"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
MUON_OPTIMIZER = "muon"

Schedule = Union[float, Callable[[Any], Any]]


def _common(params: Dict[str, Any]) -> Dict[str, Any]:
    betas = params.get("betas", (0.9, 0.999))
    return dict(
        b1=float(betas[0]),
        b2=float(betas[1]),
        eps=float(params.get("eps", 1e-8)),
    )


def _masked_weight_decay(wd: float, mask_fn) -> optax.GradientTransformation:
    if mask_fn is None:
        return optax.add_decayed_weights(wd)
    return optax.add_decayed_weights(wd, mask=mask_fn)


def get_optimizer(
    name: str,
    params: Optional[Dict[str, Any]] = None,
    learning_rate: Optional[Schedule] = None,
    weight_decay_mask=None,
) -> Tuple[optax.GradientTransformation, Schedule]:
    """Build an optax transformation for a DeepSpeed optimizer name.

    Returns ``(tx, lr_schedule)``. ``learning_rate`` overrides
    ``params['lr']`` (used to wire an LR scheduler into the compiled step).
    """
    params = dict(params or {})
    lr: Schedule = learning_rate if learning_rate is not None else float(params.get("lr", 1e-3))
    wd = float(params.get("weight_decay", 0.0))
    key = name.lower().replace("_", "")

    # OneBit optimizers = base update rule + sign-compressed gradient
    # allreduce with error feedback; the engine activates the compressed
    # collective automatically for these names (engine._onebit_config,
    # parallel/onebit.py — reference runtime/comm/nccl.py:51).
    if key in (ONEBIT_ADAM_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER):
        logger.info(f"{name}: Adam update rule + engine-level 1-bit compressed allreduce")
        key = ADAM_OPTIMIZER
    if key == ONEBIT_LAMB_OPTIMIZER:
        logger.info(f"{name}: Lamb update rule + engine-level 1-bit compressed allreduce")
        key = LAMB_OPTIMIZER

    if key == ADAM_OPTIMIZER:
        # reference FusedAdam defaults to adam_w_mode=True (ops/adam/fused_adam.py:18)
        adam_w_mode = params.get("adam_w_mode", True)
        c = _common(params)
        if adam_w_mode:
            tx = optax.chain(
                optax.scale_by_adam(**c),
                _masked_weight_decay(wd, weight_decay_mask),
                optax.scale_by_learning_rate(lr),
            )
        else:
            tx = optax.chain(
                optax.scale_by_adam(**c),
                optax.scale_by_learning_rate(lr),
            )
    elif key == ADAMW_OPTIMIZER:
        c = _common(params)
        tx = optax.chain(
            optax.scale_by_adam(**c),
            _masked_weight_decay(wd, weight_decay_mask),
            optax.scale_by_learning_rate(lr),
        )
    elif key == LAMB_OPTIMIZER:
        c = _common(params)
        tx = optax.chain(
            optax.scale_by_adam(**c),
            _masked_weight_decay(wd, weight_decay_mask),
            optax.scale_by_trust_ratio(),
            optax.scale_by_learning_rate(lr),
        )
    elif key == LION_OPTIMIZER:
        betas = params.get("betas", (0.9, 0.99))
        tx = optax.chain(
            optax.scale_by_lion(b1=float(betas[0]), b2=float(betas[1])),
            _masked_weight_decay(wd, weight_decay_mask),
            optax.scale_by_learning_rate(lr),
        )
    elif key == ADAGRAD_OPTIMIZER:
        tx = optax.chain(
            optax.scale_by_rss(initial_accumulator_value=float(params.get("initial_accumulator_value", 0.0)),
                               eps=float(params.get("eps", 1e-10))),
            _masked_weight_decay(wd, weight_decay_mask),
            optax.scale_by_learning_rate(lr),
        )
    elif key == SGD_OPTIMIZER:
        momentum = float(params.get("momentum", 0.0))
        parts = []
        if momentum:
            parts.append(optax.trace(decay=momentum, nesterov=bool(params.get("nesterov", False))))
        if wd:
            parts.append(_masked_weight_decay(wd, weight_decay_mask))
        parts.append(optax.scale_by_learning_rate(lr))
        tx = optax.chain(*parts)
    elif key == RMSPROP_OPTIMIZER:
        tx = optax.chain(
            optax.scale_by_rms(decay=float(params.get("alpha", 0.99)), eps=float(params.get("eps", 1e-8))),
            _masked_weight_decay(wd, weight_decay_mask),
            optax.scale_by_learning_rate(lr),
        )
    elif key == MUON_OPTIMIZER:
        try:
            tx = optax.contrib.muon(learning_rate=lr)  # type: ignore[attr-defined]
        except AttributeError as e:
            raise ValueError("Muon optimizer not available in this optax version") from e
    else:
        raise ValueError(f"Unknown optimizer {name!r}")

    return tx, lr
