"""Activation checkpointing (rematerialization) policies.

TPU-native analog of ``deepspeed/runtime/activation_checkpointing/
checkpointing.py`` (``checkpoint`` :948, ``CheckpointFunction`` :488,
``partition_activations`` :377). The reference re-runs each wrapped module's
forward during backward and optionally partitions/offloads the saved inputs;
on TPU the same trade is ``jax.checkpoint`` with a saveable-policy, applied to
the loss function inside the compiled train step — XLA then schedules the
recomputation, and "partitioned activations" correspond to saving nothing /
offloading residuals to host memory.

Policy names (config ``activation_checkpointing.policy``):
  - ``none``: save everything (no remat) — only valid when ``enabled`` false
  - ``full``: save nothing, recompute everything (reference default behavior
    of wrapping every transformer layer)
  - ``dots``: save matmul outputs with no batch dims (XLA's classic
    "checkpoint_dots" — good default for transformer stacks)
  - ``offload``: save residuals to pinned host memory instead of recomputing
    (reference ``cpu_checkpointing``)
"""

from __future__ import annotations

from typing import Any, Callable

import jax

POLICIES = ("none", "full", "dots", "offload")


def resolve_policy(name: str):
    """Policy name -> jax.checkpoint ``policy=`` argument."""
    pol = jax.checkpoint_policies
    if name == "full":
        return pol.nothing_saveable
    if name == "dots":
        return pol.dots_with_no_batch_dims_saveable
    if name == "offload":
        # matmul outputs (no batch dims) move to pinned host memory instead of
        # being recomputed — the reference's partitioned/CPU activation
        # checkpointing (checkpointing.py:377 partition_activations + CPU ckpt).
        # (FPDT's host offload is NOT a remat policy: its custom VJP moves the
        # q/k/v/out residuals with sharding-preserving device_puts instead —
        # named-offload policies lose shardings under the SPMD partitioner.)
        return pol.offload_dot_with_no_batch_dims("device", "pinned_host")
    raise ValueError(f"unknown activation_checkpointing policy {name!r}; one of {POLICIES}")


def apply_activation_checkpointing(loss_fn: Callable, config) -> Callable:
    """Wrap a ``(params, batch, rng) -> loss`` fn per the engine config.

    ``config`` is the ``ActivationCheckpointingConfig`` section. Returns the
    original fn unless enabled. ``cpu_checkpointing=True`` selects the host-
    offload policy regardless of ``policy``.
    """
    if not getattr(config, "enabled", False):
        return loss_fn
    name = "offload" if config.cpu_checkpointing else (config.policy or "full")
    if name == "none":
        return loss_fn
    policy = resolve_policy(name)
    return jax.checkpoint(loss_fn, policy=policy, prevent_cse=False)


def checkpoint(function: Callable, *args: Any):
    """Reference-API shim (``deepspeed.checkpointing.checkpoint``): runs
    ``function(*args)`` under full rematerialization."""
    return jax.checkpoint(function, policy=jax.checkpoint_policies.nothing_saveable)(*args)
