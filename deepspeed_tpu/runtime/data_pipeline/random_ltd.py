"""Random layerwise token dropping (random-LTD).

Reference: ``data_pipeline/data_routing/basic_layer.py:14 RandomLayerTokenDrop``
+ ``scheduler.py`` + CUDA gather/scatter kernels (``csrc/random_ltd/``). The
middle layers of a transformer see only a random subset of tokens; the subset
is gathered before and scattered back after, and the kept-token count anneals
from ``initial_seq_len`` up to the full length.

TPU design: gather/scatter are ``jnp.take_along_axis`` (XLA compiles these to
efficient dynamic-gather — the CUDA kernels aren't needed), and the random
subset is SORTED so position encodings stay monotone (reference keeps order
too). The kept count must be static per compiled step: the scheduler
quantizes it to ``step_granularity`` so recompiles are bounded.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


class RandomLTDScheduler:
    """Kept-token schedule (reference ``scheduler.py`` RandomLTDScheduler)."""

    def __init__(self, initial_seq_len: int, total_seq_len: int,
                 schedule_steps: int, step_granularity: int = 16):
        self.initial = initial_seq_len
        self.total = total_seq_len
        self.steps = max(schedule_steps, 1)
        self.gran = max(step_granularity, 1)
        self.current_seq_len = initial_seq_len

    def get_seq_len(self, global_step: int) -> int:
        frac = min(max(global_step, 0) / self.steps, 1.0)
        n = self.initial + frac * (self.total - self.initial)
        n = int(n // self.gran * self.gran)
        return max(self.initial, min(self.total, n))

    def update(self, global_step: int) -> int:
        self.current_seq_len = self.get_seq_len(global_step)
        return self.current_seq_len

    def state_dict(self) -> Dict:
        return {"current_seq_len": self.current_seq_len}

    def load_state_dict(self, sd: Dict) -> None:
        self.current_seq_len = sd["current_seq_len"]


def sample_token_indices(rng: jax.Array, batch: int, seq_len: int, keep: int) -> jax.Array:
    """[B, keep] sorted random token indices (one independent draw per row)."""
    noise = jax.random.uniform(rng, (batch, seq_len))
    _, idx = jax.lax.top_k(-noise, keep)  # random subset without replacement
    return jnp.sort(idx, axis=-1)


def random_ltd_gather(x: jax.Array, indices: jax.Array) -> jax.Array:
    """[B, S, ...] -> [B, keep, ...] (reference gather kernel
    csrc/random_ltd/token_sort.cu — here one XLA gather)."""
    idx = indices.reshape(indices.shape + (1,) * (x.ndim - 2))
    return jnp.take_along_axis(x, idx.astype(jnp.int32), axis=1)


def random_ltd_scatter(sub: jax.Array, indices: jax.Array, full: jax.Array) -> jax.Array:
    """Scatter [B, keep, ...] back into a copy of [B, S, ...]: dropped tokens
    keep their pre-layer activations (the reference's pass-through,
    csrc/random_ltd/token_scatter kernels — here one XLA scatter)."""
    b = jnp.arange(full.shape[0])[:, None]
    return full.at[b, indices].set(sub)


def apply_random_ltd(layer_fn, x: jax.Array, rng: jax.Array, keep: int):
    """Run ``layer_fn`` on a random token subset; others bypass the layer
    (reference ``RandomLayerTokenDrop.forward``). keep must be static."""
    B, S = x.shape[:2]
    if keep >= S:
        return layer_fn(x)
    idx = sample_token_indices(rng, B, S, keep)
    sub = random_ltd_gather(x, idx)
    sub = layer_fn(sub)
    b = jnp.arange(B)[:, None]
    return x.at[b, idx].set(sub)
