"""Variable batch size + LR scaling for length-grouped batching.

Reference: ``data_pipeline/data_sampling/variable_batch_size_and_lr.py:226``
— pack samples of varying sequence length into batches with roughly equal
TOKEN counts (so step compute is uniform), then scale LR per batch for the
changed effective batch size. On TPU, batches are additionally bucketed to a
few shapes so XLA compiles a handful of programs.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


def batch_by_tokens(
    seq_lens: Sequence[int],
    max_tokens_per_batch: int,
    shuffle_seed: int = None,
    len_bucket: int = 64,
    min_batch_size: int = 1,
) -> List[np.ndarray]:
    """Greedy equal-token packing (reference ``batch_by_size``).

    Samples are grouped by padded-length bucket so each batch pads to one
    shape; within a bucket, batch_size = max_tokens // padded_len.
    """
    lens = np.asarray(seq_lens)
    order = np.argsort(lens, kind="stable")
    batches: List[np.ndarray] = []
    i = 0
    while i < len(order):
        batch: List[int] = []
        padded = 0
        while i < len(order):
            L = int(lens[order[i]])
            pl = -(-max(L, 1) // len_bucket) * len_bucket
            grown = max(padded, pl)
            if batch and (len(batch) + 1) * grown > max_tokens_per_batch and len(batch) >= min_batch_size:
                break
            batch.append(int(order[i]))
            padded = grown
            i += 1
        batches.append(np.asarray(batch))
    if shuffle_seed is not None:
        np.random.RandomState(shuffle_seed).shuffle(batches)
    return batches


def scale_lr_by_batch(base_lr: float, batch_size: int, base_batch_size: int,
                      method: str = "linear") -> float:
    """LR adjustment per variable batch (reference ``scale_lr``): linear or
    sqrt scaling with effective batch size."""
    ratio = batch_size / max(base_batch_size, 1)
    if method == "linear":
        return base_lr * ratio
    if method == "sqrt":
        return base_lr * ratio ** 0.5
    if method in ("none", None):
        return base_lr
    raise ValueError(f"unknown lr scaling method {method!r}")
