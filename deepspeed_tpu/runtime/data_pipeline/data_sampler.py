"""Curriculum-aware distributed sampler.

Reference: ``data_sampling/data_sampler.py:36 DeepSpeedDataSampler`` — serves
index batches restricted to samples whose difficulty metric is within the
current curriculum difficulty, sharded across dp ranks. Here one host builds
GLOBAL batches (SPMD: the engine shards the leading dim over dp), so the
sampler yields global index batches; determinism comes from a seeded
per-epoch permutation as in the reference.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler


class DeepSpeedDataSampler:
    """Difficulty-filtered batch sampler (reference :36)."""

    def __init__(
        self,
        num_samples: int,
        batch_size: int,
        difficulties: Optional[Sequence[float]] = None,
        curriculum: Optional[CurriculumScheduler] = None,
        seed: int = 1234,
        drop_last: bool = True,
    ):
        self.num_samples = num_samples
        self.batch_size = batch_size
        self.difficulties = None if difficulties is None else np.asarray(difficulties)
        if self.difficulties is not None and len(self.difficulties) != num_samples:
            raise ValueError("difficulties must have one entry per sample")
        self.curriculum = curriculum
        self.seed = seed
        self.drop_last = drop_last
        self.global_step = 0
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def state_dict(self) -> Dict:
        return {"global_step": self.global_step, "epoch": self.epoch}

    def load_state_dict(self, sd: Dict) -> None:
        self.global_step = sd["global_step"]
        self.epoch = sd["epoch"]

    def _eligible(self) -> np.ndarray:
        if self.curriculum is None or self.difficulties is None:
            return np.arange(self.num_samples)
        cap = self.curriculum.update_difficulty(self.global_step)
        idx = np.nonzero(self.difficulties <= cap)[0]
        # curriculum must never starve the loader (reference keeps at least
        # one batch available by construction of min_difficulty)
        if len(idx) < self.batch_size:
            order = np.argsort(self.difficulties)
            idx = order[: self.batch_size]
        return idx

    def __iter__(self) -> Iterator[np.ndarray]:
        """One pass over this epoch's permutation: ineligible indices are
        skipped (never re-served) and the epoch ends when the permutation is
        exhausted, so no sample appears twice within an epoch."""
        rng = np.random.RandomState(self.seed + self.epoch)
        perm = rng.permutation(self.num_samples)
        cursor = 0
        while cursor < self.num_samples:
            eligible = set(self._eligible().tolist())
            batch: List[int] = []
            while len(batch) < self.batch_size and cursor < self.num_samples:
                i = perm[cursor]
                cursor += 1
                if i in eligible:
                    batch.append(int(i))
            if len(batch) < self.batch_size:
                if self.drop_last or not batch:
                    return
                self.global_step += 1  # partial tail batch still trains
                yield np.asarray(batch)
                return
            self.global_step += 1
            yield np.asarray(batch)

    def __len__(self) -> int:
        """UPPER BOUND on batches per epoch: under curriculum filtering some
        permutation entries are skipped, so fewer batches may be served."""
        return self.num_samples // self.batch_size
