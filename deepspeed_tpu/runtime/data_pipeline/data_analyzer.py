"""Offline per-sample metric analysis feeding curriculum sampling.

Reference analog: ``data_sampling/data_analyzer.py:22 DataAnalyzer`` /
``:455 DistributedDataAnalyzer`` — map metric functions over a corpus,
persist per-sample metric values + a value->samples index so the curriculum
sampler can filter by difficulty without touching the data.

Outputs per metric under ``save_path``:
  ``<metric>_sample_to_metric.npy``  — value per sample (the 'difficulties'
                                       array ``deepspeed_io`` consumes)
  ``<metric>_metric_to_sample.npz``  — value -> sorted sample indices
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional, Sequence

import numpy as np


def metric_seqlen(sample) -> int:
    """Default metric: token count (reference 'seqlen')."""
    return int(np.asarray(sample).reshape(-1).shape[0])


class DataAnalyzer:
    """Single-process analysis over an indexable dataset."""

    def __init__(
        self,
        dataset,
        metric_names: Sequence[str] = ("seqlen",),
        metric_functions: Optional[Dict[str, Callable]] = None,
        save_path: str = ".",
        worker_id: int = 0,
        num_workers: int = 1,
    ):
        self.dataset = dataset
        self.metric_names = list(metric_names)
        self.metric_functions = {"seqlen": metric_seqlen, **(metric_functions or {})}
        self.save_path = save_path
        self.worker_id = worker_id
        self.num_workers = num_workers
        for m in self.metric_names:
            if m not in self.metric_functions:
                raise ValueError(f"no metric function for {m!r}")

    def _my_range(self):
        n = len(self.dataset)
        per = -(-n // self.num_workers)
        lo = self.worker_id * per
        return lo, min(lo + per, n)

    def run_map(self) -> Dict[str, np.ndarray]:
        """Compute this worker's slice; returns {metric: values} and writes
        the partial file ``<metric>_sample_to_metric.w<id>.npy``."""
        lo, hi = self._my_range()
        out = {}
        for name in self.metric_names:
            fn = self.metric_functions[name]
            vals = np.asarray([fn(self.dataset[i]) for i in range(lo, hi)])
            out[name] = vals
            if self.num_workers > 1:
                os.makedirs(self.save_path, exist_ok=True)
                np.save(os.path.join(self.save_path, f"{name}_sample_to_metric.w{self.worker_id}.npy"), vals)
        return out

    def run_reduce(self, partials: Optional[Dict[str, Sequence[np.ndarray]]] = None) -> Dict[str, str]:
        """Merge worker partials and write the final maps; returns file paths."""
        os.makedirs(self.save_path, exist_ok=True)
        paths = {}
        local = None  # single-worker fallback: ONE pass computes every metric
        for name in self.metric_names:
            if partials and name in partials:
                vals = np.concatenate(list(partials[name]))
            elif self.num_workers > 1:
                vals = np.concatenate([
                    np.load(os.path.join(self.save_path, f"{name}_sample_to_metric.w{w}.npy"))
                    for w in range(self.num_workers)
                ])
            else:
                if local is None:
                    local = self.run_map()
                vals = local[name]
            s2m = os.path.join(self.save_path, f"{name}_sample_to_metric.npy")
            np.save(s2m, vals)
            uniq = {}
            for v in np.unique(vals):
                # full repr, not int-truncated: float metrics must not collide
                uniq[str(v)] = np.nonzero(vals == v)[0]
            np.savez(os.path.join(self.save_path, f"{name}_metric_to_sample.npz"), **uniq)
            paths[name] = s2m
        return paths

    def run(self) -> Dict[str, str]:
        return self.run_reduce({m: [v] for m, v in self.run_map().items()})


class DistributedDataAnalyzer(DataAnalyzer):
    """Multi-worker flavor (reference :455): each worker calls ``run_map``
    over its contiguous shard; worker 0 then calls ``run_reduce``. On TPU
    pods the workers are host processes — the map phase is embarrassingly
    parallel file I/O, so no collective is needed."""

    def run(self) -> Dict[str, str]:
        self.run_map()
        if self.worker_id == 0:
            return self.run_reduce()
        return {}


def load_difficulties(save_path: str, metric: str = "seqlen") -> np.ndarray:
    """The array ``deepspeed_io``'s curriculum sampler consumes."""
    return np.load(os.path.join(save_path, f"{metric}_sample_to_metric.npy"))
