"""Memory-mapped indexed dataset + builder.

Reference analog: ``deepspeed/runtime/data_pipeline/data_sampling/
indexed_dataset.py:369 MMapIndexedDataset`` (the Megatron-style .bin/.idx
pair) — random access into a token corpus without loading it, which is what
lets curriculum/data-efficiency sampling run at pretraining scale.

Format (own, versioned): ``<path>.idx`` holds a fixed header (magic, version,
dtype code, sample count) followed by int64 byte offsets and int32 sample
lengths; ``<path>.bin`` is the raw concatenated sample data. Reads are
zero-copy numpy views over one mmap.
"""

from __future__ import annotations

import os
import struct
from typing import Iterable, Optional, Sequence

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1

_DTYPES = {
    1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
    5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16, 9: np.uint32,
}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """Streams samples to ``<prefix>.bin`` and finalizes ``<prefix>.idx``."""

    def __init__(self, prefix: str, dtype=np.int32):
        self._prefix = prefix
        self._dtype = np.dtype(dtype)
        if self._dtype not in _DTYPE_CODES:
            raise ValueError(f"unsupported dtype {dtype}")
        parent = os.path.dirname(os.path.abspath(prefix))
        os.makedirs(parent, exist_ok=True)
        self._data = open(data_file_path(prefix), "wb")
        self._sizes: list = []

    def add_item(self, tokens: Sequence) -> None:
        arr = np.asarray(tokens, dtype=self._dtype)
        self._data.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def add_documents(self, docs: Iterable[Sequence]) -> None:
        for d in docs:
            self.add_item(d)

    def merge_file(self, other_prefix: str) -> None:
        """Append another builder's output (reference merge_file_ — the
        distributed corpus-shard merge)."""
        other = MMapIndexedDataset(other_prefix)
        if other.dtype != self._dtype:
            raise ValueError(
                f"cannot merge {other_prefix!r} (dtype {other.dtype}) into a "
                f"{self._dtype} builder — values would be silently cast"
            )
        for i in range(len(other)):
            self.add_item(other[i])

    def finalize(self) -> None:
        self._data.close()
        sizes = np.asarray(self._sizes, np.int32)
        itemsize = self._dtype.itemsize
        pointers = np.zeros(len(sizes), np.int64)
        np.cumsum(sizes[:-1].astype(np.int64) * itemsize, out=pointers[1:])
        with open(index_file_path(self._prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<HHq", _VERSION, _DTYPE_CODES[self._dtype], len(sizes)))
            f.write(pointers.tobytes())
            f.write(sizes.tobytes())


class MMapIndexedDataset:
    """Zero-copy random access over a finalized .bin/.idx pair."""

    def __init__(self, prefix: str):
        self._prefix = prefix
        with open(index_file_path(prefix), "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"{index_file_path(prefix)}: bad magic {magic!r}")
            version, dcode, count = struct.unpack("<HHq", f.read(12))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            self._dtype = np.dtype(_DTYPES[dcode])
            self._pointers = np.frombuffer(f.read(count * 8), np.int64)
            self._sizes = np.frombuffer(f.read(count * 4), np.int32)
        self._bin = np.memmap(data_file_path(prefix), dtype=np.uint8, mode="r")

    def __len__(self) -> int:
        return len(self._sizes)

    @property
    def sizes(self) -> np.ndarray:
        return self._sizes

    @property
    def dtype(self):
        return self._dtype

    def __getitem__(self, idx: int) -> np.ndarray:
        if isinstance(idx, slice):
            return [self[i] for i in range(*idx.indices(len(self)))]
        ptr = int(self._pointers[idx])
        n = int(self._sizes[idx])
        return np.frombuffer(self._bin, dtype=self._dtype, count=n, offset=ptr)

    def get(self, idx: int, offset: int = 0, length: Optional[int] = None) -> np.ndarray:
        sample = self[idx]
        end = len(sample) if length is None else offset + length
        return sample[offset:end]

    @property
    def supports_prefetch(self) -> bool:
        return False  # the OS page cache is the prefetcher

    @staticmethod
    def exists(prefix: str) -> bool:
        return os.path.exists(index_file_path(prefix)) and os.path.exists(data_file_path(prefix))
