"""deepspeed_tpu.runtime.data_pipeline: data-efficiency suite.

Reference: ``deepspeed/runtime/data_pipeline/`` (~3.2k LoC) — curriculum
learning (difficulty schedules + metric-filtered sampling), random layerwise
token dropping (random-LTD), and variable-batch/LR packing.
"""

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler
from deepspeed_tpu.runtime.data_pipeline.data_sampler import DeepSpeedDataSampler
from deepspeed_tpu.runtime.data_pipeline.random_ltd import RandomLTDScheduler, random_ltd_gather, random_ltd_scatter
from deepspeed_tpu.runtime.data_pipeline.variable_batch import batch_by_tokens, scale_lr_by_batch
