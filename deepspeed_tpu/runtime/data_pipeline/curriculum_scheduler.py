"""Curriculum difficulty schedules.

Reference: ``runtime/data_pipeline/curriculum_scheduler.py`` —
``CurriculumScheduler`` maps global step -> difficulty (e.g. max sequence
length), with fixed_linear / fixed_root / fixed_discrete / custom schedules.
Pure host-side math; the engine truncates/filters batches with the result.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional


class CurriculumScheduler:
    """step -> difficulty (reference class of the same name)."""

    def __init__(self, config: Dict):
        # Reference schema: 'curriculum_type' names the difficulty METRIC
        # ('seqlen'); 'schedule_type' names the schedule. Accept a schedule
        # name accidentally passed via curriculum_type for compatibility.
        sched = config.get("schedule_type")
        ctype = config.get("curriculum_type")
        if sched is None and ctype in ("fixed_linear", "fixed_root", "fixed_discrete"):
            sched = ctype
        self.metric = ctype if ctype not in (None, "fixed_linear", "fixed_root", "fixed_discrete") else "seqlen"
        self.schedule_type = sched or "fixed_linear"
        self.min_difficulty = int(config["min_difficulty"])
        self.max_difficulty = int(config["max_difficulty"])
        sc = config.get("schedule_config", {})
        self.total_steps = int(sc.get("total_curriculum_step", sc.get("total_steps", 1000)))
        self.difficulty_step = int(sc.get("difficulty_step", 1))
        self.root_degree = int(sc.get("root_degree", 2))
        self.difficulties: List[int] = list(sc.get("difficulty", []))
        self.max_steps: List[int] = list(sc.get("max_step", []))
        self._custom: Optional[Callable[[int], int]] = config.get("custom_fn")
        if self.schedule_type == "fixed_discrete" and len(self.difficulties) != len(self.max_steps) + 1:
            raise ValueError("fixed_discrete needs len(difficulty) == len(max_step) + 1")
        self.current_difficulty = self.min_difficulty

    def _clamp_quantize(self, d: float) -> int:
        d = int(d // self.difficulty_step * self.difficulty_step)
        return max(self.min_difficulty, min(self.max_difficulty, d))

    def get_difficulty(self, global_step: int) -> int:
        t = max(global_step, 0)
        if self._custom is not None:
            return int(self._custom(t))
        if self.schedule_type == "fixed_discrete":
            for d, until in zip(self.difficulties, self.max_steps):
                if t < until:
                    return d
            return self.difficulties[-1]
        frac = min(t / max(self.total_steps, 1), 1.0)
        if self.schedule_type == "fixed_root":
            frac = frac ** (1.0 / self.root_degree)
        elif self.schedule_type != "fixed_linear":
            raise ValueError(f"unknown curriculum_type {self.schedule_type!r}")
        d = self.min_difficulty + frac * (self.max_difficulty - self.min_difficulty)
        return self._clamp_quantize(d)

    def update_difficulty(self, global_step: int) -> int:
        self.current_difficulty = self.get_difficulty(global_step)
        return self.current_difficulty
