"""ZeRO stages as sharding placements.

TPU-native re-design of the reference ZeRO stack (``zero/stage_1_and_2.py``,
``zero/stage3.py``, ``zero/partition_parameters.py`` — ~11k LoC of hook/bucket
machinery). On TPU the same memory states are obtained by *placing* the train
state on the mesh and letting XLA schedule the collectives:

  stage 0: params/grads/opt replicated; grads all-reduced (psum) over data axes
  stage 1: optimizer state + fp32 master params sharded over the data axes
           (update computed on the shard, updated weights all-gathered —
           exactly the reference's partitioned fp32 update + bucketed
           allgather, ``stage_1_and_2.py:1835``)
  stage 2: + gradient accumulation buffers sharded (each micro-batch's grads
           are reduce-scattered into the shard instead of all-reduced,
           ``stage_1_and_2.py:1057 average_tensor``)
  stage 3: + parameters themselves sharded over the ``fsdp`` mesh axis
           per-tensor; XLA inserts per-layer allgathers during fwd/bwd,
           replacing the fetch/prefetch coordinator
           (``partitioned_param_coordinator.py``) with compiler scheduling.

MiCS (``zero/mics.py``) falls out of the mesh shape: ``fsdp < dp_world`` gives
sub-group sharding with replication across groups.

The unit of partitioning is a whole tensor dimension (largest dimension
divisible by the shard count), not a flat byte range: XLA needs dimension
shardings. Tensors too small to matter (< ``param_persistence_threshold``
elements, reference ``zero/config.py``) stay replicated, which mirrors the
reference's persistent-parameter optimization (``parameter_offload.py:261``).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.config.config import ZeroConfig
from deepspeed_tpu.topology.mesh import BATCH_AXES

# Leaves smaller than this stay replicated in stage-1/2 opt-state sharding
# (sharding a 10-element bias buys nothing and costs collective latency).
DEFAULT_SHARD_MIN_NUMEL = 2048


def _fill_largest_free_dim(
    base: list,
    shape: Sequence[int],
    mesh: Mesh,
    axes: Tuple[str, ...],
    min_numel: int,
) -> list:
    """Shared policy: shard the largest dim of ``shape`` not already occupied
    in ``base`` (and divisible by the joint axis size) over ``axes``."""
    live = tuple(a for a in axes if mesh.shape[a] > 1)
    if not live:
        return base
    n = int(np.prod([mesh.shape[a] for a in live]))
    if int(np.prod(shape or (0,))) < max(min_numel, n):
        return base
    free = [i for i, e in enumerate(base) if e is None and shape[i] % n == 0 and shape[i] >= n]
    if free:
        dim = max(free, key=lambda i: shape[i])
        base[dim] = live if len(live) > 1 else live[0]
    return base


def auto_partition_spec(
    shape: Sequence[int],
    mesh: Mesh,
    axes: Tuple[str, ...],
    min_numel: int = DEFAULT_SHARD_MIN_NUMEL,
) -> PartitionSpec:
    """Shard the largest divisible dimension of ``shape`` over ``axes`` (jointly)."""
    spec = _fill_largest_free_dim([None] * len(shape), shape, mesh, axes, min_numel)
    return PartitionSpec(*spec) if any(e is not None for e in spec) else PartitionSpec()


def param_partition_spec(
    shape: Sequence[int],
    mesh: Mesh,
    zero_config: ZeroConfig,
    base_spec: Optional[PartitionSpec] = None,
) -> PartitionSpec:
    """PartitionSpec for a *parameter* under the configured ZeRO stage.

    ``base_spec`` carries model-parallel placements (e.g. a ``tp`` entry from
    AutoTP rules); stage 3 then shards the largest still-unsharded dimension
    over ``fsdp``. Stages 0-2 keep only the base (model-parallel) placement.
    """
    base = list(base_spec) if base_spec is not None else []
    base = base + [None] * (len(shape) - len(base))
    if zero_config.stage >= 3:
        base = _fill_largest_free_dim(
            base, shape, mesh, ("fsdp",), max(zero_config.param_persistence_threshold, 1)
        )
    return PartitionSpec(*base) if any(e is not None for e in base) else PartitionSpec()


def master_partition_spec(
    shape: Sequence[int],
    mesh: Mesh,
    zero_config: ZeroConfig,
    base_spec: Optional[PartitionSpec] = None,
) -> PartitionSpec:
    """PartitionSpec for fp32 master params / optimizer moments / grad accumulators.

    Stage >=1 shards the largest free dimension over the data axes (dp and
    fsdp jointly) — the ZeRO insight that optimizer state need only exist once
    per data-parallel world. Model-parallel placements from ``base_spec``
    (e.g. ``tp`` entries) are preserved.
    """
    base = list(base_spec) if base_spec is not None else []
    base = base + [None] * (len(shape) - len(base))
    if zero_config.stage >= 1:
        base = _fill_largest_free_dim(base, shape, mesh, BATCH_AXES, DEFAULT_SHARD_MIN_NUMEL)
    return PartitionSpec(*base) if any(e is not None for e in base) else PartitionSpec()


def state_sharding(tree: Any, mesh: Mesh, spec_fn, base_specs: Any = None) -> Any:
    """Map ``spec_fn(shape, base_spec) -> PartitionSpec`` over a pytree.

    ``base_specs`` (same structure as ``tree``) carries model-parallel specs.
    """

    def _one(leaf, base):
        shape = getattr(leaf, "shape", ())
        if shape is None or len(shape) == 0:
            return NamedSharding(mesh, PartitionSpec())
        return NamedSharding(mesh, spec_fn(tuple(shape), base))

    if base_specs is None:
        # PartitionSpec is a pytree leaf, so an empty spec is a safe "no base"
        base_specs = jax.tree_util.tree_map(lambda _: PartitionSpec(), tree)
    return jax.tree_util.tree_map(_one, tree, base_specs)


def params_sharding(params: Any, mesh: Mesh, zero_config: ZeroConfig, base_specs: Any = None) -> Any:
    return state_sharding(
        params, mesh, lambda s, b: param_partition_spec(s, mesh, zero_config, b), base_specs
    )


def master_sharding(tree: Any, mesh: Mesh, zero_config: ZeroConfig, base_specs: Any = None) -> Any:
    """Sharding for fp32 master params / grad accumulators (data-axes rule)."""
    return state_sharding(
        tree, mesh, lambda s, b: master_partition_spec(s, mesh, zero_config, b), base_specs
    )


def grads_sharding(params: Any, mesh: Mesh, zero_config: ZeroConfig, base_specs: Any = None) -> Any:
    """Sharding for the gradient-accumulation buffer.

    Stage >=2 shards it like the master state (reduce-scatter per micro-batch);
    stages 0/1 keep full gradients (model-parallel placement only), matching
    the reference's allreduce-then-partition behavior.
    """
    if zero_config.stage < 2:
        return state_sharding(
            params, mesh, lambda s, b: PartitionSpec(*b) if b else PartitionSpec(), base_specs
        )
    return master_sharding(params, mesh, zero_config, base_specs)
