"""ZeRO stages as sharding placements.

TPU-native re-design of the reference ZeRO stack (``zero/stage_1_and_2.py``,
``zero/stage3.py``, ``zero/partition_parameters.py`` — ~11k LoC of hook/bucket
machinery). On TPU the same memory states are obtained by *placing* the train
state on the mesh and letting XLA schedule the collectives:

  stage 0: params/grads/opt replicated; grads all-reduced (psum) over data axes
  stage 1: optimizer state + fp32 master params sharded over the data axes
           (update computed on the shard, updated weights all-gathered —
           exactly the reference's partitioned fp32 update + bucketed
           allgather, ``stage_1_and_2.py:1835``)
  stage 2: + gradient accumulation buffers sharded (each micro-batch's grads
           are reduce-scattered into the shard instead of all-reduced,
           ``stage_1_and_2.py:1057 average_tensor``)
  stage 3: + parameters themselves sharded over the ``fsdp`` mesh axis
           per-tensor; XLA inserts per-layer allgathers during fwd/bwd,
           replacing the fetch/prefetch coordinator
           (``partitioned_param_coordinator.py``) with compiler scheduling.

MiCS (``zero/mics.py``) falls out of the mesh shape: ``fsdp < dp_world`` gives
sub-group sharding with replication across groups.

The unit of partitioning is a whole tensor dimension (largest dimension
divisible by the shard count), not a flat byte range: XLA needs dimension
shardings. Tensors too small to matter (< ``param_persistence_threshold``
elements, reference ``zero/config.py``) stay replicated, which mirrors the
reference's persistent-parameter optimization (``parameter_offload.py:261``).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.config.config import ZeroConfig
from deepspeed_tpu.topology.mesh import BATCH_AXES

# Leaves smaller than this stay replicated in stage-1/2 opt-state sharding
# (sharding a 10-element bias buys nothing and costs collective latency).
DEFAULT_SHARD_MIN_NUMEL = 2048


def _shardable_dim(shape: Sequence[int], n_shards: int, min_numel: int) -> Optional[int]:
    """Pick the dimension to shard: largest dim divisible by ``n_shards``."""
    if n_shards <= 1:
        return None
    if int(np.prod(shape or (0,))) < max(min_numel, n_shards):
        return None
    candidates = [i for i, d in enumerate(shape) if d % n_shards == 0 and d >= n_shards]
    if not candidates:
        return None
    return max(candidates, key=lambda i: shape[i])


def auto_partition_spec(
    shape: Sequence[int],
    mesh: Mesh,
    axes: Tuple[str, ...],
    min_numel: int = DEFAULT_SHARD_MIN_NUMEL,
) -> PartitionSpec:
    """Shard the largest divisible dimension of ``shape`` over ``axes`` (jointly)."""
    live = tuple(a for a in axes if mesh.shape[a] > 1)
    if not live:
        return PartitionSpec()
    n = int(np.prod([mesh.shape[a] for a in live]))
    dim = _shardable_dim(shape, n, min_numel)
    if dim is None:
        return PartitionSpec()
    spec: list = [None] * len(shape)
    spec[dim] = live if len(live) > 1 else live[0]
    return PartitionSpec(*spec)


def param_partition_spec(shape: Sequence[int], mesh: Mesh, zero_config: ZeroConfig) -> PartitionSpec:
    """PartitionSpec for a *parameter* under the configured ZeRO stage.

    Stage 3 shards over ``fsdp`` (and for MiCS semantics the mesh shape itself
    encodes the sub-group). Stages 0-2 keep parameters replicated.
    """
    if zero_config.stage < 3:
        return PartitionSpec()
    return auto_partition_spec(
        shape, mesh, axes=("fsdp",), min_numel=max(zero_config.param_persistence_threshold, 1)
    )


def master_partition_spec(shape: Sequence[int], mesh: Mesh, zero_config: ZeroConfig) -> PartitionSpec:
    """PartitionSpec for fp32 master params / optimizer moments / grad accumulators.

    Stage >=1 shards these over all data-like axes (dp and fsdp jointly) —
    the ZeRO insight that optimizer state need only exist once per data-
    parallel world. Stage 3 master state additionally must stay compatible
    with the param placement, so it uses the same data axes (a superset of
    fsdp).
    """
    if zero_config.stage < 1:
        return PartitionSpec()
    return auto_partition_spec(shape, mesh, axes=BATCH_AXES, min_numel=DEFAULT_SHARD_MIN_NUMEL)


def state_sharding(tree: Any, mesh: Mesh, spec_fn) -> Any:
    """Map ``spec_fn(shape) -> PartitionSpec`` over a pytree of array specs/arrays."""

    def _one(leaf):
        shape = getattr(leaf, "shape", ())
        if shape is None or len(shape) == 0:
            return NamedSharding(mesh, PartitionSpec())
        return NamedSharding(mesh, spec_fn(tuple(shape)))

    return jax.tree_util.tree_map(_one, tree)


def params_sharding(params: Any, mesh: Mesh, zero_config: ZeroConfig) -> Any:
    return state_sharding(params, mesh, lambda s: param_partition_spec(s, mesh, zero_config))


def master_sharding(tree: Any, mesh: Mesh, zero_config: ZeroConfig) -> Any:
    """Sharding for master params + optimizer state leaves.

    Under stage 3 a leaf keeps the param placement when it is already sharded
    over fsdp; data-axis sharding applies on top for moments. For simplicity
    and correctness we use the joint data-axes rule for every float leaf —
    scalars (step counts) replicate.
    """
    return state_sharding(tree, mesh, lambda s: master_partition_spec(s, mesh, zero_config))


def grads_sharding(params: Any, mesh: Mesh, zero_config: ZeroConfig) -> Any:
    """Sharding for the gradient-accumulation buffer.

    Stage >=2 shards it like the master state (reduce-scatter per micro-batch);
    stages 0/1 keep full (replicated) gradients, matching the reference's
    allreduce-then-partition behavior.
    """
    if zero_config.stage < 2:
        return state_sharding(params, mesh, lambda s: PartitionSpec())
    return master_sharding(params, mesh, zero_config)
