"""Progressive Layer Drop (PLD).

Reference: ``runtime/progressive_layer_drop.py:10 ProgressiveLayerDrop`` —
theta(t) schedule that anneals the keep-probability of transformer layers
from 1.0 down toward ``theta`` so early training skips layers stochastically.
The schedule math is identical; the *application* is TPU-idiomatic: the keep
decision enters the compiled step as a per-layer Bernoulli mask consumed by
``models.transformer`` (scaled residual branches), not Python control flow.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


class ProgressiveLayerDrop:
    """theta(t) = (1 - theta) * exp(-gamma * t) + theta (reference :10)."""

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> float:
        self.current_theta = (1.0 - self.theta) * math.exp(-self.gamma * global_step) + self.theta
        return self.current_theta

    def layer_keep_probs(self, num_layers: int, theta: float = None) -> jnp.ndarray:
        """Per-layer keep probability: deeper layers drop more (reference
        applies i/L scaling inside the model)."""
        th = self.current_theta if theta is None else theta
        depth_scale = jnp.arange(1, num_layers + 1, dtype=jnp.float32) / num_layers
        return 1.0 - depth_scale * (1.0 - th)

    def sample_keep_mask(self, rng: jax.Array, num_layers: int, theta: float = None) -> jnp.ndarray:
        """[L] float mask: 1/p when kept (inverted-dropout scaling), 0 when
        dropped — multiply each layer's residual branch by mask[i]."""
        probs = self.layer_keep_probs(num_layers, theta)
        keep = jax.random.bernoulli(rng, probs)
        return jnp.where(keep, 1.0 / jnp.maximum(probs, 1e-6), 0.0)
