"""NVMe IO benchmark + tuner (reference ``bin/ds_io`` / ``bin/ds_nvme_tune``
→ ``deepspeed/nvme/perf_run_sweep.py``): measure read/write GB/s through the
native AIO pool and sweep thread counts for the best config.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.ops.aio import AioHandle
from deepspeed_tpu.utils.logging import logger


def run_io_benchmark(folder: str, size_mb: int = 64, num_threads: int = 4,
                     chunks: int = 8, keep: bool = False) -> Dict[str, float]:
    """Write + read ``size_mb`` in ``chunks`` parallel requests -> GB/s."""
    os.makedirs(folder, exist_ok=True)
    handle = AioHandle(num_threads=num_threads)
    n = size_mb * (1 << 20) // chunks
    bufs = [np.random.randint(0, 255, n, np.uint8) for _ in range(chunks)]
    paths = [os.path.join(folder, f"ds_io_{i}.bin") for i in range(chunks)]
    try:
        t0 = time.perf_counter()
        for b, p in zip(bufs, paths):
            handle.async_pwrite(b, p)
        handle.wait_all()
        wt = time.perf_counter() - t0

        reads = [np.empty(n, np.uint8) for _ in range(chunks)]
        t0 = time.perf_counter()
        for b, p in zip(reads, paths):
            handle.async_pread(b, p)
        handle.wait_all()
        rt = time.perf_counter() - t0
        for a, b in zip(bufs, reads):
            if not np.array_equal(a, b):
                raise RuntimeError("ds_io: readback mismatch")
        total = size_mb / 1024
        return {"write_gbps": total / wt, "read_gbps": total / rt,
                "size_mb": size_mb, "num_threads": num_threads}
    finally:
        handle.close()
        if not keep:
            for p in paths:
                try:
                    os.remove(p)
                except OSError:
                    pass


def sweep_io_config(folder: str, size_mb: int = 64,
                    thread_counts: Optional[List[int]] = None) -> Dict:
    """ds_nvme_tune analog: pick the thread count with best read bandwidth."""
    results = []
    for t in thread_counts or [1, 2, 4, 8]:
        r = run_io_benchmark(folder, size_mb=size_mb, num_threads=t)
        logger.info(f"ds_io sweep: threads={t} write={r['write_gbps']:.2f} read={r['read_gbps']:.2f} GB/s")
        results.append(r)
    best = max(results, key=lambda r: r["read_gbps"])
    return {"best": best, "results": results}


def main():  # pragma: no cover - CLI shim (bin/ds_io)
    import argparse
    import json

    p = argparse.ArgumentParser(description="deepspeed_tpu IO benchmark (ds_io analog)")
    p.add_argument("folder")
    p.add_argument("--size-mb", type=int, default=256)
    p.add_argument("--threads", type=int, default=0, help="0 = sweep")
    a = p.parse_args()
    if a.threads:
        print(json.dumps(run_io_benchmark(a.folder, a.size_mb, a.threads)))
    else:
        print(json.dumps(sweep_io_config(a.folder, a.size_mb)))


def main_tune():  # pragma: no cover - CLI shim (bin/ds_nvme_tune)
    import argparse
    import json

    p = argparse.ArgumentParser(description="deepspeed_tpu NVMe tuner (ds_nvme_tune analog)")
    p.add_argument("folder", help="directory on the device to tune")
    p.add_argument("--size-mb", type=int, default=256)
    p.add_argument("--threads", type=int, nargs="*", default=None,
                   help="candidate thread counts (default 1 2 4 8)")
    a = p.parse_args()
    print(json.dumps(sweep_io_config(a.folder, a.size_mb, a.threads)))


if __name__ == "__main__":  # pragma: no cover
    main()
