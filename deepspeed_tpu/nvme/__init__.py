"""deepspeed_tpu.nvme: IO performance tooling (reference ``deepspeed/nvme/``
+ ``bin/ds_io``/``ds_nvme_tune``)."""

from deepspeed_tpu.nvme.perf import run_io_benchmark, sweep_io_config
