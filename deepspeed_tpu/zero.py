"""``deepspeed_tpu.zero`` — the user-facing ZeRO namespace (reference
``deepspeed.zero``: ``Init``, ``GatheredParameters``).

TPU-native mapping:

- ``zero.Init``: in the reference, wrapping model construction shards
  parameters as they are created so a model larger than one device's memory
  can materialize (``runtime/zero/partition_parameters.py:Init``). Here
  sharded construction is ALWAYS on — ``initialize`` traces the init function
  and materializes leaves directly into their target shardings under jit
  (``tests/unit/runtime/test_sharded_init.py``) — so ``Init`` is an
  API-compat context that simply yields; the semantics it exists for are the
  system default.
- ``zero.GatheredParameters``: the reference gathers partitioned torch
  params into full tensors inside the context and re-partitions on exit.
  The functional analog yields a MUTABLE dict of full numpy arrays
  (gathered across shards) and writes every leaf back to the engine's
  (sharded, possibly host-resident) masters on exit — the init-time weight
  surgery use case. Read-only access is cheaper via
  ``utils.safe_get_full_fp32_param``.
"""

from __future__ import annotations

import contextlib
from typing import Any, Iterator, Optional


@contextlib.contextmanager
def Init(*args: Any, **kwargs: Any) -> Iterator[None]:  # noqa: N802 - reference name
    """API-compat construction context (see module docstring): sharded
    construction is the default under ``initialize``; nothing to toggle."""
    yield


def GatheredParameters(engine: Any, modifier_rank: Optional[int] = None,  # noqa: N802
                       fwd_module: Any = None):
    """Yield the engine's full fp32 master params as nested numpy dicts;
    write them back (re-sharded / re-placed) on exit.

    TPU-native signature divergence (documented in
    ``docs/migrating-from-deepspeed.md``): the first argument is the ENGINE
    returned by ``deepspeed_tpu.initialize`` — params here are a pytree owned
    by the engine, not module-attached tensors, so the reference's
    ``GatheredParameters(params, modifier_rank=...)`` parameter-list form has
    no analog. Validated eagerly so migrating code fails with a clear
    TypeError instead of an opaque ``AttributeError`` later.

    ``modifier_rank``/``fwd_module`` accepted for reference signature parity
    (single-controller JAX has no per-rank modifier distinction).
    """
    if not hasattr(engine, "state"):
        raise TypeError(
            "GatheredParameters expects the ENGINE returned by "
            "deepspeed_tpu.initialize() as its first argument, got "
            f"{type(engine).__name__!r}. This diverges from the reference "
            "deepspeed.zero.GatheredParameters(params, modifier_rank=...): on "
            "TPU, parameters are a pytree owned by the engine (not module-"
            "attached tensors), so the context gathers from — and writes back "
            "to — the engine's masters. See docs/migrating-from-deepspeed.md."
        )
    return _gathered_parameters(engine)


@contextlib.contextmanager
def _gathered_parameters(engine: Any) -> Iterator[dict]:
    import jax
    import numpy as np

    # np.array copy: device_get returns read-only views; the context's whole
    # point is in-place mutation
    full = jax.tree_util.tree_map(lambda x: np.array(jax.device_get(x)),
                                  engine.state.params)
    yield full
    placed = jax.tree_util.tree_map(
        lambda v, old: jax.device_put(np.asarray(v, dtype=old.dtype), old.sharding),
        full, engine.state.params)
    engine.state = engine.state._replace(params=placed)
    # bf16 compute copies derive from the masters: invalidate any cache
    if getattr(engine, "offload_mode", None) in ("host-jit", "nvme"):
        engine._compute_dev = None
