"""deepspeed_tpu.profiling: FLOPs/MFU profiling (reference ``profiling/``).

The reference counts MACs with module hooks; here XLA's own cost analysis and
jaxpr traversal provide exact compiled-program numbers (see
``flops_profiler.py``).
"""

from deepspeed_tpu.profiling.attribution import (
    Attribution,
    attribute,
    attribute_program,
)
from deepspeed_tpu.profiling.flops_profiler import (
    FlopsProfiler,
    ProfileResult,
    compiled_cost,
    flops_by_op,
    get_model_profile,
)
