"""FLOPs profiler.

TPU-native analog of the reference flops profiler
(``profiling/flops_profiler/profiler.py:30 FlopsProfiler``): where the
reference patches ``torch.nn.functional`` and hooks every module to count
MACs, here the numbers come from the places XLA already knows them:

  - compiled-program cost analysis (``Compiled.cost_analysis()``: flops,
    bytes accessed, peak memory) — exact for the program XLA will run
  - jaxpr traversal for the per-op breakdown (dot_general / conv / einsum
    shapes → flops), the analog of the per-module table
  - wall-clock from timing real executions → achieved TFLOPS and MFU

Works on any jittable fn; ``FlopsProfiler`` wraps an engine's train step
(config section ``flops_profiler`` — reference ``profiling/config.py``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger

# Peak dense bf16 TFLOPS per chip for MFU math (public spec sheet numbers).
PEAK_TFLOPS = {
    "v4": 275.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6e": 918.0,
    "cpu": 0.0,  # unknown; MFU reported as 0 on CPU
}


def _detect_chip() -> str:
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001
        return "cpu"
    for key in ("v6e", "v5p", "v5e", "v4"):
        if key in kind.replace(" ", "").replace("lite", "e"):
            return key
    if "tpu" in kind and "v5" in kind:
        return "v5e"
    return "cpu"


# ------------------------------------------------------------- jaxpr walk
def _dot_flops(eqn) -> int:
    """2*M*N*K for dot_general from operand shapes."""
    a, b = eqn.invars[0].aval, eqn.invars[1].aval
    dims = eqn.params["dimension_numbers"]
    (a_contract, _), (a_batch, _) = dims
    batch = int(np.prod([a.shape[i] for i in a_batch])) if a_batch else 1
    k = int(np.prod([a.shape[i] for i in a_contract])) if a_contract else 1
    m = int(np.prod(a.shape)) // (batch * k)
    n = int(np.prod(b.shape)) // (batch * k)
    return 2 * batch * m * n * k


def _conv_flops(eqn) -> int:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    dn = eqn.params.get("dimension_numbers")
    o_dim = dn.rhs_spec[0] if dn is not None else 0  # kernel's output-feature dim
    per_output = int(np.prod(rhs.shape)) // int(rhs.shape[o_dim])
    return 2 * int(np.prod(out.shape)) * per_output


def flops_by_op(fn: Callable, *args, **kwargs) -> Dict[str, int]:
    """Per-primitive flop breakdown via jaxpr traversal (the per-module
    table analog — on TPU the natural unit is the XLA op, not nn.Module)."""
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    counts: Dict[str, int] = {}

    def walk(jx, mult: int):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name == "dot_general":
                counts[name] = counts.get(name, 0) + mult * _dot_flops(eqn)
            elif name == "conv_general_dilated":
                counts[name] = counts.get(name, 0) + mult * _conv_flops(eqn)
            else:
                # scan bodies run `length` times; other sub-jaxprs once
                sub_mult = mult * int(eqn.params.get("length", 1)) if name == "scan" else mult
                def _sub(v):
                    if hasattr(v, "jaxpr"):  # ClosedJaxpr (pjit/scan/cond bodies)
                        return v.jaxpr
                    if hasattr(v, "eqns"):  # open core.Jaxpr (remat2/custom_jvp)
                        return v
                    return None

                for v in eqn.params.values():
                    for u in v if isinstance(v, (list, tuple)) else (v,):
                        sub = _sub(u)
                        if sub is not None:
                            walk(sub, sub_mult)
        return counts

    return walk(jaxpr.jaxpr, 1)


# --------------------------------------------------------- compiled costs
def compiled_cost(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    """XLA cost analysis of the compiled program: exact flops/bytes.

    Routed through the compiled-program registry (telemetry/programs.py) so
    the analysis pass is recorded once and shared — repeated calls ride
    XLA's in-memory lowering/compile caches instead of re-analyzing, and
    with telemetry enabled the program lands in the ``program/*`` inventory
    like every engine-built program."""
    from deepspeed_tpu.telemetry.programs import get_program_registry

    rec = get_program_registry().capture(fn, *args, **kwargs)
    if rec is None:  # capture failed (non-jittable edge): old direct path
        return _costs_of(jax.jit(fn).lower(*args, **kwargs).compile())
    out = {"flops": rec.flops, "bytes accessed": rec.bytes_accessed}
    if rec.peak_hbm_bytes:
        out["peak_memory_bytes"] = float(rec.peak_hbm_bytes)
    return out


def _costs_of(compiled) -> Dict[str, float]:
    costs = compiled.cost_analysis()
    if isinstance(costs, list):  # older jax returns [dict]
        costs = costs[0] if costs else {}
    costs = dict(costs or {})
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            costs["peak_memory_bytes"] = float(
                getattr(mem, "temp_size_in_bytes", 0) + getattr(mem, "argument_size_in_bytes", 0)
            )
    except Exception:  # noqa: BLE001 - not all backends implement it
        pass
    return costs


@dataclass
class ProfileResult:
    flops_per_step: float
    bytes_accessed: float
    params: int
    latency_s: float
    achieved_tflops: float
    mfu: float
    per_op_flops: Dict[str, int] = field(default_factory=dict)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "flops_per_step": self.flops_per_step,
            "bytes_accessed": self.bytes_accessed,
            "params": self.params,
            "latency_s": self.latency_s,
            "achieved_tflops": self.achieved_tflops,
            "mfu": self.mfu,
            "per_op_flops": dict(self.per_op_flops),
        }

    def publish_to_telemetry(self, tracer=None) -> None:
        """Feed achieved-TFLOPS/MFU into the shared ``MetricsRegistry`` so
        MFU rides the same trace (Perfetto counter tracks), CSV/monitor
        scalars, and flight-recorder dumps as step time and comm bytes.
        No-op when telemetry is disabled (the zero-overhead contract)."""
        if tracer is None:
            from deepspeed_tpu.telemetry import get_tracer

            tracer = get_tracer()
        if not tracer.enabled:
            return
        # sample_counter = registry gauge + a plotted Perfetto counter track
        tracer.sample_counter("flops/mfu", self.mfu)
        tracer.sample_counter("flops/achieved_tflops", self.achieved_tflops)
        tracer.registry.gauge("flops/flops_per_step").set(self.flops_per_step)
        tracer.registry.gauge("flops/step_latency_ms").set(self.latency_s * 1e3)
        tracer.registry.gauge("flops/bytes_accessed").set(self.bytes_accessed)


def get_model_profile(fn: Callable, *args, warmup: int = 1, iters: int = 3,
                      params: Any = None, peak_tflops: Optional[float] = None,
                      n_devices: int = 1, **kwargs) -> ProfileResult:
    """Profile a jittable fn (reference ``get_model_profile``
    flops_profiler/profiler.py — same deliverables: flops, params, latency).

    ``n_devices``: how many devices the program is sharded over — XLA cost
    analysis reports PER-DEVICE flops while the jaxpr walk counts GLOBAL
    logical flops; the per-op table is divided by this so both agree.
    """
    # ONE lower+compile serves both execution (AOT call) and cost analysis —
    # a second jit of the same fn would recompile the whole program.
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    jfn = lambda *a, **kw: compiled(*a, **kw)

    def _sync(out):
        # A 4-byte host transfer of a scalar reduction is the only reliable
        # execution barrier: tunneled PJRT plugins ack block_until_ready
        # before the queue drains, and transferring a full leaf pays the
        # tunnel bandwidth. Device execution is in-order, so forcing the last
        # output forces everything before it.
        import jax.numpy as jnp

        leaf = jax.tree_util.tree_leaves(out)[0]
        np.asarray(jnp.sum(leaf))

    for _ in range(max(warmup, 1)):
        out = jfn(*args, **kwargs)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jfn(*args, **kwargs)
    _sync(out)
    latency = (time.perf_counter() - t0) / iters

    costs = _costs_of(compiled)
    flops = float(costs.get("flops", 0.0))
    bytes_accessed = float(costs.get("bytes accessed", costs.get("bytes_accessed", 0.0)))
    n_params = 0
    if params is not None:
        n_params = int(sum(np.prod(x.shape) for x in jax.tree_util.tree_leaves(params)))
    peak = peak_tflops if peak_tflops is not None else PEAK_TFLOPS.get(_detect_chip(), 0.0)
    try:
        per_op = flops_by_op(fn, *args, **kwargs)
    except Exception as e:  # noqa: BLE001 - breakdown is best-effort
        logger.debug(f"per-op flop breakdown unavailable: {e}")
        per_op = {}
    per_op = {k: v // max(n_devices, 1) for k, v in per_op.items()}
    if flops <= 0 and per_op:
        # some backends (CPU) omit an aggregate 'flops' key — fall back to the
        # jaxpr-derived matmul/conv count (a lower bound on true flops)
        flops = float(sum(per_op.values()))
    achieved = flops / latency / 1e12 if latency > 0 else 0.0
    result = ProfileResult(
        flops_per_step=flops,
        bytes_accessed=bytes_accessed,
        params=n_params,
        latency_s=latency,
        achieved_tflops=achieved,
        mfu=(achieved / peak if peak else 0.0),
        per_op_flops=per_op,
    )
    result.publish_to_telemetry()
    return result


class FlopsProfiler:
    """Engine-attached profiler (reference ``FlopsProfiler`` profiler.py:30).

    Two triggers, both honored by ``engine.train_batch``: the config
    (``flops_profiler.enabled`` + ``profile_step``, fires once), or an
    explicit ``start_profile()`` (fires on the next batch). Each profile
    disarms itself; ``print_model_profile()`` emits the report.
    """

    def __init__(self, engine=None, config=None):
        self.engine = engine
        # the single config the engine trigger reads (engine.train_batch)
        self.config = config or (engine.config.model.flops_profiler if engine else None)
        self.result: Optional[ProfileResult] = None
        self._armed = False

    def start_profile(self) -> None:
        self._armed = True

    def stop_profile(self) -> None:
        self._armed = False

    @property
    def armed(self) -> bool:
        return self._armed

    def profile_engine_step(self, batch):
        """Profile THE engine's compiled step on ``batch`` and execute it once.

        Lowers+compiles the engine's step once (AOT — donation and shardings
        preserved from the jit wrapper) and EXECUTES that same AOT object for
        the timed step, returning ``(new_state, metrics)``: the caller applies
        this as the real training step for the batch, so profiling never
        double-steps and the timed program is exactly the profiled one.
        """
        e = self.engine
        state = e.state
        from deepspeed_tpu.diagnostics.recompile import unwrap_jit
        from deepspeed_tpu.telemetry.programs import unwrap_program_watch

        step_wrapper = e._train_step
        step_fn = unwrap_program_watch(unwrap_jit(step_wrapper))

        import jax.numpy as jnp

        # The program registry already analyzed THIS wrapper's compiled step
        # at its dispatch compile — reuse that record and dispatch the normal
        # wrapped step (a cache hit) instead of lowering+compiling a second
        # throwaway copy of the program just to read costs.
        rec = getattr(step_wrapper, "_program_record", None)
        if rec is not None and (rec.flops or rec.bytes_accessed):
            costs = {"flops": rec.flops, "bytes accessed": rec.bytes_accessed}
            t0 = time.perf_counter()
            new_state, metrics = step_wrapper(state, batch)
            np.asarray(jnp.sum(metrics["loss"]))  # scalar-transfer execution barrier
            latency = time.perf_counter() - t0
        else:
            # registry off (or capture failed): the original AOT path
            compiled = step_fn.lower(state, batch).compile()
            costs = _costs_of(compiled)
            t0 = time.perf_counter()
            new_state, metrics = compiled(state, batch)
            np.asarray(jnp.sum(metrics["loss"]))  # scalar-transfer execution barrier
            latency = time.perf_counter() - t0
        flops = float(costs.get("flops", 0.0))

        n_dev = max(e.mesh.size, 1)
        try:
            per_op = {k: v // n_dev for k, v in flops_by_op(step_fn, state, batch).items()}
        except Exception as ex:  # noqa: BLE001 - breakdown is best-effort
            logger.debug(f"per-op flop breakdown unavailable: {ex}")
            per_op = {}
        if flops <= 0 and per_op:
            flops = float(sum(per_op.values()))
        n_params = int(sum(np.prod(x.shape) for x in jax.tree_util.tree_leaves(state.params)))
        peak = PEAK_TFLOPS.get(_detect_chip(), 0.0)
        achieved = flops / latency / 1e12 if latency > 0 else 0.0
        self.result = ProfileResult(
            flops_per_step=flops,
            bytes_accessed=float(costs.get("bytes accessed", 0.0)),
            params=n_params,
            latency_s=latency,
            achieved_tflops=achieved,
            mfu=(achieved / peak if peak else 0.0),
            per_op_flops=per_op,
        )
        self.result.publish_to_telemetry()
        self._armed = False
        return new_state, metrics

    # ------------------------------------------------------------ reporting
    def get_total_flops(self) -> float:
        return self.result.flops_per_step if self.result else 0.0

    def get_total_params(self) -> int:
        return self.result.params if self.result else 0

    def get_total_duration(self) -> float:
        return self.result.latency_s if self.result else 0.0

    def print_model_profile(self, top: int = 10) -> str:
        if self.result is None:
            return "flops profiler: no profile recorded"
        r = self.result
        lines = [
            "----------------- flops profiler (XLA cost analysis) -----------------",
            f"params:             {r.params/1e6:.2f} M",
            f"flops per step:     {r.flops_per_step/1e9:.2f} GFLOPs",
            f"bytes accessed:     {r.bytes_accessed/1e9:.3f} GB",
            f"step latency:       {r.latency_s*1e3:.2f} ms",
            f"achieved:           {r.achieved_tflops:.2f} TFLOPS (MFU {r.mfu*100:.1f}%)",
        ]
        if r.per_op_flops:
            total = max(sum(r.per_op_flops.values()), 1)
            lines.append("top ops by flops:")
            for name, fl in sorted(r.per_op_flops.items(), key=lambda kv: -kv[1])[:top]:
                lines.append(f"  {name:<24} {fl/1e9:>10.2f} GFLOPs  ({fl/total*100:.0f}% of matmul/conv)")
        report = "\n".join(lines)
        log_dist(report, ranks=[0])
        return report
