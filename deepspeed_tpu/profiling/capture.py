"""Anomaly-triggered ``jax.profiler`` capture.

The step-time anomaly detector (diagnostics/anomaly.py) can tell you a step
was slow; it cannot tell you *why*. This module closes that gap: when the
detector flags a straggler or sustained regression — or an operator sends
SIGUSR2, or code calls :meth:`ProfilerCapture.arm` — the next N steps run
under ``jax.profiler.start_trace`` and the resulting trace directory is
dropped next to the flight record, referenced from the dump context and a
telemetry instant, so the post-mortem of a slow step holds the device
timeline that explains it.

Discipline:
  - **armed ≠ active**: arming is a flag flip (any thread, signal-safe);
    the trace starts only at the next step boundary on the training thread —
    ``jax.profiler`` must bracket whole dispatches, not fire mid-step.
  - **bounded**: each window traces ``steps`` steps then stops;
    ``cooldown_steps`` gates how soon another anomaly can trigger again, so
    a straggler storm cannot turn the run into one long profile.
  - **never breaks the step**: start/stop failures (profiler already active
    in-process, unsupported backend) log and disarm.

SIGUSR2 wiring mirrors the flight recorder's process hooks: one handler per
process, dispatching to live captures through a WeakSet, chaining to any
previous handler.
"""

from __future__ import annotations

import os
import signal
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

_CAPTURES: "weakref.WeakSet[ProfilerCapture]" = weakref.WeakSet()
_HOOK_LOCK = threading.Lock()
_HOOK_INSTALLED = False
_PREV_HANDLER = None


def _sigusr2_handler(signum, frame):
    for cap in list(_CAPTURES):
        cap.arm(reason="signal:SIGUSR2")
    prev = _PREV_HANDLER
    if callable(prev):
        prev(signum, frame)


def arm_all(reason: str = "manual") -> int:
    """Arm every live capture in the process (same dispatch as the SIGUSR2
    hook, callable from code): the perf gate uses this so a detected
    regression leaves a profiler trace of the very next step window, not
    just a red exit code. Returns the number of captures reached."""
    caps = list(_CAPTURES)
    for cap in caps:
        cap.arm(reason=reason)
    return len(caps)


def install_sigusr2() -> None:
    """Install the SIGUSR2 → arm-capture hook (process-wide, once, main
    thread only — signal.signal raises elsewhere)."""
    global _HOOK_INSTALLED, _PREV_HANDLER
    with _HOOK_LOCK:
        if _HOOK_INSTALLED:
            return
        if threading.current_thread() is not threading.main_thread():
            return
        try:
            _PREV_HANDLER = signal.signal(signal.SIGUSR2, _sigusr2_handler)
            _HOOK_INSTALLED = True
        except (ValueError, OSError):  # pragma: no cover - exotic embedders
            pass


class ProfilerCapture:
    """Arms on trigger, traces the next N steps, records where the trace went.

    The engine brackets every step with :meth:`on_step_start` /
    :meth:`on_step_end` (one attribute check each when idle). ``captures``
    keeps one record per completed window so tests and the flight recorder
    can reference the trace without scraping logs.
    """

    def __init__(self, steps: int = 3, out_dir: Optional[str] = None,
                 cooldown_steps: int = 200, tracer=None, recorder=None):
        self.steps = max(int(steps), 1)
        self.cooldown_steps = max(int(cooldown_steps), 0)
        if out_dir is None:
            from deepspeed_tpu.telemetry.exporters import default_output_dir
            from deepspeed_tpu.telemetry.fleet import get_identity

            # per-process capture dir (proc 0 keeps the historical layout):
            # two replicas' device traces must land in joinable, distinct
            # directories, same policy as the flight-recorder dumps
            idx = get_identity().process_index
            sub = "profiler" if idx == 0 else f"profiler.p{idx}"
            out_dir = os.path.join(default_output_dir(), sub)
        self.out_dir = out_dir
        self.captures: List[Dict[str, Any]] = []
        self._armed_reason: Optional[str] = None
        self._active: Optional[Dict[str, Any]] = None
        self._last_window_step: Optional[int] = None
        if tracer is None:
            from deepspeed_tpu.telemetry import get_tracer

            tracer = get_tracer()
        self._tracer = tracer
        self._recorder = recorder  # FlightRecorder: trace path lands in dumps
        _CAPTURES.add(self)

    # ------------------------------------------------------------- triggers
    def arm(self, reason: str = "manual") -> None:
        """Request a capture window starting at the next step boundary.
        Idempotent while armed or active; any thread (signal handlers call
        this)."""
        if self._active is None and self._armed_reason is None:
            self._armed_reason = reason

    @property
    def active(self) -> bool:
        return self._active is not None

    # --------------------------------------------------------- step brackets
    def on_step_start(self, step: int) -> None:
        """Start the trace if armed (training thread, before dispatch)."""
        if self._armed_reason is None or self._active is not None:
            return
        if (self._last_window_step is not None
                and step - self._last_window_step < self.cooldown_steps):
            # inside the cooldown: drop the request, keep the run quiet
            self._armed_reason = None
            return
        reason = self._armed_reason
        self._armed_reason = None
        # a FAILED start consumes the cooldown too: a wedged in-process
        # profiler must not turn every subsequent anomaly into a retry storm
        self._last_window_step = step
        path = os.path.join(self.out_dir, f"step{step:06d}")
        try:
            import jax

            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
        except Exception as e:  # noqa: BLE001 — never break the step
            logger.warning(f"profiler capture failed to start ({reason}): {e}")
            try:  # best-effort: don't leave an empty stepNNNNNN dir behind
                os.rmdir(path)
            except OSError:
                pass
            return
        self._active = {"reason": reason, "path": path, "first_step": step,
                        "remaining": self.steps, "t0": time.perf_counter()}
        logger.warning(
            f"profiler capture armed by {reason}: tracing {self.steps} "
            f"step(s) from step {step} into {path}")

    def on_step_end(self, step: int) -> None:
        """Count the step; stop and record the window when it is full."""
        act = self._active
        if act is None:
            return
        act["remaining"] -= 1
        if act["remaining"] > 0:
            return
        self._active = None
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            logger.warning(f"profiler capture failed to stop: {e}")
            return
        from deepspeed_tpu.telemetry.fleet import get_identity

        ident = get_identity()
        record = {
            "reason": act["reason"],
            "trace_dir": act["path"],
            "first_step": act["first_step"],
            "last_step": step,
            "steps": self.steps,
            "wall_s": round(time.perf_counter() - act["t0"], 3),
            "run_id": ident.run_id,
            "process_index": ident.process_index,
        }
        self.captures.append(record)
        if self._tracer.enabled:
            self._tracer.count("anomaly/profiler_captures")
            self._tracer.instant("profiler_capture", cat="diagnostics", **record)
        if self._recorder is not None:
            # the crash dump's header names the freshest device trace
            self._recorder.set_context(profiler_trace=act["path"],
                                       profiler_trace_reason=act["reason"])
        logger.warning(
            f"profiler capture complete ({act['reason']}): steps "
            f"{act['first_step']}..{step} -> {act['path']}")
