"""Step-time attribution: where did the wall time of one step actually go?

Joins three measured sources the telemetry stack already collects —

  - the PR-7 **program registry**'s ``cost_analysis()`` flops +
    bytes-accessed for the compiled step program,
  - the PR-11 **collective observatory**'s per-route hop timings
    (``coll/hop_ms`` histogram children),
  - **tracer span** deltas (``span/<name>`` histograms, e.g. the host
    input-pipeline ``data`` span),

— into an exact four-bucket decomposition of the measured wall time::

    wall = compute + collective + host + stall

``compute`` is the roofline estimate ``max(flops/peak_flops,
bytes/peak_bw)`` clamped to the wall; ``collective`` and ``host`` are the
measured estimates clamped to what remains (each source is a lower bound
— a hop probe can't exceed the step that contained it); ``stall`` is the
non-negative residual (dispatch gaps, sync waits, anything unattributed).
The buckets sum to the wall **by construction** — the decomposition never
invents time, it only allocates the measured wall.

The verdict names the dominant bucket — ``compute`` / ``memory`` (the two
roofline regimes), ``comm``, ``host``, or ``stall`` — alongside
achieved-vs-peak fractions, published as ``perf/attribution_*`` and
``perf/roofline_*`` gauges so the ledger's trajectory and a step's
decomposition read from one registry. This is the measured objective the
ROADMAP's schedule-compiler and overlap work optimize against.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

#: conservative peak envelopes per ledger backend: bf16 matmul flops and
#: HBM bandwidth for v5e (datasheet); the cpu-smoke figure matches
#: bench.py's PEAK_FLOPS_CPU_SMOKE convention (MFU on CPU is a smoke
#: number, not a claim)
PEAK_FLOPS: Dict[str, float] = {"cpu": 1e12, "tpu-v5e": 197e12,
                                "interpret": 1e12}
PEAK_BYTES_PER_S: Dict[str, float] = {"cpu": 50e9, "tpu-v5e": 819e9,
                                      "interpret": 50e9}


@dataclass
class Attribution:
    label: str
    wall_ms: float
    compute_ms: float
    collective_ms: float
    host_ms: float
    stall_ms: float
    bound: str               # compute | memory | comm | host | stall
    flops: float = 0.0
    bytes_accessed: float = 0.0
    flops_fraction: float = 0.0   # achieved flops rate / peak
    bw_fraction: float = 0.0      # achieved HBM rate / peak

    def buckets(self) -> Dict[str, float]:
        return {"compute": self.compute_ms, "collective": self.collective_ms,
                "host": self.host_ms, "stall": self.stall_ms}

    def as_dict(self) -> Dict[str, Any]:
        d = {"label": self.label, "wall_ms": self.wall_ms,
             "bound": self.bound, "flops": self.flops,
             "bytes_accessed": self.bytes_accessed,
             "flops_fraction": self.flops_fraction,
             "bw_fraction": self.bw_fraction}
        d.update({f"{k}_ms": v for k, v in self.buckets().items()})
        return d

    def render(self) -> str:
        parts = [f"{k}={v:.2f}ms ({v / self.wall_ms:.0%})" if self.wall_ms
                 else f"{k}={v:.2f}ms" for k, v in self.buckets().items()]
        return (f"{self.label}: wall={self.wall_ms:.2f}ms -> "
                + " ".join(parts)
                + f" | {self.bound}-bound, {self.flops_fraction:.1%} of peak "
                  f"flops, {self.bw_fraction:.1%} of peak bw")


def attribute(label: str, wall_s: float, *, flops: float = 0.0,
              bytes_accessed: float = 0.0,
              peak_flops: Optional[float] = None,
              peak_bytes_per_s: Optional[float] = None,
              collective_s: float = 0.0, host_s: float = 0.0,
              registry=None, publish: bool = True) -> Attribution:
    """The pure decomposition. All inputs are seconds/flops/bytes for ONE
    step (or one serving chain); estimates are clamped so the four buckets
    always sum exactly to ``wall_s``."""
    wall_s = max(float(wall_s), 0.0)
    flop_term = (flops / peak_flops) if (peak_flops and flops > 0) else 0.0
    bw_term = (bytes_accessed / peak_bytes_per_s) \
        if (peak_bytes_per_s and bytes_accessed > 0) else 0.0
    compute_s = min(max(flop_term, bw_term), wall_s)
    coll_s = min(max(float(collective_s), 0.0), wall_s - compute_s)
    hst_s = min(max(float(host_s), 0.0), wall_s - compute_s - coll_s)
    stall_s = wall_s - compute_s - coll_s - hst_s

    buckets = {"compute": compute_s, "comm": coll_s, "host": hst_s,
               "stall": stall_s}
    bound = max(buckets, key=lambda k: buckets[k])
    if bound == "compute" and bw_term > flop_term:
        bound = "memory"

    flops_frac = (flops / wall_s / peak_flops) \
        if (wall_s > 0 and peak_flops) else 0.0
    bw_frac = (bytes_accessed / wall_s / peak_bytes_per_s) \
        if (wall_s > 0 and peak_bytes_per_s) else 0.0

    attr = Attribution(
        label=label, wall_ms=wall_s * 1e3, compute_ms=compute_s * 1e3,
        collective_ms=coll_s * 1e3, host_ms=hst_s * 1e3,
        stall_ms=stall_s * 1e3, bound=bound, flops=float(flops),
        bytes_accessed=float(bytes_accessed), flops_fraction=flops_frac,
        bw_fraction=bw_frac)
    if publish:
        _publish(attr, registry)
    return attr


def _publish(attr: Attribution, registry=None) -> None:
    if registry is None:
        from deepspeed_tpu.telemetry import get_tracer

        tracer = get_tracer()
        if not tracer.enabled:
            return
        registry = tracer.registry
    g = registry.gauge
    g("perf/attribution_wall_ms", program=attr.label).set(attr.wall_ms)
    g("perf/attribution_compute_ms", program=attr.label).set(attr.compute_ms)
    g("perf/attribution_collective_ms",
      program=attr.label).set(attr.collective_ms)
    g("perf/attribution_host_ms", program=attr.label).set(attr.host_ms)
    g("perf/attribution_stall_ms", program=attr.label).set(attr.stall_ms)
    g("perf/attribution_bound", program=attr.label, bound=attr.bound).set(1.0)
    g("perf/roofline_flops_fraction",
      program=attr.label).set(attr.flops_fraction)
    g("perf/roofline_bw_fraction", program=attr.label).set(attr.bw_fraction)


# ------------------------------------------------------- measured sources
def measured_collective_s(registry=None) -> float:
    """Lower-bound estimate of one step's collective time: the sum of each
    routed signature's most recent per-hop probe (``coll/hop_ms``
    children, PR 11). Probes are per-hop samples, so this undercounts
    multi-hop rings — honest as a floor, never as a ceiling."""
    if registry is None:
        from deepspeed_tpu.telemetry import get_tracer

        registry = get_tracer().registry
    total_ms = 0.0
    for kind, _key, metric in registry.iter_metrics():
        if kind == "histogram" and metric.name == "coll/hop_ms" \
                and metric.count:
            total_ms += float(metric.last)
    return total_ms / 1e3


def span_last_s(name: str, registry=None) -> float:
    """Most recent duration of tracer span ``name`` (0.0 when the span
    never ran — e.g. ``data`` before the first host batch)."""
    if registry is None:
        from deepspeed_tpu.telemetry import get_tracer

        registry = get_tracer().registry
    h = registry.peek_histogram(f"span/{name}")
    return float(h.last) if h is not None and h.count else 0.0


def attribute_program(label: str, wall_s: float, *,
                      backend: Optional[str] = None, registry=None,
                      host_span: str = "data", publish: bool = True,
                      ) -> Attribution:
    """Attribution for a registered compiled program (e.g. the engine's
    ``train_step``): flops/bytes from the program registry's latest
    capture, collective floor from the observatory, host time from the
    ``host_span`` tracer span, peaks from the ledger backend."""
    from deepspeed_tpu.telemetry.perfledger import default_backend
    from deepspeed_tpu.telemetry.programs import get_program_registry

    backend = backend or default_backend()
    rec = get_program_registry().latest(label)
    return attribute(
        label, wall_s,
        flops=float(rec.flops) if rec else 0.0,
        bytes_accessed=float(rec.bytes_accessed) if rec else 0.0,
        peak_flops=PEAK_FLOPS.get(backend),
        peak_bytes_per_s=PEAK_BYTES_PER_S.get(backend),
        collective_s=measured_collective_s(registry),
        host_s=span_last_s(host_span, registry),
        registry=registry, publish=publish)
