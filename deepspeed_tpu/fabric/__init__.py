"""Cross-process serving fabric (ISSUE 18).

A control plane over real OS-process boundaries, built on the same
stdlib-HTTP ``RouteServer`` discipline as the fleet collector:

- :mod:`deepspeed_tpu.fabric.wire` — JSON-safe byte-verbatim tensor and
  ``MigrationBuffer`` serialization (blake2b block identity survives the
  wire).
- :mod:`deepspeed_tpu.fabric.replica_daemon` — wraps a v2 engine behind
  POST ``/admit``, ``/chain_round``, ``/preempt``, ``/export_request``,
  ``/import_request``, ``/drain`` (+ GET ``/healthz``) in its own
  process, propagating ``fleet.TraceContext`` so per-request flow arrows
  join across pids in ``tools/trace_merge.py``.
- :mod:`deepspeed_tpu.fabric.remote` — ``RemoteReplica``, a client that
  satisfies the router's replica protocol over RPC so the unchanged
  ``ServingRouter`` scheduling drives a mixed roster of local and remote
  replicas.

See ``docs/serving_fabric.md`` for the endpoint table, roster lifecycle,
liveness semantics, and the wire-vs-DMA migration split.
"""

from deepspeed_tpu.fabric.remote import RemoteReplica, RemoteReplicaDownError
from deepspeed_tpu.fabric.replica_daemon import ReplicaDaemon

__all__ = ["RemoteReplica", "RemoteReplicaDownError", "ReplicaDaemon"]
