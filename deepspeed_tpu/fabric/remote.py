"""RemoteReplica: the router's replica protocol over stdlib-HTTP RPC.

``RemoteReplica`` duck-types ``InferenceEngineV2``'s serving surface —
``try_admit``, ``_put_sample``, ``decode_chain``/``decode_spec_chain``,
``chain_window``, ``_can_schedule_evicting``, KV export/import, flush —
so the UNCHANGED ``ServingRouter`` scheduling (SLO admission, disagg
roles, migration tickets, preempt-youngest) drives a mixed roster of
local engines and daemons in other OS processes. Scheduling state stays
router-side; the remote carries only per-dispatch batches and the
replica's own pool state.

Liveness rides a heartbeat thread polling ``GET /healthz``: after
``heartbeat_miss_limit`` consecutive misses the replica flips
``alive=False`` and the router re-admits its in-flight requests on
survivors. A transport error during a dispatch raises
:class:`RemoteReplicaDownError` (marker attribute ``replica_gone``) —
the router converts it into the same mark-dead path instead of aborting
the serve, which is how "admitted requests are never dropped" survives a
SIGKILL mid-decode.

Queue-depth and goodput signals ride the heartbeat into ``remote_load``,
which the router folds into its load score — a saturated daemon repels
new placements exactly like a deep local queue.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.fabric.wire import (
    export_from_wire,
    export_to_wire,
    key_from_wire,
    key_to_wire,
)
from deepspeed_tpu.telemetry.tracer import get_tracer

__all__ = ["RemoteReplica", "RemoteReplicaDownError"]


class RemoteReplicaDownError(RuntimeError):
    """Transport-level failure talking to a replica daemon. The marker
    attribute lets the router detect it without importing this module."""

    replica_gone = True


def _post(url: str, path: str, doc: Dict, timeout: float) -> Dict:
    data = json.dumps(doc).encode()
    req = urllib.request.Request(
        url + path, data=data, headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        # RouteServer answers 400 for handler ValueError/KeyError/TypeError:
        # those are CONTRACT errors (layout mismatch, unknown uid) and must
        # re-raise as ValueError — the in-process exception the router's
        # migration machinery already handles. Anything else is transport.
        if e.code == 400:
            try:
                msg = json.loads(e.read().decode()).get("error", str(e))
            except Exception:  # noqa: BLE001 - body already lost
                msg = str(e)
            raise ValueError(msg) from None
        raise RemoteReplicaDownError(f"{url}{path}: HTTP {e.code}") from None
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        raise RemoteReplicaDownError(f"{url}{path}: {e}") from None


def _get(url: str, path: str, timeout: float) -> Dict:
    try:
        with urllib.request.urlopen(url + path, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        raise RemoteReplicaDownError(f"{url}{path}: {e}") from None


class _RemotePoolLeaf:
    """Shape-only stand-in for one pool tensor: the router's disagg layout
    check reads ``pool.k.dtype``."""

    def __init__(self, dtype):
        self.dtype = dtype


class _RemotePool:
    def __init__(self, quant: Optional[str], kv_dtype: str):
        import jax.numpy as jnp

        self.quant = quant
        self.k = _RemotePoolLeaf(jnp.dtype(kv_dtype))


class _RemotePrefixCache:
    """Router-facing view of the daemon's prefix cache: existence gates the
    post-import/post-prefill ``_insert_prefix`` calls; the hit rate rides
    ``GET /stats``."""

    def __init__(self, replica: "RemoteReplica"):
        self._replica = replica

    @property
    def hit_rate(self) -> float:
        return float(self._replica.stats().get("prefix_hit_rate", 0.0))


class RemoteReplica:
    """Client half of a replica daemon — see module docstring.

    ``__init__`` fetches ``GET /spec`` and reconstructs the daemon's real
    ``RaggedInferenceConfig`` from its dump, so every config-derived router
    decision (role, SLO targets, chain length, spec mode, migration depth)
    is computed from the daemon's OWN settings, not a client-side copy.
    """

    def __init__(self, url: str, timeout: float = 60.0,
                 heartbeat_interval_s: float = 0.25,
                 heartbeat_miss_limit: int = 4,
                 start_heartbeat: bool = True,
                 tracer=None):
        from deepspeed_tpu.inference.engine_v2 import RaggedInferenceConfig

        self.url = url.rstrip("/")
        self.timeout = float(timeout)
        self._tracer = tracer if tracer is not None else get_tracer()
        spec = _get(self.url, "/spec", self.timeout)
        self.config = RaggedInferenceConfig(**spec["config"])
        self.num_kv_blocks = int(spec["num_kv_blocks"])
        self.max_seq_len = int(spec["max_seq_len"])
        self.pool = _RemotePool(spec["quant"], spec["kv_dtype"])
        self.prefix_cache = (_RemotePrefixCache(self)
                             if spec.get("prefix_cache") else None)
        self.mesh = self._local_mesh()
        # router-facing accounting attrs (same names as the local engine)
        self.tokens_decoded = 0
        self.dispatch_count = 0
        self._recorder = None
        # liveness + load signals (heartbeat-fed)
        self.alive = True
        self.draining = False
        self.queue_depth = 0.0
        self.goodput = 1.0
        self.heartbeat_misses = 0
        self.last_heartbeat: Optional[Dict] = None
        self._hb_interval = float(heartbeat_interval_s)
        self._hb_miss_limit = int(heartbeat_miss_limit)
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        if start_heartbeat:
            self.start_heartbeat()

    @staticmethod
    def _local_mesh():
        """A one-device local mesh: the router replicates its per-replica
        PRNG key onto ``engine.mesh`` — for a remote replica the key only
        needs a host-side home before it rides the wire."""
        import jax
        from jax.sharding import Mesh

        return Mesh(np.asarray(jax.devices()[:1]), ("fabric",))

    # ------------------------------------------------------------ liveness
    def start_heartbeat(self) -> None:
        if self._hb_thread is not None:
            return
        self._hb_thread = threading.Thread(
            target=self._hb_loop, name="dstpu-fabric-heartbeat", daemon=True)
        self._hb_thread.start()

    def stop_heartbeat(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
            self._hb_thread = None

    def heartbeat_now(self) -> bool:
        """One poll of ``GET /healthz``; updates load/liveness signals.
        Returns True on a successful beat."""
        try:
            doc = _get(self.url, "/healthz",
                       timeout=max(self._hb_interval, 0.2) * 2)
        except RemoteReplicaDownError:
            self.heartbeat_misses += 1
            if self._tracer.enabled:
                self._tracer.registry.counter(
                    "fabric/heartbeat_misses").add(1)
            if self.heartbeat_misses >= self._hb_miss_limit and self.alive:
                self.alive = False
                if self._tracer.enabled:
                    self._tracer.registry.counter(
                        "fabric/dead_replicas").add(1)
                from deepspeed_tpu.telemetry.events import emit_event

                emit_event(
                    "fabric", "replica_unreachable",
                    f"remote replica {self.url} unreachable: "
                    f"{self.heartbeat_misses} consecutive heartbeat misses",
                    severity="critical", labels={"url": self.url},
                    dedup_key=f"fabric:replica_unreachable:{self.url}")
            return False
        self.heartbeat_misses = 0
        self.last_heartbeat = doc
        self.queue_depth = float(doc.get("queue_depth", 0.0))
        self.goodput = float(doc.get("goodput", 1.0))
        self.draining = bool(doc.get("draining", False))
        return True

    def _hb_loop(self) -> None:
        while not self._hb_stop.wait(self._hb_interval):
            self.heartbeat_now()
            if not self.alive:
                return

    @property
    def remote_load(self) -> float:
        """Extra load score the router folds into placement: the daemon's
        own queue depth plus its goodput deficit (mirrors ``_Replica.load``
        for work the router did not dispatch itself)."""
        if not self.alive:
            return float("inf")
        return self.queue_depth + (1.0 - self.goodput)

    # ----------------------------------------------------------------- rpc
    def _rpc(self, path: str, doc: Dict) -> Dict:
        endpoint = path.lstrip("/")
        t0 = time.perf_counter()
        try:
            ack = _post(self.url, path, doc, self.timeout)
        except RemoteReplicaDownError as e:
            # a 400 (ValueError) is the replica answering — only transport
            # failures count against the endpoint and land on the event
            # stream (the alert engine's rpc_failures rule reads these)
            if self._tracer.enabled:
                self._tracer.registry.counter(
                    "fabric/rpc_failures", endpoint=endpoint).add(1)
            from deepspeed_tpu.telemetry.events import emit_event

            emit_event("fabric", "rpc_failure",
                       f"fabric RPC {endpoint} to {self.url} failed: {e}",
                       severity="warn",
                       labels={"endpoint": endpoint, "url": self.url},
                       dedup_key=f"fabric:rpc_failure:{self.url}:{endpoint}")
            raise
        if self._tracer.enabled:
            self._tracer.registry.histogram(
                "fabric/rpc_ms", endpoint=endpoint).observe(
                (time.perf_counter() - t0) * 1e3)
        return ack

    def _ctx_wires(self, tracker, rids: Optional[Sequence[int]],
                   n: int) -> List[Optional[Dict]]:
        """Per-row wire TraceContexts (from the router-side lifecycle
        tracker) so the daemon's dispatch spans join each request's flow."""
        if tracker is None or rids is None:
            return [None] * n
        out: List[Optional[Dict]] = []
        for rid in rids:
            ctx = tracker.trace_context(rid)
            out.append(None if ctx is None else ctx.to_wire())
        return out

    # ------------------------------------------------- admission/scheduling
    def try_admit(self, uid: int, cand: np.ndarray, other_uids: Sequence[int],
                  other_counts: Sequence[int]) -> Optional[np.ndarray]:
        ack = self._rpc("/admit", {
            "uid": int(uid), "cand": [int(t) for t in np.asarray(cand)],
            "other_uids": [int(u) for u in other_uids],
            "other_counts": [int(c) for c in other_counts]})
        if self.draining or ack.get("draining"):
            return None
        s = ack.get("suffix")
        return None if s is None else np.asarray(s, np.int32)

    def _can_schedule_evicting(self, uids, counts) -> bool:
        ack = self._rpc("/can_schedule", {
            "uids": [int(u) for u in uids],
            "counts": [int(c) for c in counts]})
        return bool(ack["ok"])

    def chain_window(self, budgets: Sequence[int], k: int) -> List[int]:
        # pure config arithmetic — no RPC (same formula as the engine)
        m = 1 + self.config.spec_decode
        return [min(k * m, int(b)) + self.config.spec_decode
                for b in budgets]

    def query(self, uid: int) -> Tuple[int, int]:
        ack = self._rpc("/query", {"uid": int(uid)})
        return int(ack["seen"]), int(ack["free"])

    def flush(self, uid: int) -> None:
        self._rpc("/flush", {"uid": int(uid)})

    def preempt(self, uid: int) -> None:
        self._rpc("/preempt", {"uid": int(uid)})

    def _insert_prefix(self, uid: int, full_tokens: np.ndarray) -> None:
        self._rpc("/insert_prefix", {
            "uid": int(uid),
            "tokens": [int(t) for t in np.asarray(full_tokens)]})

    # ----------------------------------------------------------- dispatches
    def _put_sample(self, uids, token_lists, rng, sample_kw: Tuple,
                    tracker=None, rids=None) -> Tuple[np.ndarray, Any]:
        doc = {
            "uids": [int(u) for u in uids],
            "token_lists": [[int(t) for t in np.asarray(tl)]
                            for tl in token_lists],
            "rng": key_to_wire(rng),
            "sample_kw": [list(p) for p in sample_kw],
            "ctxs": self._ctx_wires(tracker, rids, len(uids)),
        }
        with self._tracer.span("serve:dispatch", kind="prefill",
                               rows=len(uids), remote=self.url):
            if tracker is not None and rids is not None:
                tracker.mark_dispatch(rids, "prefill")
            ack = self._rpc("/prefill", doc)
        self.dispatch_count += 1
        return np.asarray(ack["toks"], np.int32), key_from_wire(ack["rng"])

    def decode_chain(self, uids, last_tokens, budgets, k, rng,
                     eos_id: Optional[int] = None,
                     sample_kw: Tuple = (("do_sample", False),),
                     tracker=None, rids=None):
        doc = {
            "uids": [int(u) for u in uids],
            "last_tokens": [int(t) for t in last_tokens],
            "budgets": [int(b) for b in budgets],
            "k": int(k), "rng": key_to_wire(rng),
            "eos_id": None if eos_id is None else int(eos_id),
            "sample_kw": [list(p) for p in sample_kw],
            "spec": False,
            "ctxs": self._ctx_wires(tracker, rids, len(uids)),
        }
        with self._tracer.span("serve:dispatch", kind="chain",
                               rows=len(uids), k=int(k), remote=self.url):
            if tracker is not None and rids is not None:
                tracker.mark_dispatch(rids, "chain")
            ack = self._rpc("/chain_round", doc)
        self.dispatch_count += 1
        return (np.asarray(ack["out"], np.int32),
                np.asarray(ack["emitted"], np.int32),
                key_from_wire(ack["rng"]))

    def decode_spec_chain(self, uids, last_tokens, budgets, k, rng,
                          histories, eos_id: Optional[int] = None,
                          tracker=None, rids=None):
        doc = {
            "uids": [int(u) for u in uids],
            "last_tokens": [int(t) for t in last_tokens],
            "budgets": [int(b) for b in budgets],
            "k": int(k), "rng": key_to_wire(rng),
            "eos_id": None if eos_id is None else int(eos_id),
            "spec": True,
            "histories": [[int(t) for t in np.asarray(h)]
                          for h in histories],
            "ctxs": self._ctx_wires(tracker, rids, len(uids)),
        }
        with self._tracer.span("serve:dispatch", kind="spec_chain",
                               rows=len(uids), k=int(k), remote=self.url):
            if tracker is not None and rids is not None:
                tracker.mark_dispatch(rids, "chain")
            ack = self._rpc("/chain_round", doc)
        self.dispatch_count += 1
        return (np.asarray(ack["out"], np.int32),
                np.asarray(ack["emitted"], np.int32),
                key_from_wire(ack["rng"]))

    # ------------------------------------------------------------ migration
    def export_request(self, uid: int) -> Dict[str, Any]:
        t0 = time.perf_counter()
        ack = self._rpc("/export_request", {"uid": int(uid)})
        export = export_from_wire(ack)
        export.pop("ok", None)
        if self._tracer.enabled:
            self._tracer.registry.histogram(
                "fabric/wire_migration_ms").observe(
                    (time.perf_counter() - t0) * 1e3)
        return export

    def can_import(self, n_blocks: int) -> bool:
        ack = self._rpc("/can_import", {"n_blocks": int(n_blocks)})
        return bool(ack["ok"])

    def import_request(self, uid: int, export: Dict[str, Any],
                       ctx=None) -> bool:
        t0 = time.perf_counter()
        doc = {"uid": int(uid), "export": export_to_wire(export),
               "ctx": None if ctx is None else ctx.to_wire()}
        ack = self._rpc("/import_request", doc)
        if self._tracer.enabled:
            self._tracer.registry.histogram(
                "fabric/wire_migration_ms").observe(
                    (time.perf_counter() - t0) * 1e3)
        return bool(ack["ok"])

    def block_hashes(self, uid: int) -> List[str]:
        return list(self._rpc("/block_hashes", {"uid": int(uid)})["hashes"])

    # -------------------------------------------------------------- control
    def drain(self) -> List[int]:
        """Ask the daemon to quiesce admissions; returns its active uids.
        The router's ``request_drain`` pairs this with peer handoff."""
        ack = self._rpc("/drain", {})
        self.draining = True
        return [int(u) for u in ack.get("active_uids", ())]

    def dump_trace(self, path: str) -> str:
        return str(self._rpc("/dump_trace", {"path": path})["path"])

    def request_shutdown(self) -> None:
        try:
            self._rpc("/shutdown", {})
        except RemoteReplicaDownError:
            pass  # already gone — that is what shutdown is for

    def stats(self) -> Dict[str, Any]:
        try:
            return _get(self.url, "/stats", self.timeout)
        except RemoteReplicaDownError:
            return {}

    def close(self) -> None:
        self.stop_heartbeat()
