"""Byte-verbatim tensor serialization for the serving fabric.

The cross-process control plane is JSON over stdlib HTTP (same transport
discipline as ``FleetCollector``), so tensors ride as base64 of the raw
buffer plus a dtype/shape header. Two properties matter:

- **bytes verbatim**: the KV pool may be int8/fp8/bf16; quantized
  payloads and their fp32 scales must cross the boundary bit-exact so
  ``PagedKVPool._block_content_hash`` (blake2b over the raw slices)
  yields the *same digest* on both sides — that digest equality is the
  fabric's end-to-end migration-fidelity gate.
- **dtype fidelity**: dtype names round-trip through ``jnp.dtype`` so
  extended types (bfloat16, float8_*) resolve via the ml_dtypes registry
  rather than numpy's builtin table.

On TPU this wire path is the *control* plane only — bulk KV moves between
co-resident chips use ``migrate.remote_copy_pages`` (device-to-device
DMA); the wire path carries KV bytes when the hop crosses a host.
"""

from __future__ import annotations

import base64
from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.paged import MigrationBuffer

__all__ = [
    "array_to_wire",
    "array_from_wire",
    "export_to_wire",
    "export_from_wire",
    "key_to_wire",
    "key_from_wire",
]


def array_to_wire(a: Optional[Any]) -> Optional[Dict[str, Any]]:
    """ndarray/jax.Array -> JSON-safe ``{"dtype", "shape", "data"}`` (or None)."""
    if a is None:
        return None
    arr = np.asarray(a)
    return {
        "dtype": str(arr.dtype),
        "shape": list(arr.shape),
        "data": base64.b64encode(arr.tobytes()).decode("ascii"),
    }


def array_from_wire(doc: Optional[Dict[str, Any]]) -> Optional[np.ndarray]:
    """Inverse of :func:`array_to_wire`; returns a writable numpy array."""
    if doc is None:
        return None
    dt = jnp.dtype(doc["dtype"])  # ml_dtypes-aware (bfloat16, float8_*)
    raw = base64.b64decode(doc["data"])
    return np.frombuffer(raw, dtype=dt).reshape(doc["shape"]).copy()


def key_to_wire(rng: Any) -> Dict[str, Any]:
    """PRNG key -> wire doc (legacy uint32[2] keys are plain arrays)."""
    return array_to_wire(np.asarray(rng))


def key_from_wire(doc: Dict[str, Any]) -> np.ndarray:
    return array_from_wire(doc)


def export_to_wire(export: Dict[str, Any]) -> Dict[str, Any]:
    """``engine.export_request`` dict -> JSON-safe doc.

    The ``MigrationBuffer`` leaves (k, v and optional per-block scales)
    are serialized byte-verbatim; the scalar metadata (block geometry,
    seen tokens, pool dtype/quant mode) passes through unchanged so the
    importer's layout check is exactly the in-process one.
    """
    buf = export["buffer"]
    doc = {k: v for k, v in export.items() if k != "buffer"}
    doc["buffer"] = {
        "k": array_to_wire(buf.k),
        "v": array_to_wire(buf.v),
        "k_scale": array_to_wire(buf.k_scale),
        "v_scale": array_to_wire(buf.v_scale),
    }
    return doc


def export_from_wire(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of :func:`export_to_wire`."""
    wire_buf = doc["buffer"]
    export = {k: v for k, v in doc.items() if k != "buffer"}
    export["buffer"] = MigrationBuffer(
        k=array_from_wire(wire_buf["k"]),
        v=array_from_wire(wire_buf["v"]),
        k_scale=array_from_wire(wire_buf.get("k_scale")),
        v_scale=array_from_wire(wire_buf.get("v_scale")),
    )
    return export
