"""Replica daemon: a v2 engine behind stdlib HTTP in its own OS process.

``ReplicaDaemon`` exposes the router's replica protocol (admission, fused
prefill, decode chains, preemption, KV export/import, drain) as POST
routes on a :class:`~deepspeed_tpu.telemetry.exposition.RouteServer` —
the same one daemon-thread/bind/handler implementation behind the fleet
collector, so the fabric adds no new transport machinery.

Observability joins the existing planes end to end:

- the daemon configures ``fleet.ProcessIdentity`` (``role="replica"``) so
  its heartbeats and trace stream carry the fleet identity;
- every dispatched batch row re-enters the sender's trace through
  ``fleet.dispatch_span`` with the request's ``TraceContext`` — the flow
  STEP lands inside this process's ``serve:dispatch`` slice, so
  ``tools/trace_merge.py`` draws the router→replica arrow across pids;
- ``/block_hashes`` exposes ``_block_content_hash`` digests so the smoke
  can prove wire migration moved the quantized pool bytes verbatim.

Run as a subprocess via ``python -m deepspeed_tpu.fabric.replica_daemon``
(one JSON line ``{"port": N}`` on stdout once serving), or embed
``ReplicaDaemon(engine).start()`` in-process for tests.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.fabric.wire import (
    export_from_wire,
    export_to_wire,
    key_from_wire,
    key_to_wire,
)
from deepspeed_tpu.telemetry import fleet
from deepspeed_tpu.telemetry.exposition import RouteServer
from deepspeed_tpu.telemetry.tracer import get_tracer

__all__ = ["ReplicaDaemon", "main"]


def _sample_kw(doc: Any) -> Tuple:
    """Wire sample_kw (list of [k, v] pairs) -> the hashable tuple-of-pairs
    form the engine's jit-static step cache keys on."""
    if doc is None:
        return (("do_sample", False),)
    return tuple((str(k), v) for k, v in doc)


class ReplicaDaemon:
    """One engine, one process, one route table.

    All engine-touching handlers serialize on a single lock: the v2 engine
    mutates ``self.pool`` with donated buffers, so two concurrent RPCs must
    never interleave inside it. The router already serializes per-replica
    traffic (one dispatch thread per replica), so the lock is contention-
    free in the steady state and purely a safety net for control RPCs
    (drain, export) landing mid-dispatch.
    """

    def __init__(self, engine: Any, host: str = "127.0.0.1", port: int = 0,
                 config_doc: Optional[Dict[str, Any]] = None):
        self.engine = engine
        self.draining = False
        self._lock = threading.RLock()
        self._shutdown = threading.Event()
        self._tracer = get_tracer()
        self._requests = 0
        self._preempts = 0
        self._migrations_in = 0
        self._migrations_out = 0
        if config_doc is None:
            dump = getattr(engine.config, "model_dump", None) or getattr(
                engine.config, "dict", None)
            config_doc = json.loads(json.dumps(dump(), default=str)) if dump else {}
        self._config_doc = config_doc
        self.server = RouteServer(
            get_routes={
                "/healthz": self._get_healthz,
                "/spec": self._get_spec,
                "/stats": self._get_stats,
            },
            post_routes={
                path: self._timed(path.lstrip("/"), fn)
                for path, fn in {
                    "/admit": self._post_admit,
                    "/prefill": self._post_prefill,
                    "/chain_round": self._post_chain_round,
                    "/can_schedule": self._post_can_schedule,
                    "/query": self._post_query,
                    "/flush": self._post_flush,
                    "/preempt": self._post_preempt,
                    "/insert_prefix": self._post_insert_prefix,
                    "/export_request": self._post_export_request,
                    "/import_request": self._post_import_request,
                    "/can_import": self._post_can_import,
                    "/block_hashes": self._post_block_hashes,
                    "/drain": self._post_drain,
                    "/dump_trace": self._post_dump_trace,
                    "/shutdown": self._post_shutdown,
                }.items()
            },
            port=port, host=host, name="dstpu-replica-daemon")

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ReplicaDaemon":
        self.server.start()
        return self

    def stop(self) -> None:
        self.server.stop()

    @property
    def url(self) -> str:
        return f"http://{self.server._host}:{self.server.port}"

    def wait_shutdown(self, timeout: Optional[float] = None) -> bool:
        return self._shutdown.wait(timeout)

    def _count(self, name: str, n: int = 1) -> None:
        if self._tracer.enabled:
            self._tracer.registry.counter(name).add(n)

    def _timed(self, endpoint: str, fn):
        """Per-endpoint server-side RPC accounting. Distinct metric names
        from the client's ``fabric/rpc_ms{endpoint=}`` so federation never
        merges client round-trip and server handler time into one
        histogram. Failures re-raise unchanged (RouteServer's 400/500
        mapping is the protocol) after counting + an event."""
        def handler(doc: Dict) -> Dict:
            t0 = time.perf_counter()
            try:
                out = fn(doc)
            except Exception as e:
                if self._tracer.enabled:
                    self._tracer.registry.counter(
                        "fabric/rpc_server_failures", endpoint=endpoint).add(1)
                from deepspeed_tpu.telemetry.events import emit_event

                emit_event(
                    "fabric", "rpc_server_failure",
                    f"replica daemon RPC {endpoint} raised "
                    f"{type(e).__name__}: {e}",
                    severity="warn", labels={"endpoint": endpoint},
                    dedup_key=f"fabric:rpc_server_failure:{endpoint}")
                raise
            if self._tracer.enabled:
                self._tracer.registry.histogram(
                    "fabric/rpc_server_ms", endpoint=endpoint).observe(
                    (time.perf_counter() - t0) * 1e3)
            return out
        return handler

    # ------------------------------------------------------------------ GET
    def _get_healthz(self) -> Tuple[bytes, str]:
        ident = fleet.get_identity()
        # deliberately LOCK-FREE: a jit compile inside /prefill can hold the
        # daemon lock for tens of seconds, and a heartbeat blocked behind it
        # would read as 4+ consecutive misses — a spurious death verdict on a
        # healthy replica. len() of a dict is GIL-atomic and XLA releases
        # the GIL while compiling, so the read here is always safe and fast.
        active = len(self.engine.state._seqs)
        body = {
            "ok": True,
            "identity": {"run_id": ident.run_id,
                         "process_index": ident.process_index,
                         "host": ident.host, "role": ident.role,
                         "pid": ident.pid},
            "draining": self.draining,
            "queue_depth": active,
            # the daemon serves whatever the router dispatches; SLO goodput
            # is tracked router-side per replica, so the heartbeat reports
            # capacity pressure (pool occupancy), not SLO attainment
            "goodput": 1.0,
            "time_unix": time.time(),
        }
        return json.dumps(body).encode(), "application/json"

    def _get_spec(self) -> Tuple[bytes, str]:
        eng = self.engine
        body = {
            "config": self._config_doc,
            "num_kv_blocks": int(eng.num_kv_blocks),
            "max_seq_len": int(eng.max_seq_len),
            "kv_dtype": str(eng.pool.k.dtype),
            "quant": eng.pool.quant,
            "prefix_cache": eng.prefix_cache is not None,
        }
        return json.dumps(body).encode(), "application/json"

    def _get_stats(self) -> Tuple[bytes, str]:
        eng = self.engine
        body = {
            "requests": self._requests,
            "preempts": self._preempts,
            "migrations_in": self._migrations_in,
            "migrations_out": self._migrations_out,
            "tokens_decoded": int(getattr(eng, "tokens_decoded", 0)),
            "dispatch_count": int(getattr(eng, "dispatch_count", 0)),
            "prefill_tokens_total": int(getattr(eng, "prefill_tokens_total", 0)),
            "prefill_tokens_cached": int(getattr(eng, "prefill_tokens_cached", 0)),
            "prefix_hit_rate": float(getattr(eng.prefix_cache, "hit_rate", 0.0))
            if eng.prefix_cache is not None else 0.0,
        }
        return json.dumps(body).encode(), "application/json"

    # ----------------------------------------------------------- dispatches
    def _span_stack(self, ctxs: Optional[Sequence], stack: contextlib.ExitStack,
                    **args: Any) -> None:
        """Open one ``fleet.dispatch_span`` per request context in the batch:
        each flow STEP lands inside this process's dispatch slice, binding
        the router-side admission arrows into this pid in the merged trace."""
        for wire_ctx in ctxs or ():
            if wire_ctx:
                ctx = fleet.TraceContext.from_wire(wire_ctx)
                stack.enter_context(
                    fleet.dispatch_span(ctx, tracer=self._tracer, **args))

    def _post_admit(self, doc: Dict) -> Dict:
        self._requests += 1
        self._count("fabric/rpcs")
        if self.draining:
            return {"ok": True, "suffix": None, "draining": True}
        with self._lock:
            suffix = self.engine.try_admit(
                int(doc["uid"]), np.asarray(doc["cand"], np.int32),
                [int(u) for u in doc.get("other_uids", ())],
                [int(c) for c in doc.get("other_counts", ())])
        return {"ok": True, "draining": False,
                "suffix": None if suffix is None else [int(t) for t in suffix]}

    def _post_prefill(self, doc: Dict) -> Dict:
        self._requests += 1
        self._count("fabric/rpcs")
        uids = [int(u) for u in doc["uids"]]
        token_lists = [np.asarray(t, np.int32) for t in doc["token_lists"]]
        rng = key_from_wire(doc["rng"])
        with self._lock, contextlib.ExitStack() as stack:
            self._span_stack(doc.get("ctxs"), stack, kind="prefill",
                             rows=len(uids))
            toks, rng = self.engine._put_sample(
                uids, token_lists, rng, _sample_kw(doc.get("sample_kw")))
        return {"ok": True, "toks": [int(t) for t in toks],
                "rng": key_to_wire(rng)}

    def _post_chain_round(self, doc: Dict) -> Dict:
        self._requests += 1
        self._count("fabric/rpcs")
        uids = [int(u) for u in doc["uids"]]
        last = [int(t) for t in doc["last_tokens"]]
        budgets = [int(b) for b in doc["budgets"]]
        k = int(doc["k"])
        rng = key_from_wire(doc["rng"])
        eos = doc.get("eos_id")
        eos = None if eos is None else int(eos)
        with self._lock, contextlib.ExitStack() as stack:
            self._span_stack(doc.get("ctxs"), stack, kind="chain",
                             rows=len(uids), k=k)
            if doc.get("spec"):
                hist = [np.asarray(h, np.int32) for h in doc["histories"]]
                out, emitted, rng = self.engine.decode_spec_chain(
                    uids, last, budgets, k, rng, hist, eos_id=eos)
            else:
                out, emitted, rng = self.engine.decode_chain(
                    uids, last, budgets, k, rng, eos_id=eos,
                    sample_kw=_sample_kw(doc.get("sample_kw")))
        return {"ok": True, "out": np.asarray(out).tolist(),
                "emitted": np.asarray(emitted).tolist(),
                "rng": key_to_wire(rng)}

    # ----------------------------------------------------------- scheduling
    def _post_can_schedule(self, doc: Dict) -> Dict:
        with self._lock:
            ok = self.engine._can_schedule_evicting(
                [int(u) for u in doc["uids"]],
                [int(c) for c in doc["counts"]])
        return {"ok": bool(ok)}

    def _post_query(self, doc: Dict) -> Dict:
        with self._lock:
            seen, free = self.engine.query(int(doc["uid"]))
        return {"ok": True, "seen": int(seen), "free": int(free)}

    def _post_flush(self, doc: Dict) -> Dict:
        with self._lock:
            self.engine.flush(int(doc["uid"]))
        return {"ok": True}

    def _post_preempt(self, doc: Dict) -> Dict:
        """Preemption = flush; the router re-queues and re-admits (the KV
        pages are rebuilt by re-prefill, exactly the in-process semantics)."""
        self._preempts += 1
        self._count("fabric/preempts")
        with self._lock:
            self.engine.flush(int(doc["uid"]))
        return {"ok": True}

    def _post_insert_prefix(self, doc: Dict) -> Dict:
        with self._lock:
            self.engine._insert_prefix(
                int(doc["uid"]), np.asarray(doc["tokens"], np.int32))
        return {"ok": True}

    # ------------------------------------------------------------ migration
    def _post_export_request(self, doc: Dict) -> Dict:
        self._migrations_out += 1
        self._count("fabric/rpcs")
        with self._lock:
            export = self.engine.export_request(int(doc["uid"]))
        wire = export_to_wire(export)
        self._count("fabric/wire_bytes",
                    sum(len(w["data"]) for w in wire["buffer"].values()
                        if w is not None))
        return dict(wire, ok=True)

    def _post_import_request(self, doc: Dict) -> Dict:
        # a layout mismatch raises ValueError -> RouteServer answers 400
        # -> RemoteReplica re-raises ValueError, the in-process contract
        self._migrations_in += 1
        self._count("fabric/rpcs")
        export = export_from_wire(doc["export"])
        with self._lock, contextlib.ExitStack() as stack:
            wire_ctx = doc.get("ctx")
            if wire_ctx:
                stack.enter_context(fleet.dispatch_span(
                    fleet.TraceContext.from_wire(wire_ctx),
                    name="serve:migrate", tracer=self._tracer,
                    blocks=int(export["n_blocks"])))
            ok = self.engine.import_request(int(doc["uid"]), export)
        return {"ok": bool(ok)}

    def _post_can_import(self, doc: Dict) -> Dict:
        with self._lock:
            ok = self.engine.can_import(int(doc["n_blocks"]))
        return {"ok": bool(ok)}

    def _post_block_hashes(self, doc: Dict) -> Dict:
        """Per-block blake2b digests of a live request's pool bytes — the
        fabric's migration-fidelity witness (compared across processes)."""
        with self._lock:
            seq = self.engine.state.get(int(doc["uid"]))
            if seq is None:
                raise ValueError(f"unknown uid {doc['uid']}")
            hashes = [self.engine._block_content_hash(int(b))
                      for b in seq.blocks]
        return {"ok": True, "hashes": hashes}

    # -------------------------------------------------------------- control
    def _post_drain(self, doc: Dict) -> Dict:
        """Quiesce: refuse new admissions. In-flight requests keep serving;
        the router's drain path exports their KV and hands them to a peer
        through the ordinary migration-ticket machinery."""
        self.draining = True
        self._count("fabric/drains")
        with self._lock:
            active = [int(u) for u in self.engine.state._seqs]
        return {"ok": True, "draining": True, "active_uids": active}

    def _post_dump_trace(self, doc: Dict) -> Dict:
        from deepspeed_tpu.telemetry.exporters import export_jsonl

        path = export_jsonl(str(doc["path"]), tracer=self._tracer)
        return {"ok": True, "path": path}

    def _post_shutdown(self, doc: Dict) -> Dict:
        self._shutdown.set()
        return {"ok": True}


def _build_model(name: str = "tiny"):
    """Deterministic test model shared by every fabric process: flax init
    from PRNGKey(0) is bit-identical across processes, so daemons and the
    parent's reference engine agree on params BY CONSTRUCTION — no weight
    shipping on the wire (real deployments load a checkpoint instead)."""
    import jax

    from deepspeed_tpu.models import CausalLM, TransformerConfig

    if name == "tiny":
        cfg = TransformerConfig(
            vocab_size=512, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=256)
    else:
        raise ValueError(f"unknown fabric model {name!r}")
    module = CausalLM(cfg)
    params = module.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(0)},
        {"input_ids": np.zeros((1, 8), np.int32)}, train=False)["params"]
    return cfg, params


def main(argv: Optional[List[str]] = None) -> int:
    """Subprocess entrypoint: build the deterministic model + engine, serve,
    print ``{"port": N}`` on stdout, block until ``/shutdown`` (or until the
    parent dies), export the trace stream, exit 0."""
    import argparse
    import os
    import sys

    p = argparse.ArgumentParser(description="serving-fabric replica daemon")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--index", type=int, default=0)
    p.add_argument("--run-id", default=None)
    p.add_argument("--model", default="tiny")
    p.add_argument("--engine-config", default="{}",
                   help="RaggedInferenceConfig fields as JSON")
    p.add_argument("--out", default=None,
                   help="directory for the trace JSONL export on shutdown")
    args = p.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    fleet.configure_identity(run_id=args.run_id, process_index=args.index,
                             role="replica")
    tracer = get_tracer()
    tracer.configure(enabled=True)

    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2

    eng_cfg = json.loads(args.engine_config)
    cfg, params = _build_model(args.model)
    engine = InferenceEngineV2(cfg, params, eng_cfg)
    # no config_doc override: /spec advertises the engine's FULL validated
    # config (model_dump), not just the fields the caller set — the remote
    # proxy's RaggedInferenceConfig then matches the daemon's exactly
    daemon = ReplicaDaemon(engine, host=args.host, port=args.port).start()
    print(json.dumps({"port": daemon.server.port, "pid": os.getpid()}),
          flush=True)
    # serve until asked to stop; bail out if the parent process died (ppid
    # reparented to init) so orphaned daemons never outlive a crashed smoke
    while not daemon.wait_shutdown(timeout=0.5):
        if os.getppid() == 1:
            break
    if args.out:
        from deepspeed_tpu.telemetry.exporters import export_jsonl

        os.makedirs(args.out, exist_ok=True)
        export_jsonl(os.path.join(args.out, f"events.p{args.index}.jsonl"),
                     tracer=tracer)
    daemon.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
