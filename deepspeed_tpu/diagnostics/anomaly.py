"""Step-time anomaly detection: rolling median + MAD over step wall times.

T3-style transparent runtime tracking (arXiv:2401.16677) argues the runtime
itself should notice when steps slow down, not a human reading dashboards
hours later. Two detectors over one rolling window:

  - **straggler**: a single step beyond ``median + k * MAD`` (MAD is robust —
    one slow step cannot inflate its own threshold the way a stddev would);
  - **regression**: the median of the most recent quarter of the window drifts
    past ``regression_factor`` x the window median — a sustained slowdown
    (thermal throttling, a neighbor job, a recompile storm), not a blip.

Results land as registry gauges (``anomaly/...``) so they ride the existing
telemetry export/monitor paths, plus tracer instants for the Perfetto view.
All host-side floats — never touches the device.
"""

from __future__ import annotations

import collections
import statistics
from typing import Dict, Optional

from deepspeed_tpu.telemetry.events import emit_event
from deepspeed_tpu.utils.logging import logger


class StepTimeAnomalyDetector:
    def __init__(
        self,
        window: int = 64,
        straggler_mads: float = 6.0,
        regression_factor: float = 1.3,
        min_samples: int = 8,
        name: str = "step",
        tracer=None,
    ):
        self.window = int(window)
        self.straggler_mads = float(straggler_mads)
        self.regression_factor = float(regression_factor)
        self.min_samples = max(int(min_samples), 4)
        self.name = name
        self._durs: collections.deque = collections.deque(maxlen=self.window)
        self.stragglers = 0
        self._regressing = False
        if tracer is None:
            from deepspeed_tpu.telemetry import get_tracer

            tracer = get_tracer()
        self._tracer = tracer

    def observe(self, dur_s: float, step: Optional[int] = None) -> Dict[str, float]:
        """Record one step duration; returns this step's anomaly flags."""
        flags = {"straggler": False, "regression": False}
        prior = list(self._durs)
        self._durs.append(float(dur_s))
        if len(prior) < self.min_samples:
            return flags
        med = statistics.median(prior)
        mad = statistics.median(abs(x - med) for x in prior)
        # MAD floor: identical timings give MAD 0 and any jitter would flag
        mad = max(mad, 0.01 * med, 1e-6)
        if dur_s > med + self.straggler_mads * mad:
            flags["straggler"] = True
            self.stragglers += 1
            msg = (f"[anomaly/{self.name}] straggler step"
                   + (f" {step}" if step is not None else "")
                   + f": {dur_s * 1e3:.1f} ms vs median {med * 1e3:.1f} ms "
                   f"(MAD {mad * 1e3:.2f} ms)")
            logger.warning(msg)
            self._tracer.instant(f"straggler:{self.name}", cat="diagnostics",
                                 dur_ms=round(dur_s * 1e3, 3),
                                 median_ms=round(med * 1e3, 3))
            emit_event("anomaly", "straggler", msg, severity="warn",
                       labels={"name": self.name}, step=step,
                       dedup_key=f"anomaly:straggler:{self.name}")
        recent_n = max(len(self._durs) // 4, self.min_samples // 2)
        recent = list(self._durs)[-recent_n:]
        recent_med = statistics.median(recent)
        regressing = recent_med > self.regression_factor * med
        flags["regression"] = regressing
        if regressing and not self._regressing:
            msg = (f"[anomaly/{self.name}] sustained step-time regression: "
                   f"recent median {recent_med * 1e3:.1f} ms vs window median "
                   f"{med * 1e3:.1f} ms (> {self.regression_factor:.2f}x)")
            logger.warning(msg)
            self._tracer.instant(f"regression:{self.name}", cat="diagnostics",
                                 recent_ms=round(recent_med * 1e3, 3),
                                 median_ms=round(med * 1e3, 3))
            emit_event("anomaly", "regression", msg, severity="warn",
                       labels={"name": self.name}, step=step)
        self._regressing = regressing

        reg = self._tracer.registry
        reg.gauge(f"anomaly/{self.name}_median_ms").set(med * 1e3)
        reg.gauge(f"anomaly/{self.name}_mad_ms").set(mad * 1e3)
        reg.gauge(f"anomaly/{self.name}_straggler").set(float(flags["straggler"]))
        reg.gauge(f"anomaly/{self.name}_regression").set(float(regressing))
        return flags
