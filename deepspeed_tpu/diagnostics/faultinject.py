"""Deterministic fault injection: the tool that proves recovery works.

The resilience stack (async snapshots in ``checkpoint/snapshot.py``, the
rewind supervisor in ``elasticity/resilience.py``) is only as real as the
faults it has survived. This module injects the three failure classes the
stack claims to handle, each deterministically (a given seed/step always
produces the same fault — flaky fault tests are worse than none):

  - **NaN gradients at step K** — a NaN planted in the batch poisons the
    whole backward (the same propagation path a bad data shard takes in
    production; the idiom the diagnostics test suite established). The
    in-step health probes then fire ``nonfinite`` with whatever policy is
    configured.
  - **writer killed mid-save** — the snapshot writer thread raises between
    two shard writes (or before the manifest / the commit rename), leaving a
    ``*.tmp-*`` directory and an untouched ``latest`` pointer: the
    crash-mid-save atomicity claim, made testable.
  - **shard truncated on disk** — post-commit corruption (bit rot, a
    truncated copy): the manifest checksum must catch it BEFORE any device
    state is touched and the loader must fall back to the previous tag.

Used by ``tests/unit/checkpoint/test_snapshot.py``,
``tests/unit/aux/test_resilience.py`` and the nightly smoke stage
(``tools/fault_smoke.py``).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Iterable, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger


class InjectedWriterCrash(RuntimeError):
    """Raised inside the snapshot writer thread by :meth:`FaultInjector.kill_writer`."""


def poison_batch(batch: Any, value: float = float("nan")) -> Any:
    """Copy of ``batch`` with ``value`` planted in the first element of every
    float leaf — one poisoned element is enough to NaN the whole backward."""
    out = {}
    poisoned = False
    for k, v in batch.items():
        arr = np.array(v, copy=True)
        if np.issubdtype(arr.dtype, np.floating) and arr.size:
            arr.flat[0] = value
            poisoned = True
        out[k] = arr
    if not poisoned:
        raise ValueError(
            "poison_batch: no float leaf to poison (integer-only batches "
            "need a model-level injection point)")
    return out


class FaultInjector:
    """One injector instance per experiment; every injection is logged and
    counted so a test can assert the fault actually fired."""

    def __init__(self):
        self.nan_steps_fired: list = []
        self.writer_kills_fired: int = 0
        self.daemon_kills_fired: int = 0

    # ------------------------------------------------------------- NaN grads
    def nan_batch_fn(
        self,
        batch_fn: Callable[[int], Any],
        at_steps: Iterable[int],
        repeat: bool = False,
    ) -> Callable[[int], Any]:
        """Wrap a deterministic ``batch_fn(step)`` so the batch for each step
        in ``at_steps`` comes back NaN-poisoned. ``repeat=False`` (default)
        injects each step's fault ONCE — a rewind that replays the step gets
        the clean batch, modeling a transient fault; ``repeat=True`` keeps
        poisoning on every replay, modeling a deterministic fault (the
        give-up path)."""
        pending = set(int(s) for s in at_steps)
        always = frozenset(pending) if repeat else None

        def wrapped(step: int) -> Any:
            fire = (step in always) if repeat else (step in pending)
            if not fire:
                return batch_fn(step)
            if not repeat:
                pending.discard(step)
            self.nan_steps_fired.append(step)
            logger.warning(f"faultinject: NaN planted in the batch for step {step}")
            return poison_batch(batch_fn(step))

        return wrapped

    def poison_engine_params(self, engine, value: float = float("nan")) -> int:
        """Plant ``value`` in the first element of EVERY float param leaf ON
        DEVICE — the model-level injection point for integer-batch models (a
        causal LM's ``input_ids`` carries no float to poison). Every-leaf
        coverage is deliberate: a single poisoned element can sit outside the
        compute path (an embedding row no token id gathers propagates NOTHING
        — its grad is a zero scatter, not NaN), but a NaN in every dense
        kernel/norm reaches the loss on any input. A snapshot restore
        replaces params wholesale, so the fault is transient across a rewind
        by construction. Returns the number of leaves poisoned."""
        import jax

        from deepspeed_tpu.utils.compat import device_put_unaliased

        leaves, treedef = jax.tree_util.tree_flatten_with_path(engine.state.params)
        new_leaves, n = [], 0
        for _path, leaf in leaves:
            arr = np.asarray(jax.device_get(leaf))
            if np.issubdtype(arr.dtype, np.floating) and arr.size:
                arr = np.array(arr, copy=True)
                arr.flat[0] = value
                leaf = device_put_unaliased(arr, leaf.sharding)
                n += 1
            new_leaves.append(leaf)
        if not n:
            raise ValueError("poison_engine_params: no float param leaf to poison")
        engine.state = engine.state._replace(
            params=jax.tree_util.tree_unflatten(treedef, new_leaves))
        logger.warning(f"faultinject: NaN planted in {n} param leaves")
        return n

    def flip_param_bit(self, engine, replica_index: int = -1, bit: int = 20,
                       element: int = 0) -> str:
        """Flip ONE mantissa bit of ONE element on ONE replica's copy of the
        first replicated float param leaf — the single-replica silent-
        corruption fault (an SDC/cosmic-ray flip, or a diverged lossy
        collective) the numerics divergence sentinel exists to catch.

        Unlike :meth:`poison_engine_params` (which poisons every replica
        identically and is therefore INVISIBLE to a cross-replica digest),
        this edits exactly one addressable shard's buffer, so replicas
        physically disagree afterwards. Deterministic: the same
        (replica_index, bit, element) always flips the same bit. Returns
        the path-string of the leaf flipped."""
        import jax

        leaves = jax.tree_util.tree_flatten_with_path(engine.state.params)[0]
        for path, leaf in leaves:
            arr_dtype = np.asarray(jax.device_get(
                leaf.addressable_shards[0].data)).dtype if leaf.addressable_shards else None
            if arr_dtype is None or not np.issubdtype(arr_dtype, np.floating):
                continue
            shards = [np.array(np.asarray(s.data), copy=True)
                      for s in leaf.addressable_shards]
            # only a leaf with >1 replica copy can disagree: find two shards
            # holding identical data (a fully-sharded leaf has none)
            if len(shards) < 2 or not any(
                    np.array_equal(shards[0], s) for s in shards[1:]):
                continue
            target = shards[replica_index % len(shards)]
            if target.size <= element or target.dtype != np.float32:
                # the master params are fp32; a sub-fp32 leaf would round
                # the flip away on the astype round trip — skip it
                continue
            flat = np.ascontiguousarray(target)
            flat.view(np.uint32).flat[element] ^= np.uint32(1 << bit)
            shards[replica_index % len(shards)] = flat
            bufs = [jax.device_put(s, sh.device)
                    for s, sh in zip(shards, leaf.addressable_shards)]
            new_leaf = jax.make_array_from_single_device_arrays(
                leaf.shape, leaf.sharding, bufs)
            key = jax.tree_util.keystr(path)
            params = jax.tree_util.tree_map_with_path(
                lambda p, l: new_leaf if p == path else l,
                engine.state.params)
            engine.state = engine.state._replace(params=params)
            logger.warning(
                f"faultinject: flipped bit {bit} of element {element} on "
                f"replica shard {replica_index % len(shards)} of param "
                f"{key} — replicas now physically disagree")
            return key
        raise ValueError(
            "flip_param_bit: no replicated float param leaf to corrupt "
            "(every leaf is fully sharded or non-float)")

    def nan_params_fn(
        self,
        engine,
        batch_fn: Callable[[int], Any],
        at_steps: Iterable[int],
    ) -> Callable[[int], Any]:
        """Wrap a deterministic ``batch_fn(step)`` so the ENGINE PARAMS are
        NaN-poisoned just before each step in ``at_steps`` — the injection
        path for models whose batches carry no float leaf. Each step fires
        once; the rewind's restore replaces the poisoned params, so replays
        run clean (transient-fault semantics, like ``nan_batch_fn``'s
        default)."""
        pending = set(int(s) for s in at_steps)

        def wrapped(step: int) -> Any:
            if step in pending:
                pending.discard(step)
                self.nan_steps_fired.append(step)
                self.poison_engine_params(engine)
            return batch_fn(step)

        return wrapped

    # ------------------------------------------------------- writer crashes
    def kill_writer(self, manager, after_shards: int = 1, times: int = 1,
                    at: str = "shard") -> None:
        """Arm ``manager`` (a SnapshotManager) so its writer thread crashes
        mid-save: at the ``after_shards``-th shard write (``at='shard'``),
        before the manifest (``at='manifest'``) or just before the commit
        rename (``at='commit'``). Fires ``times`` saves, then disarms —
        subsequent snapshots succeed (transient disk fault semantics)."""
        if at not in ("shard", "manifest", "commit"):
            raise ValueError(f"kill_writer at={at!r}: shard|manifest|commit")
        state = {"remaining": int(times)}

        def hook(event: str, index: int) -> None:
            if state["remaining"] <= 0:
                return
            if event == at and (event != "shard" or index >= after_shards):
                state["remaining"] -= 1
                self.writer_kills_fired += 1
                logger.warning(
                    f"faultinject: killing snapshot writer at {event}[{index}]")
                raise InjectedWriterCrash(
                    f"injected writer crash at {event}[{index}]")

        manager.fault_hook = hook

    # ----------------------------------------------------- fabric / process
    def kill_replica_daemon(self, proc_or_pid) -> int:
        """SIGKILL a serving-fabric replica daemon (ISSUE 18): the hard-death
        case — no drain, no flush, the HTTP socket just goes away. The
        router must detect it via heartbeat/dispatch failure and re-admit
        the replica's admitted-but-unfinished requests elsewhere. Accepts a
        ``subprocess.Popen`` or a raw pid; returns the pid killed."""
        import signal

        pid = int(getattr(proc_or_pid, "pid", proc_or_pid))
        os.kill(pid, signal.SIGKILL)
        wait = getattr(proc_or_pid, "wait", None)
        if wait is not None:
            try:
                wait(timeout=10.0)  # reap so the test sees returncode set
            except Exception:
                pass
        self.daemon_kills_fired += 1
        logger.warning(f"faultinject: SIGKILLed replica daemon pid={pid}")
        return pid

    # --------------------------------------------------- on-disk corruption
    @staticmethod
    def truncate_shard(base_dir: str, tag: Optional[str] = None,
                       shard_index: int = 0, keep_bytes: int = 16) -> str:
        """Truncate one committed shard file to ``keep_bytes`` — the checksum
        in the manifest no longer matches. Returns the file truncated."""
        from deepspeed_tpu.checkpoint import snapshot as snap

        tag = tag or snap.latest_tag(base_dir)
        if tag is None:
            raise FileNotFoundError(f"no snapshots under {base_dir}")
        manifest = snap.read_manifest(base_dir, tag)
        shard = manifest["shards"][shard_index]
        path = os.path.join(snap.snapshot_root(base_dir), tag, shard["file"])
        with open(path, "r+b") as f:
            f.truncate(keep_bytes)
        logger.warning(f"faultinject: truncated {path} to {keep_bytes} bytes")
        return path

    @staticmethod
    def corrupt_manifest(base_dir: str, tag: Optional[str] = None) -> str:
        """Overwrite a committed manifest with junk (an interrupted rewrite /
        filesystem fault). Returns the path corrupted."""
        from deepspeed_tpu.checkpoint import snapshot as snap

        tag = tag or snap.latest_tag(base_dir)
        if tag is None:
            raise FileNotFoundError(f"no snapshots under {base_dir}")
        path = os.path.join(snap.snapshot_root(base_dir), tag, snap.MANIFEST_FILE)
        with open(path, "w") as f:
            f.write("{not json")
        logger.warning(f"faultinject: corrupted {path}")
        return path

    # ------------------------------------------------------------- reporting
    def summary(self) -> Dict[str, Any]:
        return {
            "nan_steps_fired": list(self.nan_steps_fired),
            "writer_kills_fired": self.writer_kills_fired,
            "daemon_kills_fired": self.daemon_kills_fired,
        }
