"""Crash flight recorder: a bounded ring of recent step records.

Black-box recorder semantics: every step appends its (device-resident) metric
snapshot + health verdicts to a ring of the last N steps — an append and
nothing else, so recording costs no device fetch and no sync. Only ``dump()``
pays: ONE bulk ``jax.device_get`` over the ring, then JSONL to disk plus (when
the tracer is enabled) a Perfetto trace next to it, so a dead run always
leaves a post-mortem:

  - unhandled exception (``sys.excepthook`` chain)
  - SIGTERM (preemption — dump, then chain to the prior handler) and SIGUSR1
    (inspect a live run without stopping it)
  - an explicit ``engine.diagnostics.dump()``

Process-wide hooks are installed ONCE and dispatch to every live recorder
through a WeakSet — engines come and go (tests build dozens) without handler
stacking or teardown ordering hazards.

Serving mode (``request_capacity > 0``): the recorder additionally keeps a
ring of per-request records — request id, phase, lifecycle stamps, chain
count — updated by the v2 engine's ``LifecycleTracker`` at every request
transition. A crashed serving run's dump then NAMES the in-flight requests
(which uid was decoding, which were queued, how far each had gotten), the
serving analog of the step ring.

Dump schema (JSONL, one object per line):
  {"kind": "header", "reason", "time_unix", "pid", "context", "n_records"}
  {"kind": "step_record", "step", "t_unix", "metrics": {...}, "health": {...}}
  {"kind": "request_record", "rid", "uid", "phase", "tokens", "chains", ...}
  {"kind": "span" | "instant" | "counter", ...}   # recent tracer events
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

_RECORDERS: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()
_HOOKS_LOCK = threading.Lock()
_HOOKS_INSTALLED = False
_PREV_EXCEPTHOOK = None
_PREV_SIGNAL_HANDLERS: Dict[int, Any] = {}


def _to_plain(x: Any) -> Any:
    """Host python value for one fetched metric leaf (JSON-serializable)."""
    import numpy as np

    arr = np.asarray(x)
    if arr.size == 1:
        v = arr.reshape(()).item()
        if isinstance(v, float) and not np.isfinite(v):
            return repr(v)  # JSON has no NaN/Inf; keep the information
        return v
    return arr.tolist()


def dump_all(reason: str) -> List[str]:
    """Dump every live recorder; never raises (post-mortem best effort)."""
    paths = []
    for rec in list(_RECORDERS):
        try:
            paths.append(rec.dump(reason=reason))
        except Exception as e:  # noqa: BLE001 - must not mask the real crash
            logger.warning(f"flight recorder dump failed: {type(e).__name__}: {e}")
    return paths


def _excepthook(exc_type, exc, tb):
    if not issubclass(exc_type, (KeyboardInterrupt, SystemExit)):
        dump_all(reason=f"exception:{exc_type.__name__}")
    (_PREV_EXCEPTHOOK or sys.__excepthook__)(exc_type, exc, tb)


def _signal_handler(signum, frame):
    name = signal.Signals(signum).name
    dump_all(reason=f"signal:{name}")
    prev = _PREV_SIGNAL_HANDLERS.get(signum)
    if callable(prev):
        prev(signum, frame)
    elif signum != signal.SIGUSR1:
        # restore + re-raise so default termination semantics survive the dump
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)


def install_process_hooks(signals: bool = True, excepthook: bool = True) -> None:
    """Install the dump-on-death hooks once per process (idempotent)."""
    global _HOOKS_INSTALLED, _PREV_EXCEPTHOOK
    with _HOOKS_LOCK:
        if _HOOKS_INSTALLED:
            return
        _HOOKS_INSTALLED = True
        if excepthook:
            _PREV_EXCEPTHOOK = sys.excepthook
            sys.excepthook = _excepthook
        if signals and threading.current_thread() is threading.main_thread():
            for sig in (signal.SIGTERM, signal.SIGUSR1):
                try:
                    _PREV_SIGNAL_HANDLERS[sig] = signal.signal(sig, _signal_handler)
                except (ValueError, OSError):  # non-main thread / exotic runtime
                    pass


class FlightRecorder:
    def __init__(
        self,
        capacity: int = 16,
        dump_dir: Optional[str] = None,
        tracer=None,
        max_trace_events: int = 512,
        request_capacity: int = 0,
    ):
        self.capacity = max(int(capacity), 1)
        self.dump_dir = dump_dir
        self.max_trace_events = max_trace_events
        # serving mode: bounded ring of per-request records (0 = off);
        # latest state per request id, LRU-evicted past capacity
        self.request_capacity = max(int(request_capacity), 0)
        self._requests: "OrderedDict[Any, Dict[str, Any]]" = OrderedDict()
        self._ring: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._context: Dict[str, Any] = {}
        if tracer is None:
            from deepspeed_tpu.telemetry import get_tracer

            tracer = get_tracer()
        self._tracer = tracer
        _RECORDERS.add(self)

    def set_context(self, **kwargs: Any) -> None:
        """Static run facts for the dump header (mesh, stages, dtype, ...)."""
        self._context.update(kwargs)

    def record(self, step: int, metrics: Dict[str, Any], **extra: Any) -> None:
        """Append one step record. Metric values may be device arrays — they
        are fetched only at dump time, so this never blocks dispatch."""
        rec = {"step": int(step), "t_unix": time.time(), "metrics": dict(metrics)}
        if extra:
            rec.update(extra)
        with self._lock:
            self._ring.append(rec)
            if len(self._ring) > self.capacity:
                del self._ring[: len(self._ring) - self.capacity]

    def record_request(self, rid: Any, **fields: Any) -> None:
        """Update (or create) the serving ring's record for request ``rid``
        — plain host values only, so recording never touches the device.
        No-op unless serving mode (``request_capacity > 0``) is on."""
        if self.request_capacity <= 0:
            return
        with self._lock:
            rec = self._requests.pop(rid, None)
            if rec is None:
                rec = {"rid": rid}
            rec.update(fields)
            self._requests[rid] = rec  # most-recently-updated last
            while len(self._requests) > self.request_capacity:
                self._requests.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # ------------------------------------------------------------------ dump
    def _resolve_path(self, path: Optional[str]) -> str:
        if path:
            return path
        from deepspeed_tpu.telemetry import default_output_dir
        from deepspeed_tpu.telemetry.fleet import get_identity

        # per-process default filename: N processes sharing a telemetry dir
        # must not overwrite each other's post-mortems (process 0 keeps the
        # historical name so single-process tooling is unchanged)
        idx = get_identity().process_index
        name = ("flight_record.jsonl" if idx == 0
                else f"flight_record.p{idx}.jsonl")
        return os.path.join(self.dump_dir or default_output_dir(), name)

    def dump(self, reason: str = "manual", path: Optional[str] = None) -> str:
        """Fetch the ring (one bulk transfer) and write the JSONL post-mortem.
        Returns the path written."""
        import jax

        with self._lock:
            ring = [dict(r) for r in self._ring]
            requests = [dict(r) for r in self._requests.values()]
        fetched = jax.device_get([r["metrics"] for r in ring])
        path = self._resolve_path(path)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            from deepspeed_tpu.telemetry.fleet import get_identity

            header = {
                "kind": "header",
                "reason": reason,
                "time_unix": time.time(),
                "pid": os.getpid(),
                # the fleet join key: run_id/process_index/host/role — two
                # replicas' dumps were indistinguishable without it
                "identity": get_identity().to_dict(),
                "context": self._context,
                "n_records": len(ring),
                "n_requests": len(requests),
            }
            f.write(json.dumps(header) + "\n")
            for rec, metrics in zip(ring, fetched):
                plain = {k: _to_plain(v) for k, v in metrics.items()}
                health = {k[len("health/"):]: v for k, v in plain.items()
                          if k.startswith("health/")}
                row = {
                    "kind": "step_record",
                    "step": rec["step"],
                    "t_unix": rec["t_unix"],
                    "metrics": {k: v for k, v in plain.items()
                                if not k.startswith("health/")},
                    "health": health,
                }
                for k, v in rec.items():
                    if k not in ("step", "t_unix", "metrics"):
                        row[k] = v
                f.write(json.dumps(row) + "\n")
            for rec in requests:  # serving mode: name the in-flight requests
                f.write(json.dumps({"kind": "request_record", **rec}) + "\n")
            for ev in self._tracer.events()[-self.max_trace_events:]:
                f.write(json.dumps({"pid": os.getpid(), **ev}) + "\n")
        if self._tracer.enabled:
            try:
                from deepspeed_tpu.telemetry import export_chrome_trace

                export_chrome_trace(
                    os.path.splitext(path)[0] + "_trace.json", tracer=self._tracer)
            except Exception as e:  # noqa: BLE001 - trace export is best-effort
                logger.warning(f"flight-recorder trace export failed: {e}")
        logger.warning(f"flight recorder: dumped {len(ring)} step records to "
                       f"{path} (reason: {reason})")
        return path
