"""Training-health probes that run INSIDE the compiled train step.

The reference treats overflow detection as a first-class runtime feature
(``zero/stage_1_and_2.py:2038 _has_inf_or_nan`` fused into the step;
``FP16_Optimizer.step``'s skip path). This module generalizes that machinery
to health *signals* beyond fp16 overflow, all traced into the one jitted
program so detection costs no extra device->host fetch:

  - **nonfinite**: per-leaf-group NaN/Inf element counts over the (unscaled)
    gradients. Catches bf16 NaN storms, which the fp16 loss-scaler machinery
    never sees (bf16 runs with ``all_finite`` compiled out).
  - **grad_spike**: z-score of the global grad norm against EMA mean/variance
    carried in the train state (``HealthState``).
  - **loss_spike**: same detector over the step loss (fused-step path only;
    the offload host program receives gradients, not losses).

Each signal has a policy: ``log`` (record only), ``skip_step`` (gate the
optimizer update off inside the jitted program — the fp16 overflow-skip
``jnp.where`` select, extended), or ``abort`` (skip AND raise host-side; the
per-step abort fetch is the one policy that synchronizes the dispatch
pipeline, a latency-for-certainty trade the config opts into).

Verdicts travel in the step ``metrics`` dict as device scalars under
``health/``; nothing here forces a transfer — the engine's existing periodic
fetch, the monitor flush, and the flight-recorder dump are the sync points.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

POLICIES = ("log", "skip_step", "abort")

# Signals detectable without history run even at count=0; EMA z-scores need
# warmup_steps healthy samples before they may fire.
SIGNALS = ("nonfinite", "grad_spike", "loss_spike")


class HealthState(NamedTuple):
    """EMA state carried in ``TrainState.health`` (device scalars)."""

    count: jax.Array  # i32: healthy steps absorbed into the EMAs
    gnorm_ema: jax.Array  # f32 EMA of the global grad norm
    gnorm_sq_ema: jax.Array  # f32 EMA of its square (for variance)
    loss_ema: jax.Array
    loss_sq_ema: jax.Array


def _group_key(path) -> str:
    """Top-level tree key for a leaf path ('' for scalar/leaf-only trees)."""
    if not path:
        return "params"
    entry = path[0]
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry).strip("[].'\"")


def group_nonfinite_counts(tree: Any) -> Dict[str, jax.Array]:
    """Per-top-level-group count of nonfinite elements (i32 device scalars).

    Grouping by the first path element matches how model params are organized
    (flax module name / layer dict key), so a NaN storm names the subtree it
    started in rather than just "somewhere".
    """
    counts: Dict[str, jax.Array] = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            continue
        key = _group_key(path)
        c = jnp.sum(~jnp.isfinite(leaf)).astype(jnp.int32)
        counts[key] = counts[key] + c if key in counts else c
    return counts


class HealthMonitor:
    """Builds the in-jit probes and holds the (static) per-signal policies."""

    def __init__(self, config, fp16: bool = False):
        self.config = config
        self.fp16 = fp16
        self.policies = {
            "nonfinite": config.nonfinite_policy,
            "grad_spike": config.grad_spike_policy,
            "loss_spike": config.loss_spike_policy,
        }
        for sig, pol in self.policies.items():
            if pol not in POLICIES:
                raise ValueError(
                    f"diagnostics.health.{sig}_policy={pol!r}: must be one of {POLICIES}")
        self.skip_signals = tuple(
            s for s, p in self.policies.items() if p in ("skip_step", "abort"))
        self.abort_signals = tuple(
            s for s, p in self.policies.items() if p == "abort")

    # ------------------------------------------------------------------ state
    def init_state(self) -> HealthState:
        # distinct arrays per field: shared zeros would alias buffers and trip
        # the fused step's donation ("same buffer donated twice")
        return HealthState(
            count=jnp.zeros((), jnp.int32),
            gnorm_ema=jnp.zeros((), jnp.float32),
            gnorm_sq_ema=jnp.zeros((), jnp.float32),
            loss_ema=jnp.zeros((), jnp.float32),
            loss_sq_ema=jnp.zeros((), jnp.float32),
        )

    # ------------------------------------------------------------------ probe
    def _zscore(self, x, ema, sq_ema, count):
        warm = count >= self.config.warmup_steps
        var = jnp.maximum(sq_ema - jnp.square(ema), 0.0)
        z = (x - ema) * jax.lax.rsqrt(var + 1e-12)
        # NaN x compares False against any threshold, so a nonfinite value
        # never double-fires as a "spike"; warmup gates the cold-start noise.
        return jnp.where(warm, z, 0.0)

    def _ema_step(self, ema, x, count):
        beta = jnp.float32(self.config.ema_beta)
        # first healthy sample seeds the EMA exactly (no zero-bias ramp)
        return jnp.where(count == 0, x, beta * ema + (1.0 - beta) * x)

    def probe(
        self,
        hstate: HealthState,
        grads: Any,
        gnorm: jax.Array,
        loss: Optional[jax.Array] = None,
        finite: Optional[jax.Array] = None,
    ) -> Tuple[HealthState, Dict[str, jax.Array], jax.Array, jax.Array]:
        """All health signals for one step — traced into the caller's program.

        Returns ``(new_hstate, metrics, skip, abort)`` where ``metrics`` holds
        the device-scalar verdicts (``health/...``), ``skip`` gates the
        optimizer update (signals whose policy is skip_step/abort), and
        ``abort`` marks signals whose policy asks the host to raise.
        """
        cfg = self.config
        gnorm = gnorm.astype(jnp.float32)
        finite = jnp.asarray(True) if finite is None else finite

        group_counts = group_nonfinite_counts(grads)
        nonfinite_total = sum(group_counts.values()) if group_counts else jnp.zeros((), jnp.int32)
        nonfinite_any = nonfinite_total > 0

        gz = self._zscore(gnorm, hstate.gnorm_ema, hstate.gnorm_sq_ema, hstate.count)
        grad_spike = gz > cfg.grad_spike_zscore

        if loss is not None:
            loss = loss.astype(jnp.float32)
            lz = self._zscore(loss, hstate.loss_ema, hstate.loss_sq_ema, hstate.count)
            loss_spike = lz > cfg.loss_spike_zscore
        else:
            lz = jnp.zeros((), jnp.float32)
            loss_spike = jnp.asarray(False)

        signals = {
            "nonfinite": nonfinite_any,
            "grad_spike": grad_spike,
            "loss_spike": loss_spike,
        }
        false = jnp.asarray(False)
        skip = false
        for s in self.skip_signals:
            skip = skip | signals[s]
        abort = false
        for s in self.abort_signals:
            abort = abort | signals[s]

        # EMAs absorb only clean, finite steps: one poisoned step must not
        # shift the baseline the next steps are judged against.
        healthy = finite & ~nonfinite_any & ~grad_spike & ~loss_spike & jnp.isfinite(gnorm)
        absorb = lambda ema, x: jnp.where(  # noqa: E731
            healthy, self._ema_step(ema, x, hstate.count), ema)
        new_hstate = HealthState(
            count=hstate.count + jnp.where(healthy, 1, 0).astype(jnp.int32),
            gnorm_ema=absorb(hstate.gnorm_ema, gnorm),
            gnorm_sq_ema=absorb(hstate.gnorm_sq_ema, jnp.square(gnorm)),
            loss_ema=absorb(hstate.loss_ema, loss) if loss is not None else hstate.loss_ema,
            loss_sq_ema=(absorb(hstate.loss_sq_ema, jnp.square(loss))
                         if loss is not None else hstate.loss_sq_ema),
        )

        metrics: Dict[str, jax.Array] = {
            "health/nonfinite_total": nonfinite_total,
            "health/nonfinite_any": nonfinite_any,
            "health/grad_zscore": gz,
            "health/grad_spike": grad_spike,
            "health/loss_zscore": lz,
            "health/loss_spike": loss_spike,
            "health/skip": skip,
            "health/abort": abort,
        }
        for g, c in group_counts.items():
            metrics[f"health/nonfinite/{g}"] = c
        return new_hstate, metrics, skip, abort
