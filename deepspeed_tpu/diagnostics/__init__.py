"""deepspeed_tpu.diagnostics: the production half of observability.

PR 1's telemetry core records what happened (spans, metrics, traces); this
package WATCHES it and the training math itself:

  - ``health``          — in-jit training-health probes (per-leaf-group
    nonfinite counts, grad-norm / loss z-score spike detection) with
    per-signal ``log | skip_step | abort`` policies, folded into the engine's
    compiled step next to the existing overflow/grad-norm math
  - ``recompile``       — recompile detection on jitted callables (compile-
    cache growth + argument shape-diff attribution, storm escalation); also
    verifies the inference engines' "bucketing means no recompile" claim
  - ``anomaly``         — rolling median+MAD step-time straggler/regression
    detection over the step wall times the telemetry spans already measure
  - ``flight_recorder`` — bounded ring of recent step records dumped to
    JSONL (+ Perfetto trace) on unhandled exception, SIGTERM/SIGUSR1, or an
    explicit ``engine.diagnostics.dump()``
  - ``faultinject``     — deterministic fault injection (NaN grads at step K,
    snapshot writer killed mid-save, shard truncated on disk): the harness
    that proves the resilience stack (``checkpoint/snapshot.py`` +
    ``elasticity/resilience.py``) actually recovers

Enable via the ``diagnostics`` config block (see ``config/config.py``);
disabled (the default) the engine carries no health state, compiles the same
program as before, and every hook is one attribute check. See
``docs/diagnostics.md``.
"""

from deepspeed_tpu.diagnostics.anomaly import StepTimeAnomalyDetector
from deepspeed_tpu.diagnostics.faultinject import (
    FaultInjector,
    InjectedWriterCrash,
    poison_batch,
)
from deepspeed_tpu.diagnostics.flight_recorder import (
    FlightRecorder,
    dump_all,
    install_process_hooks,
)
from deepspeed_tpu.diagnostics.health import (
    HealthMonitor,
    HealthState,
    group_nonfinite_counts,
)
from deepspeed_tpu.diagnostics.manager import DiagnosticsManager, TrainingHealthError
from deepspeed_tpu.diagnostics.recompile import RecompileDetector, diff_signatures

__all__ = [
    "DiagnosticsManager",
    "FaultInjector",
    "FlightRecorder",
    "HealthMonitor",
    "HealthState",
    "InjectedWriterCrash",
    "RecompileDetector",
    "StepTimeAnomalyDetector",
    "TrainingHealthError",
    "diff_signatures",
    "dump_all",
    "group_nonfinite_counts",
    "install_process_hooks",
    "poison_batch",
]
