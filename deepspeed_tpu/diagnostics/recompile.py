"""Recompile detector for jitted callables.

XLA recompiles silently: a drifting input shape (an unpadded batch, a new
sequence bucket, a weak-typed scalar) turns a cached dispatch into a full
compile, and the only symptom is a mysteriously slow step. This detector
wraps a jitted callable and, per call, compares ``fn._cache_size()`` before
and after — growth IS a compile. On every compile past the first it warns
with the argument-level shape diff against the previous call (naming the
operand that changed), emits a telemetry instant + counter, and escalates to
a storm error when compiles cluster in time.

The per-call overhead is one ``_cache_size()`` call plus a shape walk of the
argument tree — nanoseconds against a training step or a generate request —
and the wrapper is only installed when diagnostics/recompile checking is
enabled. Attribute access (``.lower`` for AOT compilation, etc.) forwards to
the wrapped function, so profiler paths keep working.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.utils.logging import logger


def _leaf_sig(x: Any):
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None:
        # python scalars / static args: the value itself keys the jit cache
        return ("static", type(x).__name__, repr(x)[:64])
    return (tuple(shape), str(dtype))


def _tree_sig(args: Tuple, kwargs: Dict, arg_names: Optional[Sequence[str]]) -> Dict[str, Any]:
    import jax

    sig: Dict[str, Any] = {}
    for i, a in enumerate(args):
        name = arg_names[i] if arg_names and i < len(arg_names) else f"arg{i}"
        for path, leaf in jax.tree_util.tree_leaves_with_path(a):
            sig[name + jax.tree_util.keystr(path)] = _leaf_sig(leaf)
    for k, v in kwargs.items():
        for path, leaf in jax.tree_util.tree_leaves_with_path(v):
            sig[k + jax.tree_util.keystr(path)] = _leaf_sig(leaf)
    return sig


def diff_signatures(old: Dict[str, Any], new: Dict[str, Any]) -> List[str]:
    """Human-readable operand-level diff, changed arguments named first."""
    out = []
    for k in new:
        if k in old and old[k] != new[k]:
            out.append(f"{k}: {old[k]} -> {new[k]}")
    for k in new:
        if k not in old:
            out.append(f"{k}: (new) {new[k]}")
    for k in old:
        if k not in new:
            out.append(f"{k}: {old[k]} -> (gone)")
    return out


class _WrappedJit:
    """Callable proxy recording cache growth; forwards everything else.

    Cost discipline: on a cache hit the wrapper does exactly two
    ``_cache_size()`` probes (a C++ int read) — the argument-tree shape walk
    only runs when a compile actually happened, so wrapping the train step
    adds no per-leaf host work to steady-state dispatch. ``_last_sig`` is the
    signature captured at the previous compile; diffing against it names what
    drifted since the program that was running."""

    def __init__(self, fn: Callable, detector: "RecompileDetector", label: str):
        self._fn = fn
        self._detector = detector
        self._label = label
        self._last_sig: Optional[Dict[str, Any]] = None
        self._compiles_seen = 0
        # freshest ProgramRecord captured for THIS wrapper's program (the
        # flops profiler reads it instead of AOT-compiling a second copy)
        self._program_record = None

    def __call__(self, *args, **kwargs):
        det = self._detector
        # program capture shares this wrap point (telemetry/programs.py):
        # when the registry is live, the call is timed so a detected compile
        # carries its wall cost — one perf_counter read per call, nothing
        # when the registry is disabled
        programs = det.programs
        capture = programs is not None and programs.enabled
        t0 = time.perf_counter() if capture else 0.0
        before = self._cache_size()
        out = self._fn(*args, **kwargs)
        after = self._cache_size()
        if before is None or after is None:
            # no cache introspection (non-pjit callable, private-API drift):
            # "unknown" must read as no-information, never as a compile —
            # else every call would fire a spurious recompile warning
            return out
        if after > before:
            program = prev_program = None
            if capture:
                prev_program = programs.latest(self._label)
                program = programs.on_compile(
                    self._label, self._fn, args, kwargs,
                    wall_s=time.perf_counter() - t0,
                    hbm_scope=det.hbm_scope)
                if program is not None:
                    self._program_record = program
            sig = _tree_sig(args, kwargs, det.arg_names)
            det._on_compile(self._label, self._last_sig, sig,
                            first=(self._compiles_seen == 0), cache_size=after,
                            program=program, prev_program=prev_program)
            self._last_sig = sig
            self._compiles_seen += 1
        return out

    def _cache_size(self) -> Optional[int]:
        try:
            return self._fn._cache_size()
        except Exception:  # noqa: BLE001 - non-pjit callables (tests, shims)
            return None

    def __getattr__(self, name):
        return getattr(self._fn, name)


def unwrap_jit(fn: Callable) -> Callable:
    """The underlying jitted callable of a detector-wrapped fn (identity for
    anything else) — for AOT paths (``.lower``/``make_jaxpr``) that should
    not count their tracing as dispatch."""
    return fn._fn if isinstance(fn, _WrappedJit) else fn


class RecompileDetector:
    """Tracks compiles across one or more wrapped jitted callables.

    ``events`` keeps one record per compile (kind: initial/recompile/storm)
    so tests and tooling can assert on detector state without scraping logs.
    """

    def __init__(
        self,
        name: str,
        arg_names: Optional[Sequence[str]] = None,
        storm_threshold: int = 3,
        storm_window_s: float = 60.0,
        tracer=None,
        hbm_scope: Optional[str] = None,
    ):
        self.name = name
        self.arg_names = tuple(arg_names) if arg_names else None
        self.storm_threshold = max(int(storm_threshold), 2)
        self.storm_window_s = float(storm_window_s)
        self.compiles = 0
        self.recompiles = 0
        self.events: List[Dict[str, Any]] = []
        self._recent: collections.deque = collections.deque(maxlen=self.storm_threshold)
        self._storm_reported = False
        if tracer is None:
            from deepspeed_tpu.telemetry import get_tracer

            tracer = get_tracer()
        self._tracer = tracer
        # Compiled-program capture rides this wrap point; ``hbm_scope`` tags
        # captures for estimate-vs-actual calibration (see utils/hbm.py).
        from deepspeed_tpu.telemetry.programs import get_program_registry

        self.programs = get_program_registry()
        self.hbm_scope = hbm_scope

    def wrap(self, fn: Callable, label: Optional[str] = None) -> Callable:
        return _WrappedJit(fn, self, label or self.name)

    # ------------------------------------------------------------------ hooks
    def _on_compile(self, label: str, old_sig, new_sig, first: bool,
                    cache_size: Optional[int],
                    program=None, prev_program=None) -> None:
        now = time.monotonic()
        self.compiles += 1
        self._tracer.count(f"recompile/{self.name}")
        ev: Dict[str, Any] = {"label": label, "t": now, "cache_size": cache_size}
        if program is not None:
            ev["hlo"] = {"fingerprint": program.fingerprint,
                         "instructions": program.instruction_count}
        if first:
            # the initial compile of a program is expected, not a defect
            ev.update(kind="initial", diff=[])
            self.events.append(ev)
            logger.debug(f"[{self.name}] initial compile of {label}")
            return
        self.recompiles += 1
        diff = diff_signatures(old_sig or {}, new_sig or {})
        ev.update(kind="recompile", diff=diff)
        self.events.append(ev)
        detail = "; ".join(diff[:6]) if diff else (
            "no argument shape/dtype change — weak types, donation, or "
            "non-hashable static state are the usual suspects")
        if program is not None and program.fingerprint:
            # say what GREW, not just which argument drifted: the captured
            # HLO identity of the program that was running vs the new one
            if prev_program is not None and prev_program.fingerprint:
                delta = program.instruction_count - prev_program.instruction_count
                detail += (
                    f"; HLO {prev_program.fingerprint}"
                    f" ({prev_program.instruction_count} instr)"
                    f" -> {program.fingerprint}"
                    f" ({program.instruction_count} instr, {delta:+d})")
            else:
                detail += (f"; HLO {program.fingerprint}"
                           f" ({program.instruction_count} instr)")
        msg = (f"[{self.name}] RECOMPILE #{self.recompiles} of {label}"
               + (f" (jit cache size {cache_size})" if cache_size else "")
               + f": {detail}")
        logger.warning(msg)
        ev["message"] = msg
        self._tracer.instant(f"recompile:{self.name}", cat="diagnostics",
                             label=label, diff=diff[:6])
        from deepspeed_tpu.telemetry.events import emit_event

        emit_event("recompile", "recompile", msg, severity="warn",
                   labels={"detector": self.name, "program": label},
                   dedup_key=f"recompile:{self.name}:{label}")
        self._recent.append(now)
        if (len(self._recent) == self.storm_threshold
                and now - self._recent[0] <= self.storm_window_s):
            if not self._storm_reported:
                self._storm_reported = True
                storm = (f"[{self.name}] recompile STORM: {self.storm_threshold} "
                         f"recompiles within {now - self._recent[0]:.1f}s — every "
                         "step is paying a compile; pad/bucket the varying input")
                logger.error(storm)
                self.events.append({"kind": "storm", "label": label, "t": now,
                                    "message": storm})
                self._tracer.instant(f"recompile_storm:{self.name}", cat="diagnostics",
                                     label=label)
                from deepspeed_tpu.telemetry.events import emit_event

                emit_event("recompile", "storm", storm, severity="critical",
                           labels={"detector": self.name, "program": label})
        else:
            self._storm_reported = False
