"""DiagnosticsManager: one handle wiring the four diagnostics into an engine.

The engine constructs one manager when the ``diagnostics`` config block is
enabled and keeps ``engine.diagnostics = None`` otherwise — every hot-path
hook is a single ``is not None`` check, the telemetry zero-overhead contract.

Responsibilities:
  - hold the :class:`HealthMonitor` whose probes the engine traces into its
    compiled step (``engine._update_math``)
  - wrap the engine's jitted callables with :class:`RecompileDetector`
  - feed step wall times to :class:`StepTimeAnomalyDetector`
  - append every step's metric snapshot to the :class:`FlightRecorder` and
    honor the ``abort`` policy (the one per-step device fetch diagnostics
    ever does, and only when an abort policy is configured)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

from deepspeed_tpu.diagnostics.anomaly import StepTimeAnomalyDetector
from deepspeed_tpu.diagnostics.flight_recorder import (
    FlightRecorder,
    install_process_hooks,
)
from deepspeed_tpu.diagnostics.health import HealthMonitor
from deepspeed_tpu.diagnostics.recompile import RecompileDetector
from deepspeed_tpu.utils.logging import logger


class TrainingHealthError(RuntimeError):
    """Raised by the ``abort`` policy; carries the offending step's verdicts."""

    def __init__(self, message: str, step: int, verdicts: Dict[str, Any],
                 dump_path: Optional[str] = None):
        super().__init__(message)
        self.step = step
        self.verdicts = verdicts
        self.dump_path = dump_path


class DiagnosticsManager:
    def __init__(self, config, fp16: bool = False, tracer=None):
        self.config = config
        if tracer is None:
            from deepspeed_tpu.telemetry import get_tracer

            tracer = get_tracer()
        self._tracer = tracer

        self.health: Optional[HealthMonitor] = None
        if config.health.enabled:
            self.health = HealthMonitor(config.health, fp16=fp16)

        self._detectors: Dict[str, RecompileDetector] = {}
        self.step_time: Optional[StepTimeAnomalyDetector] = None
        if config.step_time.enabled:
            self.step_time = StepTimeAnomalyDetector(
                window=config.step_time.window,
                straggler_mads=config.step_time.straggler_mads,
                regression_factor=config.step_time.regression_factor,
                min_samples=config.step_time.min_samples,
                tracer=tracer,
            )

        self.flight_recorder: Optional[FlightRecorder] = None
        if config.flight_recorder.enabled:
            self.flight_recorder = FlightRecorder(
                capacity=config.flight_recorder.capacity,
                dump_dir=config.flight_recorder.dump_dir,
                tracer=tracer,
            )
            install_process_hooks(
                signals=config.flight_recorder.install_signal_handlers,
                excepthook=config.flight_recorder.dump_on_exception,
            )

        # Anomaly-triggered jax.profiler capture (profiling/capture.py):
        # straggler/regression flags — or SIGUSR2 — trace the next N steps
        # and drop the device trace next to the flight record.
        self.profiler_capture = None
        pcfg = getattr(config, "profiler_capture", None)
        if pcfg is not None and pcfg.enabled:
            from deepspeed_tpu.profiling.capture import (
                ProfilerCapture,
                install_sigusr2,
            )

            self.profiler_capture = ProfilerCapture(
                steps=pcfg.steps,
                out_dir=pcfg.dir or (self.flight_recorder.dump_dir
                                     if self.flight_recorder is not None else None),
                cooldown_steps=pcfg.cooldown_steps,
                tracer=tracer,
                recorder=self.flight_recorder,
            )
            if pcfg.signal:
                install_sigusr2()

        self._abort_armed = bool(self.health and self.health.abort_signals)
        self._skips_seen = 0

    # -------------------------------------------------------------- recompile
    def wrap_jit(self, name: str, fn: Callable,
                 arg_names: Optional[Sequence[str]] = None) -> Callable:
        """Wrap a jitted callable with a recompile detector (identity when
        recompile checking is off).

        With recompile checking off but the compiled-program registry live,
        the registry still gets its wrap point (same fallback the engine
        uses when diagnostics are absent entirely) — program capture must
        not silently vanish because only the detector was disabled."""
        if not self.config.recompile.enabled or fn is None:
            if fn is None:
                return fn
            from deepspeed_tpu.telemetry.programs import get_program_registry

            # wrap unconditionally: enablement is checked per call (the
            # tracer may not be configured yet at step-build time), and a
            # disabled watcher is a single flag check falling through
            return get_program_registry().wrap(fn, name, hbm_scope="train")
        det = self._detectors.get(name)
        if det is None:
            det = self._detectors[name] = RecompileDetector(
                name,
                arg_names=arg_names,
                storm_threshold=self.config.recompile.storm_threshold,
                storm_window_s=self.config.recompile.storm_window_s,
                tracer=self._tracer,
                # engine step programs calibrate against the train-scope
                # pre-flight HBM estimate (telemetry/programs.py)
                hbm_scope="train",
            )
        return det.wrap(fn)

    def detector(self, name: str) -> Optional[RecompileDetector]:
        return self._detectors.get(name)

    # -------------------------------------------------------------- per step
    def before_step(self, step: int) -> None:
        """Pre-dispatch hook: starts an armed profiler-capture window so the
        trace brackets whole steps. One attribute check when idle."""
        if self.profiler_capture is not None:
            self.profiler_capture.on_step_start(step)

    def after_step(self, step: int, metrics: Dict[str, Any],
                   step_time_s: Optional[float] = None) -> None:
        """Host-side per-step hook: ring append + step-time observe + abort.

        ``metrics`` leaves stay device-side except under the abort policy,
        which fetches the scalar verdicts (an explicit sync the config chose).
        """
        if self.flight_recorder is not None:
            extra = {}
            if step_time_s is not None:
                extra["step_time_ms"] = round(step_time_s * 1e3, 3)
            self.flight_recorder.record(step, metrics, **extra)
        if self.step_time is not None and step_time_s is not None:
            flags = self.step_time.observe(step_time_s, step=step)
            if (self.profiler_capture is not None
                    and self.config.profiler_capture.on_anomaly
                    and (flags["straggler"] or flags["regression"])):
                kind = "straggler" if flags["straggler"] else "regression"
                self.profiler_capture.arm(reason=f"anomaly:{kind}@step{step}")
        if self.profiler_capture is not None:
            self.profiler_capture.on_step_end(step)
        if self._abort_armed and "health/abort" in metrics:
            import jax

            if bool(jax.device_get(metrics["health/abort"])):
                fetched = jax.device_get(
                    {k: v for k, v in metrics.items() if k.startswith("health/")})
                verdicts = {k: (v.item() if hasattr(v, "item") else v)
                            for k, v in fetched.items()}
                dump_path = self.dump(reason="health_abort")
                bad = [s for s in ("nonfinite_any", "grad_spike", "loss_spike")
                       if verdicts.get(f"health/{s}")]
                msg = (f"training health abort at step {step}: "
                       f"{', '.join(bad) or 'health signal'} fired "
                       f"(verdicts: {verdicts})"
                       + (f"; flight record: {dump_path}" if dump_path else ""))
                from deepspeed_tpu.telemetry.events import emit_event

                emit_event("health", "abort", msg, severity="critical",
                           labels={"signals": ",".join(bad) or "unknown",
                                   **({"dump": dump_path} if dump_path
                                      else {})},
                           step=step)
                raise TrainingHealthError(
                    msg, step=step, verdicts=verdicts, dump_path=dump_path)

    # ------------------------------------------------------------------ dump
    def dump(self, reason: str = "manual", path: Optional[str] = None) -> Optional[str]:
        """Explicit flight-recorder dump; returns the path (None when the
        recorder is disabled)."""
        if self.flight_recorder is None:
            logger.warning("diagnostics.dump(): flight recorder is disabled")
            return None
        return self.flight_recorder.dump(reason=reason, path=path)
