"""Multi-host launcher CLI.

Reference: ``deepspeed/launcher/runner.py:419 main`` (the ``deepspeed`` CLI)
+ ``multinode_runner.py`` (PDSH/MPI/Slurm runners). TPU-native differences:
rendezvous is ``jax.distributed.initialize`` (coordinator ip:port +
process_id/num_processes) instead of torch.distributed; one PROCESS per host
drives all local chips (SPMD), so "slots" in the hostfile count chips for
world-size math but do not multiply processes.

Hostfile format parity (reference ``parse_resource_filter``):
    worker-1 slots=4
    worker-2 slots=4
with ``--include``/``--exclude`` filters (``worker-1@worker-2:0,1`` syntax
reduces to host granularity here — chips aren't individually addressable
under SPMD).
"""

from __future__ import annotations

import argparse
import os
import shlex
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

DEFAULT_COORD_PORT = 29500


def parse_hostfile(path_or_text: str, from_text: bool = False) -> Dict[str, int]:
    """'host slots=N' lines -> {host: slots} (reference runner.py
    ``_parse_hostfile``). Comments (#) and blank lines skipped."""
    if from_text:
        lines = path_or_text.splitlines()
    else:
        with open(path_or_text) as f:
            lines = f.readlines()
    hosts: Dict[str, int] = {}
    for ln in lines:
        ln = ln.split("#", 1)[0].strip()
        if not ln:
            continue
        parts = ln.split()
        host = parts[0]
        slots = 1
        for p in parts[1:]:
            if p.startswith("slots="):
                slots = int(p.split("=", 1)[1])
        if host in hosts:
            raise ValueError(f"duplicate host {host!r} in hostfile")
        hosts[host] = slots
    if not hosts:
        raise ValueError("hostfile contains no hosts")
    return hosts


def filter_hosts(hosts: Dict[str, int], include: str = "", exclude: str = "") -> Dict[str, int]:
    """Apply --include/--exclude host filters (reference ``parse_inclusion_exclusion``)."""
    if include and exclude:
        raise ValueError("--include and --exclude are mutually exclusive")
    sel = dict(hosts)
    if include:
        names = [h.split(":")[0] for h in include.split("@")]
        missing = [n for n in names if n not in hosts]
        if missing:
            raise ValueError(f"--include names unknown hosts {missing}")
        sel = {n: hosts[n] for n in names}
    if exclude:
        for h in exclude.split("@"):
            sel.pop(h.split(":")[0], None)
        if not sel:
            raise ValueError("--exclude removed every host")
    return sel


_LOCAL_NAMES = ("localhost", "127.0.0.1", "::1")


def _is_local(host: str) -> bool:
    return host in _LOCAL_NAMES or host == socket.gethostname()


def build_launch_commands(
    hosts: Dict[str, int],
    script: str,
    script_args: List[str],
    coordinator: Optional[str] = None,
    port: int = DEFAULT_COORD_PORT,
    ssh_port: Optional[int] = None,
    env_passthrough: Optional[List[str]] = None,
) -> List[Tuple[str, List[str]]]:
    """Per-host (host, argv) pairs invoking ``launcher.launch`` over ssh
    (reference ``PDSHRunner.get_cmd`` multinode_runner.py:55 — here plain ssh
    per host; pdsh adds fanout, not semantics). Remote commands cd into the
    invoking working directory (relative script/data paths must resolve) and
    get a pty (-tt) so Ctrl-C reaches the remote process tree."""
    host_list = list(hosts)
    coordinator = coordinator or host_list[0]
    n = len(host_list)
    cwd = os.path.abspath(os.getcwd())
    cmds = []
    for rank, host in enumerate(host_list):
        inner = [
            sys.executable, "-m", "deepspeed_tpu.launcher.launch",
            "--coordinator", f"{coordinator}:{port}",
            "--num-processes", str(n),
            "--process-id", str(rank),
            "--", script, *script_args,
        ]
        if _is_local(host):
            cmds.append((host, inner))
            continue
        envs = []
        for var in env_passthrough or []:
            if var in os.environ:
                envs.append(f"{var}={shlex.quote(os.environ[var])}")
        ssh = ["ssh", "-tt", "-o", "StrictHostKeyChecking=no"]
        if ssh_port:
            ssh += ["-p", str(ssh_port)]
        remote = f"cd {shlex.quote(cwd)} && " + " ".join(["env", *envs, *map(shlex.quote, inner)])
        ssh += [host, remote]
        cmds.append((host, ssh))
    return cmds


def main(argv: Optional[List[str]] = None) -> int:
    """The ``dstpu`` CLI entry (reference ``deepspeed`` bin + runner main)."""
    p = argparse.ArgumentParser(
        prog="dstpu", description="deepspeed_tpu multi-host launcher"
    )
    p.add_argument("--hostfile", default=None, help="'host slots=N' lines; absent = single host")
    p.add_argument("--include", default="", help="host[@host...] to include")
    p.add_argument("--exclude", default="", help="host[@host...] to exclude")
    p.add_argument("--master_addr", default=None, help="coordinator address (default: first host)")
    p.add_argument("--master_port", type=int, default=DEFAULT_COORD_PORT)
    p.add_argument("--ssh_port", type=int, default=None)
    p.add_argument("--env", action="append", default=[], help="env vars to pass through ssh")
    p.add_argument("--dry_run", action="store_true", help="print commands, do not launch")
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)

    if args.hostfile:
        hosts = filter_hosts(parse_hostfile(args.hostfile), args.include, args.exclude)
    else:
        hosts = {"localhost": 1}
    cmds = build_launch_commands(
        hosts, args.script, args.script_args,
        coordinator=args.master_addr, port=args.master_port,
        ssh_port=args.ssh_port, env_passthrough=args.env,
    )
    if args.dry_run:
        for host, argv_ in cmds:
            print(f"[{host}] {' '.join(argv_)}")
        return 0

    procs = [subprocess.Popen(argv_) for _, argv_ in cmds]

    def _kill_all():
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        deadline = time.time() + 10
        for proc in procs:
            if proc.poll() is None:
                try:
                    proc.wait(timeout=max(deadline - time.time(), 0.1))
                except subprocess.TimeoutExpired:
                    proc.kill()

    rc = 0
    try:
        # poll: first nonzero exit kills the peers — otherwise survivors hang
        # forever in jax.distributed rendezvous/collectives
        live = dict(enumerate(procs))
        while live:
            for i in list(live):
                code = live[i].poll()
                if code is None:
                    continue
                del live[i]
                if code != 0:
                    logger.error(f"host {cmds[i][0]} exited with {code}; terminating peers")
                    rc = rc or code
                    _kill_all()
                    live.clear()
            time.sleep(0.2)
    except KeyboardInterrupt:
        _kill_all()
        rc = 130
    return rc


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
