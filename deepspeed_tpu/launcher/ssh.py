"""Parallel ssh over a hostfile (reference ``bin/ds_ssh``)."""

from __future__ import annotations

import shlex
import subprocess
import sys
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional


def run_on_hosts(hosts: List[str], command: List[str], max_workers: int = 32) -> int:
    """Run ``command`` on every host via ssh; per-host-prefixed output.

    Remote args are shlex-quoted (the repo-wide convention,
    ``launcher/runner.py``) so spaces/metacharacters survive the remote shell.
    Returns the max exit code.
    """
    remote = " ".join(map(shlex.quote, command))

    def run(host: str) -> int:
        r = subprocess.run(
            ["ssh", "-o", "StrictHostKeyChecking=no", host, remote],
            capture_output=True, text=True,
        )
        # one write per host: concurrent prints cannot interleave mid-line
        block = "".join(f"[{host}] {line}\n"
                        for line in (r.stdout + r.stderr).splitlines())
        sys.stdout.write(block)
        sys.stdout.flush()
        return r.returncode

    with ThreadPoolExecutor(max_workers=min(len(hosts), max_workers)) as ex:
        codes = list(ex.map(run, hosts))
    return max(codes) if codes else 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    from deepspeed_tpu.launcher.runner import parse_hostfile

    p = argparse.ArgumentParser(description="run a command on every hostfile host")
    p.add_argument("--hostfile", default="/job/hostfile")
    p.add_argument("command", nargs=argparse.REMAINDER)
    a = p.parse_args(argv)
    if not a.command:
        p.error("no command given")
    return run_on_hosts(list(parse_hostfile(a.hostfile)), a.command)
