"""Per-host launcher: wire jax.distributed, exec the training script.

Reference: ``deepspeed/launcher/launch.py:133 main`` — the per-node process
that sets rank env vars and spawns local workers. Under SPMD one process per
host drives all local chips, so this just initializes the JAX distributed
runtime (coordinator rendezvous over DCN) and runs the script in-process.
"""

from __future__ import annotations

import argparse
import runpy
import sys
from typing import List, Optional

from deepspeed_tpu.utils.logging import logger


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="deepspeed_tpu.launcher.launch")
    p.add_argument("--coordinator", required=True, help="ip:port of process 0")
    p.add_argument("--num-processes", type=int, required=True)
    p.add_argument("--process-id", type=int, required=True)
    p.add_argument("rest", nargs=argparse.REMAINDER, help="-- script [args...]")
    args = p.parse_args(argv)

    rest = args.rest
    if rest and rest[0] == "--":
        rest = rest[1:]
    if not rest:
        p.error("no training script given")
    script, script_args = rest[0], rest[1:]

    if args.num_processes > 1:
        import jax

        logger.info(
            f"jax.distributed.initialize({args.coordinator}, "
            f"num={args.num_processes}, id={args.process_id})"
        )
        jax.distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )

    sys.argv = [script, *script_args]
    runpy.run_path(script, run_name="__main__")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
