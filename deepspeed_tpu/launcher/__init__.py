"""deepspeed_tpu.launcher (reference ``deepspeed/launcher/``): the ``dstpu``
multi-host CLI (``runner.py``) and per-host bootstrap (``launch.py``)."""

from deepspeed_tpu.launcher.runner import build_launch_commands, filter_hosts, parse_hostfile
