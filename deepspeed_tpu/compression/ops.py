"""Compression primitives (fake-quant + structured/unstructured pruning).

Reference: ``compression/basic_layer.py`` (LinearLayer_Compress and friends:
weight quantization with straight-through estimator, sparse/row/head pruning
masks) and ``compression/helper.py`` layer-reduction utilities. All pure
jnp — they fuse into the training step and differentiate via STE.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def fake_quantize(w: jax.Array, bits: int = 8, symmetric: bool = True,
                  group_size: int = 0) -> jax.Array:
    """Quantize-dequantize with straight-through gradient (QAT).

    Reference ``Quantizer`` forward in compression/basic_layer.py; per-group
    scales along the last dim when ``group_size`` > 0.
    """
    if bits >= 32:
        return w
    orig_shape = w.shape
    g = group_size if group_size and w.shape[-1] % group_size == 0 else w.shape[-1]
    wg = w.reshape(-1, g)
    qmax = 2.0 ** (bits - 1) - 1 if symmetric else 2.0 ** bits - 1
    if symmetric:
        scale = jnp.max(jnp.abs(wg), axis=-1, keepdims=True) / qmax
        scale = jnp.maximum(scale, 1e-8)
        q = jnp.clip(jnp.round(wg / scale), -qmax - 1, qmax) * scale
    else:
        lo = jnp.min(wg, axis=-1, keepdims=True)
        hi = jnp.max(wg, axis=-1, keepdims=True)
        scale = jnp.maximum((hi - lo) / qmax, 1e-8)
        q = (jnp.clip(jnp.round((wg - lo) / scale), 0, qmax)) * scale + lo
    q = q.reshape(orig_shape)
    # straight-through estimator: forward quantized, backward identity
    return w + jax.lax.stop_gradient(q - w)


def magnitude_prune_mask(w: jax.Array, sparsity: float) -> jax.Array:
    """Unstructured |w| mask at the given sparsity (reference sparse_pruning
    'l1' method)."""
    if sparsity <= 0:
        return jnp.ones_like(w)
    k = int((1.0 - sparsity) * w.size)
    if k < 1:
        return jnp.zeros_like(w)
    thresh = jnp.sort(jnp.abs(w).ravel())[-k]
    return (jnp.abs(w) >= thresh).astype(w.dtype)


def row_prune_mask(w: jax.Array, sparsity: float, axis: int = 0) -> jax.Array:
    """Structured row/column mask by L1 norm (reference row_pruning)."""
    if sparsity <= 0:
        return jnp.ones_like(w)
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    norms = jnp.sum(jnp.abs(w), axis=reduce_axes)
    k = max(int((1.0 - sparsity) * norms.size), 1)
    thresh = jnp.sort(norms)[-k]
    keep = (norms >= thresh).astype(w.dtype)
    shape = [1] * w.ndim
    shape[axis] = -1
    return jnp.broadcast_to(keep.reshape(shape), w.shape)


def head_prune_mask(w: jax.Array, sparsity: float, num_heads: int,
                    head_axis: int = 1) -> jax.Array:
    """Attention-head mask by per-head L1 norm (reference head_pruning;
    w shaped [..., heads, ...] with ``head_axis`` pointing at the head dim)."""
    if sparsity <= 0:
        return jnp.ones_like(w)
    if w.shape[head_axis] != num_heads:
        raise ValueError(f"axis {head_axis} has {w.shape[head_axis]} != num_heads {num_heads}")
    reduce_axes = tuple(i for i in range(w.ndim) if i != head_axis)
    norms = jnp.sum(jnp.abs(w), axis=reduce_axes)
    k = max(int((1.0 - sparsity) * num_heads), 1)
    thresh = jnp.sort(norms)[-k]
    keep = (norms >= thresh).astype(w.dtype)
    shape = [1] * w.ndim
    shape[head_axis] = -1
    return jnp.broadcast_to(keep.reshape(shape), w.shape)


def reduce_layers(stacked: jax.Array, keep_layers: Optional[list] = None,
                  target_depth: Optional[int] = None) -> jax.Array:
    """Layer reduction over nn.scan-stacked leaves [L, ...] (reference
    compression/helper.py student-initialization: pick a subset of teacher
    layers)."""
    L = stacked.shape[0]
    if keep_layers is None:
        if target_depth is None or target_depth >= L:
            return stacked
        idx = jnp.linspace(0, L - 1, target_depth).round().astype(jnp.int32)
    else:
        idx = jnp.asarray(keep_layers, jnp.int32)
    return jnp.take(stacked, idx, axis=0)
