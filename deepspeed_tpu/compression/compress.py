"""Compression orchestration: config + schedule + pytree application.

Reference: ``compression/compress.py:100 init_compression`` +
``compression/scheduler.py CompressionScheduler``. The reference swaps
nn.Modules for *_Compress variants and lets a scheduler flip them on at
``schedule_offset``; here ``apply_compression`` is a pure params->params
function (fake-quant with STE, prune masks, layer reduction) meant to be
called inside the loss (QAT path) or once offline, and the scheduler just
answers "which methods are active at step t".

Config schema parity (subset of reference ``compression/config.py``):
  {"weight_quantization": {"shared_parameters": {...}, "different_groups":
      {"group1": {"params": {"target_bits": 8}, "modules": ["attn", "mlp"]}}},
   "sparse_pruning": {...}, "row_pruning": {...}, "head_pruning": {...},
   "layer_reduction": {"enabled": true, "keep_number_layer": N, ...}}
Module matching is substring-on-pytree-path (the reference matches module
names the same way).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.compression.ops import (
    fake_quantize,
    head_prune_mask,
    magnitude_prune_mask,
    reduce_layers,
    row_prune_mask,
)
from deepspeed_tpu.utils.logging import log_dist


_METHODS = ("weight_quantization", "sparse_pruning", "row_pruning", "head_pruning")


class CompressionScheduler:
    """Answers which compression methods are live at a step (reference
    ``CompressionScheduler`` compression/scheduler.py)."""

    def __init__(self, config: Dict):
        self.config = config or {}
        self.offsets: Dict[str, int] = {}
        for m in _METHODS:
            sec = self.config.get(m, {})
            shared = sec.get("shared_parameters", sec)
            self.offsets[m] = int(shared.get("schedule_offset", 0)) if sec else -1

    def active_methods(self, step: int) -> List[str]:
        return [m for m, off in self.offsets.items() if off >= 0 and step >= off and self.config.get(m)]

    def is_active(self, method: str, step: int) -> bool:
        return method in self.active_methods(step)


def _groups_of(section: Dict) -> List[Tuple[Dict, List[str]]]:
    out = []
    for g in section.get("different_groups", {}).values():
        out.append((g.get("params", {}), list(g.get("modules", ["*"]))))
    if not out:
        out.append((section.get("shared_parameters", {}), ["*"]))
    return out


def _matches(path: str, patterns: List[str]) -> bool:
    return any(p == "*" or p in path for p in patterns)


def apply_compression(params: Any, config: Dict, step: int = 10**9,
                      num_heads: Optional[int] = None) -> Any:
    """Pure params -> compressed params (the *_Compress forward equivalents).

    Only kernels/embeddings are touched (2D+ leaves); biases/norms pass
    through, matching the reference's Linear/Conv targeting.
    """
    sched = CompressionScheduler(config)
    active = sched.active_methods(step)
    if not active and not config.get("layer_reduction", {}).get("enabled", False):
        return params

    wq = config.get("weight_quantization", {})
    sp = config.get("sparse_pruning", {})
    rp = config.get("row_pruning", {})
    hp = config.get("head_pruning", {})

    def leaf_fn(path_keys, w):
        path = jax.tree_util.keystr(path_keys)
        if not hasattr(w, "ndim") or w.ndim < 2 or not jnp.issubdtype(w.dtype, jnp.floating):
            return w
        out = w
        if "sparse_pruning" in active:
            for p, mods in _groups_of(sp):
                if _matches(path, mods):
                    sparsity = float(p["sparsity"]) if "sparsity" in p else 1.0 - float(p.get("dense_ratio", 0.5))
                    out = out * magnitude_prune_mask(out, sparsity)
                    break
        if "row_pruning" in active:
            for p, mods in _groups_of(rp):
                if _matches(path, mods):
                    out = out * row_prune_mask(out, 1.0 - float(p.get("dense_ratio", 0.5)), axis=out.ndim - 1)
                    break
        if "head_pruning" in active and num_heads:
            for p, mods in _groups_of(hp):
                if _matches(path, mods) and any(t in path for t in ("'wq'", "'wk'", "'wv'", "'wo'")):
                    axis = out.ndim - 2 if "'wo'" not in path else out.ndim - 3
                    if 0 <= axis < out.ndim and out.shape[axis] == num_heads:
                        out = out * head_prune_mask(out, 1.0 - float(p.get("dense_ratio", 0.5)), num_heads, head_axis=axis)
                    break
        if "weight_quantization" in active:
            for p, mods in _groups_of(wq):
                if _matches(path, mods):
                    out = fake_quantize(
                        out,
                        bits=int(p.get("target_bits", p.get("start_bits", 8))),
                        symmetric=p.get("quantization_type", "symmetric") == "symmetric",
                        group_size=int(p.get("quantize_groups", 0)) and out.shape[-1] // int(p.get("quantize_groups", 1)),
                    )
                    break
        return out

    params = jax.tree_util.tree_map_with_path(leaf_fn, params)

    lr = config.get("layer_reduction", {})
    if lr.get("enabled", False) and isinstance(params, dict) and "layers" in params:
        target = int(lr.get("keep_number_layer", 0)) or None
        keep = lr.get("teacher_layer")
        params = dict(params)
        params["layers"] = jax.tree_util.tree_map(
            lambda x: reduce_layers(x, keep_layers=keep, target_depth=target), params["layers"]
        )
    return params


def init_compression(config: Dict, num_heads: Optional[int] = None):
    """Build (scheduler, loss-transform) — reference ``init_compression``
    compress.py:100 returns the rewritten model; here you wrap your loss:

        sched, compress = init_compression(comp_cfg)
        def loss_fn(params, batch, rng, step):
            return base_loss(compress(params, step), batch, rng)
    """
    sched = CompressionScheduler(config)

    def compress(params, step=10**9):
        return apply_compression(params, config, step, num_heads=num_heads)

    log_dist(f"compression initialized: methods={[m for m in _METHODS if config.get(m)]}", ranks=[0])
    return sched, compress
