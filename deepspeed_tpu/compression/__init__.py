"""deepspeed_tpu.compression: QAT fake-quant, pruning, layer reduction.

Reference: ``deepspeed/compression/`` — ``init_compression``
(compress.py:100) rewrites modules into compressible variants driven by a
schedule; here compression is a pure function over the param pytree applied
inside the compiled loss (QAT) or once offline (post-training), scheduled by
``CompressionScheduler``.
"""

from deepspeed_tpu.compression.compress import (
    CompressionScheduler,
    apply_compression,
    init_compression,
)
from deepspeed_tpu.compression.ops import (
    fake_quantize,
    head_prune_mask,
    magnitude_prune_mask,
    reduce_layers,
    row_prune_mask,
)
