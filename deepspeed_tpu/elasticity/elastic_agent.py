"""Elastic agent: restart training with a re-resolved world after failures.

Reference analog: ``deepspeed/elasticity/elastic_agent.py:32 DSElasticAgent``
(a torch-elastic agent subclass that restarts failed workers and lets
elasticity re-resolve the batch config). TPU mapping: workers are per-host
processes launched by ``launcher/runner.py``; on a worker failure the agent
kills the generation, drops the failed host, asks
``elasticity.compute_elastic_config`` for a valid (batch, micro, world)
triple at the surviving world size, and relaunches — up to
``max_restarts`` generations. State continuity comes from the framework's
checkpoint/resume (universal checkpoints load under any world size).

Semantics gap vs the reference (deliberate, documented): torch-elastic's
agent re-forms the process group IN PLACE via a rendezvous barrier — ranks
of a surviving generation re-join without the script exiting. Here a
generation change always goes through full process relaunch +
checkpoint-resume, because a jax.distributed world (and every compiled
program's mesh) is fixed at initialization: XLA binds collectives to the
topology at compile time, so "the same training step at world-1" is a NEW
program either way. Relaunch makes that explicit and keeps the recovery
path identical to the cold-start path (one code path, always exercised).
The cost is generation-restart latency = process spawn + resume, vs
torch-elastic's in-process re-rendezvous.
"""

from __future__ import annotations

import dataclasses
import subprocess
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.elasticity.elasticity import compute_elastic_config
from deepspeed_tpu.elasticity.resilience import EXIT_PREEMPTED
from deepspeed_tpu.utils.logging import logger


@dataclasses.dataclass
class GenerationResult:
    generation: int
    world_size: int
    returncodes: Dict[str, int]
    ok: bool
    # Hosts that exited with the preemption code (clean snapshot-then-exit,
    # ``resilience.EXIT_PREEMPTED``). They are relaunched in the next
    # generation rather than dropped from the roster.
    preempted: List[str] = dataclasses.field(default_factory=list)


class DSElasticAgent:
    """Launch + supervise worker processes; restart on failure with a
    shrunken world.

    ``launch_fn(hosts, gen, elastic_cfg) -> {host: Popen}`` abstracts process
    creation so unit tests (and future schedulers) can inject their own; the
    default shells out like ``launcher/runner.py`` does.
    """

    def __init__(
        self,
        hosts: Dict[str, int],  # host -> slots
        elastic_config: Dict,  # reference 'elasticity' config section
        launch_fn: Callable[[Sequence[str], int, Dict], Dict[str, subprocess.Popen]],
        max_restarts: int = 3,
        min_hosts: int = 1,
        poll_interval_s: float = 0.5,
        preempt_exit_code: int = EXIT_PREEMPTED,
    ):
        self.hosts = dict(hosts)
        self.elastic_config = elastic_config
        self.launch_fn = launch_fn
        self.max_restarts = max_restarts
        self.min_hosts = min_hosts
        self.poll_interval_s = poll_interval_s
        self.preempt_exit_code = preempt_exit_code
        self.history: List[GenerationResult] = []

    # ------------------------------------------------------------------
    def _world_size(self, hosts: Dict[str, int]) -> int:
        return sum(hosts.values())

    def resolve_config(self, hosts: Dict[str, int]) -> Tuple[Dict, int]:
        """Elastic batch triple for this generation's world size."""
        from deepspeed_tpu.elasticity.elasticity import ElasticityError

        world = self._world_size(hosts)
        batch, valid, _micro_map, micro = compute_elastic_config(
            self.elastic_config, world_size=world)
        if micro is None:
            raise ElasticityError(
                f"world size {world} is not elastic-compatible (valid: {valid})")
        return {"train_batch_size": batch, "train_micro_batch_size_per_gpu": micro}, world

    def _wait_generation(
        self, procs: Dict[str, subprocess.Popen]
    ) -> Tuple[Dict[str, int], List[str], List[str]]:
        """Block until all exit, or kill the generation on first failure
        (the launcher's peers-die-together contract).

        Returns (exit codes, failed hosts, preempted hosts). Survivors the
        AGENT terminated exit non-zero too, but they did not fail — only
        hosts that died on their own count (otherwise one crash would
        disqualify every host and no restart could ever happen). A host that
        self-exited with ``preempt_exit_code`` is *preempted*, not failed:
        it took a clean snapshot on SIGTERM (``resilience.PreemptionGuard``)
        and keeps its roster slot, but the generation still ends — peers
        can't train past a departed rank — so the cascade fires for it too."""
        live = dict(procs)
        codes: Dict[str, int] = {}
        agent_killed: set = set()

        def sweep():
            for host, p in list(live.items()):
                rc = p.poll()
                if rc is not None:
                    codes[host] = rc
                    del live[host]

        cascaded = False
        while live:
            sweep()
            if not cascaded and any(
                rc != 0 for h, rc in codes.items() if h not in agent_killed
            ):
                # one grace poll so SIMULTANEOUS crashers surface as genuine
                # failures before the cascade marks survivors agent-killed
                time.sleep(self.poll_interval_s)
                sweep()
                for other_host, other in live.items():
                    try:
                        other.terminate()
                        agent_killed.add(other_host)
                    except Exception:
                        pass
                cascaded = True
            time.sleep(self.poll_interval_s)
        for host, p in procs.items():
            codes.setdefault(host, p.returncode if p.returncode is not None else -1)
        preempted = [
            h for h, rc in codes.items()
            if rc == self.preempt_exit_code and h not in agent_killed
        ]
        failed = [
            h for h, rc in codes.items()
            if rc != 0 and h not in agent_killed and h not in preempted
        ]
        return codes, failed, preempted

    def run(self) -> GenerationResult:
        """Supervise generations until success or restart budget exhausted."""
        hosts = dict(self.hosts)
        for gen in range(self.max_restarts + 1):
            cfg, world = self.resolve_config(hosts)
            logger.info(f"elastic generation {gen}: hosts={list(hosts)} world={world} cfg={cfg}")
            procs = self.launch_fn(list(hosts), gen, cfg)
            codes, failed, preempted = self._wait_generation(procs)
            result = GenerationResult(
                gen, world, codes,
                ok=not any(rc != 0 for rc in codes.values()),
                preempted=preempted,
            )
            self.history.append(result)
            if result.ok:
                return result
            # drop failed hosts; restart the survivors as a smaller world.
            # Preempted hosts keep their slot — they exited cleanly with a
            # durable snapshot and resume from it on relaunch.
            for h in failed:
                hosts.pop(h, None)
            if len(hosts) < self.min_hosts:
                logger.error(f"elastic agent: {len(hosts)} hosts left (< min {self.min_hosts}); giving up")
                return result
            if failed:
                logger.warning(f"elastic agent: workers failed on {failed}; restarting with {list(hosts)}")
            if preempted:
                logger.warning(
                    f"elastic agent: hosts preempted (clean exit {self.preempt_exit_code}): "
                    f"{preempted}; relaunching with roster intact")
        return self.history[-1]
