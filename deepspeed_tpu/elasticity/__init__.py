"""deepspeed_tpu.elasticity: batch-size-compatible world sizing.

Reference analog: ``deepspeed/elasticity/`` — ``compute_elastic_config``
(elasticity.py:233) picks a global batch size divisible by many chip counts so
a job can resume on whatever slice size is available, keeping the batch triad
consistent (v2 additionally scales by model-parallel size). On TPU this is
how a run survives preemption onto a different slice topology; combined with
universal checkpoints (``deepspeed_tpu.checkpoint``) the resume is turnkey.
"""

from deepspeed_tpu.elasticity.elasticity import (
    ElasticityConfig,
    ElasticityError,
    compatible_world_sizes,
    compute_elastic_config,
    elastic_batch_candidates,
)
from deepspeed_tpu.elasticity.resilience import (
    RecoveryReport,
    run_resilient,
)
