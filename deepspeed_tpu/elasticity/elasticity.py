"""Elastic batch-size math.

Reference: ``elasticity/elasticity.py`` — the capability re-implemented here:
choose a global train batch ≤ max_acceptable that (a) is a multiple of some
allowed micro-batch, and (b) is divisible by as many chip counts in
[min_chips, max_chips] as possible, so ANY of those world sizes can run the
job with an integral (micro_batch × grad_accum × world) decomposition.
Version 2 semantics: world sizes are counted in units of the model-parallel
degree (chips per replica).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.utils.logging import logger


class ElasticityError(ValueError):
    """Bad elasticity config or incompatible world size."""


@dataclasses.dataclass
class ElasticityConfig:
    """Config section (reference ``elasticity/config.py``)."""

    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: Sequence[int] = (2, 4, 6)
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    prefer_larger_batch: bool = True
    version: float = 0.2
    model_parallel_size: int = 1

    @classmethod
    def from_dict(cls, d: Dict) -> "ElasticityConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


# Highly composite numbers: maximally divisible batch-size building blocks.
def _highly_composite(limit: int) -> List[int]:
    out, best = [], 0

    def n_divisors(n: int) -> int:
        cnt, i = 0, 1
        while i * i <= n:
            if n % i == 0:
                cnt += 2 if i * i != n else 1
            i += 1
        return cnt

    n = 1
    while n <= limit:
        d = n_divisors(n)
        if d > best:
            best = d
            out.append(n)
        # jump: HCNs are sparse above 10k and all are multiples of 60 (of
        # 840 above 100k) — step to the NEXT multiple so none is skipped
        if n < 10000:
            n += 1
        elif n < 100000:
            n += 60 - (n % 60) if n % 60 else 60
        else:
            n += 840 - (n % 840) if n % 840 else 840
    return out


_HCN_CACHE: Dict[int, List[int]] = {}


def _hcns_up_to(limit: int) -> List[int]:
    if limit not in _HCN_CACHE:
        _HCN_CACHE[limit] = _highly_composite(limit)
    return _HCN_CACHE[limit]


def elastic_batch_candidates(micro_batches: Sequence[int], max_batch: int) -> List[int]:
    """Per micro-batch: the largest (HCN × micro_batch) ≤ max_batch (HCN
    multiples are divisible by the most world sizes)."""
    cands = set()
    for mb in micro_batches:
        if mb >= max_batch:
            cands.add(mb)
            continue
        budget = max_batch // mb
        hcns = _hcns_up_to(budget)
        cands.add(hcns[-1] * mb)
    return sorted(cands)


def compatible_world_sizes(batch: int, micro_batches: Sequence[int],
                           min_chips: int, max_chips: int) -> Dict[int, int]:
    """{world_size: micro_batch} for every world size that divides ``batch``
    through some allowed micro-batch (world × micro × gas == batch)."""
    valid: Dict[int, int] = {}
    for mb in sorted(micro_batches, reverse=True):
        if batch % mb:
            continue
        slots = batch // mb  # world × gas
        w = 1
        while w * w <= slots:
            if slots % w == 0:
                for cand in (w, slots // w):
                    if min_chips <= cand <= max_chips and cand not in valid:
                        valid[cand] = mb
            w += 1
    return dict(sorted(valid.items()))


def compute_elastic_config(
    config: Dict | ElasticityConfig,
    world_size: int = 0,
) -> Tuple[int, List[int], Dict[int, int], Optional[int]]:
    """Pick the elastic batch (reference ``compute_elastic_config``
    elasticity.py:233).

    Returns (final_batch_size, valid_world_sizes, {world: micro_batch},
    micro_batch_for_current_world). ``world_size`` counts replicas-worth of
    chips divided by model_parallel_size (v2 semantics).
    """
    ecfg = config if isinstance(config, ElasticityConfig) else ElasticityConfig.from_dict(config)
    if not ecfg.micro_batch_sizes or min(ecfg.micro_batch_sizes) < 1:
        raise ElasticityError(f"bad micro_batch_sizes {ecfg.micro_batch_sizes}")
    if ecfg.max_train_batch_size < max(ecfg.micro_batch_sizes):
        raise ElasticityError(
            f"max_train_batch_size {ecfg.max_train_batch_size} < largest micro batch"
        )
    mp = max(ecfg.model_parallel_size, 1)
    min_w = max(ecfg.min_gpus // mp, 1)
    max_w = max(ecfg.max_gpus // mp, 1)

    best: Tuple[int, Dict[int, int]] = (0, {})
    for cand in elastic_batch_candidates(ecfg.micro_batch_sizes, ecfg.max_train_batch_size):
        valid = compatible_world_sizes(cand, ecfg.micro_batch_sizes, min_w, max_w)
        score = (len(valid), cand if ecfg.prefer_larger_batch else -cand)
        cur = (len(best[1]), best[0] if ecfg.prefer_larger_batch else -best[0])
        if score > cur:
            best = (cand, valid)
    final_batch, valid = best
    if not valid:
        raise ElasticityError(
            f"no world size in [{ecfg.min_gpus},{ecfg.max_gpus}] compatible with "
            f"micro_batches={list(ecfg.micro_batch_sizes)} max_batch={ecfg.max_train_batch_size}"
        )

    micro = None
    if world_size:
        replicas = world_size // mp
        if replicas not in valid:
            raise ElasticityError(
                f"world_size {world_size} (= {replicas} replicas × mp {mp}) not in "
                f"compatible set {sorted(valid)}"
            )
        micro = valid[replicas]
    logger.info(
        f"elasticity: batch={final_batch} valid_world_sizes={sorted(valid)}"
        + (f" micro_batch={micro}" if micro else "")
    )
    return final_batch, sorted(valid), valid, micro


def main():  # pragma: no cover - CLI shim (bin/ds_elastic)
    """Elastic config checker (reference ``bin/ds_elastic``)."""
    import argparse
    import json

    p = argparse.ArgumentParser(description="deepspeed_tpu elastic config checker")
    p.add_argument("-c", "--config", required=True,
                   help="ds config json with an 'elasticity' section")
    p.add_argument("-w", "--world-size", type=int, default=0)
    a = p.parse_args()
    with open(a.config) as f:
        cfg = json.load(f)
    batch, worlds, _table, micro = compute_elastic_config(
        cfg.get("elasticity", cfg), world_size=a.world_size)
    print(json.dumps({"train_batch_size": batch, "valid_world_sizes": worlds,
                      "micro_batch": micro}))
