"""Auto-recovery supervisor: diagnostics-driven rewind-to-last-good-snapshot.

Closes the loop ROADMAP open item 5 names: the PR-2 diagnostics stack can
*detect* a poisoned run (``TrainingHealthError`` from the in-step health
probes) but until now detection just killed the job. ``run_resilient`` is the
in-process supervisor between the elastic agent (process-level restarts,
``elastic_agent.py``) and the step loop:

  - drives ``engine.train_batch`` over a deterministic per-step batch stream
    while the engine's :class:`~deepspeed_tpu.checkpoint.snapshot.SnapshotManager`
    takes cadenced async snapshots off the step clock;
  - on ``TrainingHealthError`` (the abort policy fired — the flight recorder
    has already dumped) or a corrupt/unloadable snapshot at restore time:
    rewinds to the last-good snapshot (checksums validated, fresh committed
    buffers, any mesh), re-arms the health monitor (fresh EMA baselines), and
    resumes after an exponential backoff;
  - gives up — re-raising the ORIGINAL error, with the flight-record path and
    a :class:`RecoveryReport` attached — once ``max_rewinds_per_snapshot``
    rewinds land on the SAME snapshot (a fault that reproduces from identical
    state is deterministic, not transient) or ``max_total_rewinds`` is spent.

A failed snapshot *write* (disk full, writer crash — surfaced by the
manager's durability barrier) is logged and training continues: the manager's
``latest`` pointer still names the previous durable snapshot, so a save
failure must never trigger a rewind of healthy training state.

Reference analog: the DeepSpeed elasticity + universal-checkpoint pair plus
what its users script around it (watchdog → load latest → resume); here the
loop is a library feature, exercised by the fault-injection harness
(``diagnostics/faultinject.py``).
"""

from __future__ import annotations

import dataclasses
import signal
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from deepspeed_tpu.checkpoint.snapshot import (
    SnapshotCorruptionError,
    SnapshotError,
    SnapshotManager,
    read_manifest,
)
from deepspeed_tpu.utils.logging import log_dist, logger

# Exit code of a preemption-clean exit (128 + SIGTERM, the conventional
# spelling): the elastic agent treats it as "host preempted, relaunch and
# resume" — NOT a failure that drops the host from the roster.
EXIT_PREEMPTED = 143


class PreemptionGuard:
    """SIGTERM → snapshot at the next step boundary → clean exit.

    A preemption notice (SIGTERM from the scheduler, SIGINT from an
    operator) must never kill the process mid-optimizer-step: the handler
    only sets a flag, and ``run_resilient`` checks it at each step
    boundary — where engine state is consistent — takes a BLOCKING
    snapshot, and raises ``SystemExit(EXIT_PREEMPTED)``. The restarted
    process (same or different mesh shape — restore re-slices) resumes
    from that snapshot with a bit-identical forward trajectory, because
    ``batch_fn(step)`` is a deterministic mapping (asserted below).

    Signal handlers install only on the main thread; elsewhere the guard
    degrades to flag-only (callers can set ``requested`` directly — the
    test seam, and the embedding story for frameworks that own signals).
    """

    def __init__(self, signals: Sequence[int] = (signal.SIGTERM,)):
        self.requested = False
        self._installed: List[Any] = []
        for sig in signals:
            try:
                prev = signal.signal(sig, self._handler)
            except ValueError:
                logger.warning(
                    f"PreemptionGuard: cannot install handler for signal "
                    f"{sig} outside the main thread; set .requested "
                    "directly to request a preemption exit")
                continue
            self._installed.append((sig, prev))

    def _handler(self, signum, frame):  # noqa: ARG002 - signal contract
        self.requested = True

    def uninstall(self) -> None:
        for sig, prev in self._installed:
            try:
                signal.signal(sig, prev)
            except ValueError:
                pass  # not the main thread anymore; nothing to restore
        self._installed = []


def assert_deterministic_batch_fn(batch_fn: Callable[[int], Any],
                                  step: int) -> None:
    """Pin the ``batch_fn(step)`` determinism contract: two calls at the
    same step must return identical batches, leaf for leaf. A resumed run
    replays the data stream from the restored step — a nondeterministic
    batch_fn silently diverges the trajectory instead, which is exactly
    the class of bug that survives every other resume check."""
    import jax
    import numpy as np

    a = jax.tree_util.tree_leaves(batch_fn(step))
    b = jax.tree_util.tree_leaves(batch_fn(step))
    same = len(a) == len(b) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(a, b))
    if not same:
        raise ValueError(
            f"batch_fn({step}) returned different batches on two calls: "
            "run_resilient requires batch_fn(step) to be a DETERMINISTIC "
            "mapping from step to batch (derive randomness from the step, "
            "e.g. seed=step), or a preemption-resumed run will train on a "
            "different data stream than the uninterrupted one")


@dataclasses.dataclass
class RecoveryReport:
    """What the supervisor did — attached to the give-up re-raise as
    ``exc.recovery_report`` and returned on success."""

    steps_completed: int = 0
    snapshots_taken: int = 0
    rewinds: int = 0
    # one entry per rewind: {"step", "tag", "reason"}
    rewind_log: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    save_failures: int = 0
    gave_up: bool = False
    flight_record: Optional[str] = None


def _policy(engine, policy):
    if policy is not None:
        return policy
    return engine.config.model.recovery


def _dump_flight_record(engine, reason: str) -> Optional[str]:
    diag = getattr(engine, "diagnostics", None)
    if diag is None or diag.flight_recorder is None:
        return None
    try:
        return diag.dump(reason=reason)
    except Exception as e:  # noqa: BLE001 — post-mortem best effort
        logger.warning(f"run_resilient: flight-record dump failed: {e}")
        return None


def run_resilient(
    engine,
    batch_fn: Callable[[int], Any],
    num_steps: int,
    snapshot_dir: Optional[str] = None,
    policy=None,
    on_rewind: Optional[Callable[[Dict[str, Any]], None]] = None,
    fleet_client=None,
    resume: str = "auto",
    preemptible: bool = False,
    preemption_signals: Optional[Sequence[int]] = None,
    check_batch_determinism: bool = True,
) -> RecoveryReport:
    """Train ``engine`` to ``num_steps`` optimizer steps, surviving health
    aborts and snapshot corruption by rewinding to the last-good snapshot.

    ``batch_fn(step)`` must return the global batch for optimizer step
    ``step`` (0-based) — a *deterministic* mapping, so a rewind replays the
    same data stream the uninterrupted run would have seen. ``snapshot_dir``
    is required unless the engine already has a configured
    ``snapshot_manager`` (the ``snapshot`` config block); when given, a
    manager is installed on the engine so the cadence hook drives saves.
    ``policy`` defaults to the engine's ``recovery`` config block;
    ``on_rewind`` (if given) is called with each rewind-log entry — the test
    seam, and the place to page a human. ``fleet_client`` (a
    ``telemetry.collector.FleetClient``, or the engine's own when the
    ``telemetry.fleet_url`` config key set one up) gets an out-of-cadence
    push at every rewind and at give-up, stamped with the recovery state —
    the cluster health ledger sees a rewinding/failed process the moment it
    happens, not a heartbeat interval later.

    Elastic/preemption extensions (ISSUE 18):

    - ``resume="auto"`` (default): a FRESH process (``engine.global_steps
      == 0``) pointed at a snapshot directory holding committed snapshots
      restores the latest one before training — the restarted half of a
      preemption. ``resume="never"`` keeps the pre-18 start-from-scratch.
    - ``preemptible=True`` installs a :class:`PreemptionGuard` on
      ``preemption_signals`` (default SIGTERM): at the step boundary after
      the signal, a BLOCKING snapshot is taken and
      ``SystemExit(EXIT_PREEMPTED)`` raised (``recovery_report`` attached).
      The elastic agent recognizes the exit code and relaunches without
      dropping the host.
    - ``batch_fn(step)`` determinism is ASSERTED once at startup
      (``check_batch_determinism``): the resumed data stream must equal
      the uninterrupted one, or resume-bit-identity is silently lost.
    """
    pol = _policy(engine, policy)
    if fleet_client is None:
        fleet_client = getattr(engine, "_fleet_client", None)

    def _fleet_push(phase: str, **extra):
        if fleet_client is not None:
            # never raises (FleetClient swallows transport failures) and
            # carries only host floats — safe inside the recovery path
            fleet_client.push(heartbeat_extra={
                "phase": phase, "rewinds": report.rewinds,
                "gave_up": report.gave_up, **extra})

    mgr: Optional[SnapshotManager] = getattr(engine, "snapshot_manager", None)
    if mgr is None:
        if snapshot_dir is None:
            raise ValueError(
                "run_resilient needs snapshots to rewind to: enable the "
                "'snapshot' config block or pass snapshot_dir=")
        mgr = SnapshotManager(engine, engine.config.model.snapshot,
                              base_dir=snapshot_dir)
        engine.snapshot_manager = mgr  # engine's after_step hook drives cadence

    report = RecoveryReport()
    rewinds_by_tag: Dict[str, int] = {}
    consecutive_rewinds = 0
    sf0 = mgr.save_failures  # cadenced-save failures the manager swallows
    explicit_failures = [0]

    def _sync_save_failures():
        report.save_failures = mgr.save_failures - sf0 + explicit_failures[0]

    if resume not in ("auto", "never"):
        raise ValueError(f"resume must be 'auto'|'never', got {resume!r}")
    if resume == "auto" and mgr.last_good_tag is not None \
            and int(engine.global_steps) == 0:
        # restarted process (preemption, crash): pick up where the last
        # committed snapshot left off — any mesh shape, restore re-slices
        try:
            tag = mgr.restore()
            log_dist(
                f"run_resilient: auto-restored snapshot {tag!r} "
                f"(step {int(engine.global_steps)})", ranks=[0])
        except (SnapshotError, SnapshotCorruptionError) as e:
            logger.warning(
                f"run_resilient: auto-restore failed ({e}); "
                "training from scratch")

    if check_batch_determinism:
        assert_deterministic_batch_fn(batch_fn, int(engine.global_steps))

    guard = PreemptionGuard(
        signals=tuple(preemption_signals) if preemption_signals is not None
        else (signal.SIGTERM,)) if preemptible else None

    if mgr.last_good_tag is None:
        # step-0 anchor: there must always be something to rewind to
        mgr.snapshot(blocking=True)
        report.snapshots_taken += 1

    def give_up(exc: BaseException, reason: str):
        _sync_save_failures()
        report.gave_up = True
        report.flight_record = (getattr(exc, "dump_path", None)
                                or _dump_flight_record(engine, f"giveup:{reason}")
                                or report.flight_record)
        exc.recovery_report = report
        _fleet_push("failed", reason=reason)
        msg = (f"run_resilient: giving up after {report.rewinds} rewind(s) — "
               f"{reason}"
               + (f"; flight record: {report.flight_record}"
                  if report.flight_record else ""))
        logger.error(msg)
        from deepspeed_tpu.telemetry.events import emit_event

        emit_event("resilience", "give_up", msg, severity="critical",
                   labels={"reason": reason, "rewinds": report.rewinds},
                   step=int(engine.global_steps))
        raise exc

    def _preempt_exit(at_step: int):
        """Step boundary after a preemption signal: durable snapshot, clean
        exit. A failed snapshot write still exits — the restart resumes
        from the previous good tag (steps replay, trajectory identical)."""
        try:
            mgr.snapshot(blocking=True)
            report.snapshots_taken += 1
        except SnapshotError as e:
            explicit_failures[0] += 1
            logger.warning(
                f"run_resilient: preemption snapshot failed ({e}); exiting "
                "on the previous good snapshot")
        _sync_save_failures()
        report.steps_completed = at_step
        _fleet_push("preempted", step=at_step)
        from deepspeed_tpu.telemetry.events import emit_event

        emit_event("resilience", "preempted",
                   f"run_resilient: preemption signal honored at step "
                   f"{at_step} — snapshot committed, exiting {EXIT_PREEMPTED}",
                   severity="warn", step=at_step)
        log_dist(
            f"run_resilient: preemption signal honored at step {at_step} — "
            f"snapshot committed, exiting {EXIT_PREEMPTED}", ranks=[0])
        if guard is not None:
            guard.uninstall()
        exc = SystemExit(EXIT_PREEMPTED)
        exc.recovery_report = report
        raise exc

    step = int(engine.global_steps)
    report.steps_completed = step
    while step < num_steps:
        if guard is not None and guard.requested:
            _preempt_exit(step)
        last_tag_before = mgr.last_good_tag
        try:
            engine.train_batch(batch_fn(step))
        except SnapshotCorruptionError as e:
            # raised by a restore path, not training — nothing to rewind to
            give_up(e, "snapshot store corrupt")
        except SnapshotError as e:
            # Defense in depth: cadenced saves swallow write failures inside
            # after_step, so nothing raises SnapshotError out of train_batch
            # today. If one ever escapes, it comes from the POST-update
            # boundary hook — the optimizer step applied, training state is
            # healthy, 'latest' still names the previous durable snapshot —
            # so count the step and keep going.
            explicit_failures[0] += 1
            logger.warning(f"run_resilient: snapshot save failed ({e}); "
                           "training continues on the previous good snapshot")
            step += 1
            report.steps_completed = step
            continue
        except Exception as e:
            from deepspeed_tpu.diagnostics import TrainingHealthError

            if not isinstance(e, TrainingHealthError):
                raise  # not a health verdict: the supervisor has no opinion
            report.flight_record = e.dump_path or report.flight_record
            report.rewinds += 1
            consecutive_rewinds += 1
            if report.rewinds > pol.max_total_rewinds:
                give_up(e, f"max_total_rewinds={pol.max_total_rewinds} exhausted")
            try:
                tag = mgr.restore()  # validates checksums; falls back past
                # corrupt tags; fresh committed buffers on THIS mesh
            except (SnapshotError, SnapshotCorruptionError) as re_err:
                re_err.__cause__ = e
                give_up(re_err, "no loadable snapshot to rewind to")
            rewinds_by_tag[tag] = rewinds_by_tag.get(tag, 0) + 1
            if rewinds_by_tag[tag] > pol.max_rewinds_per_snapshot:
                give_up(e, f"{rewinds_by_tag[tag]} rewinds landed on snapshot "
                           f"{tag!r} (deterministic fault)")
            engine.reset_health()  # fresh EMA baselines for the resumed run
            step = int(engine.global_steps)
            entry = {"step": step, "tag": tag, "reason": str(e)}
            report.rewind_log.append(entry)
            report.steps_completed = step
            backoff = min(pol.backoff_base_s * (2.0 ** (consecutive_rewinds - 1)),
                          pol.backoff_max_s)
            log_dist(
                f"run_resilient: rewound to snapshot {tag!r} (step {step}) "
                f"after: {e}; backing off {backoff:.1f}s "
                f"(rewind {report.rewinds}, {rewinds_by_tag[tag]} on this tag)",
                ranks=[0])
            if on_rewind is not None:
                on_rewind(entry)
            _fleet_push("rewound", tag=tag, step=step)
            from deepspeed_tpu.telemetry.events import emit_event

            emit_event("resilience", "rewind",
                       f"run_resilient: rewound to snapshot {tag!r} "
                       f"(step {step}) after: {e}",
                       severity="warn",
                       labels={"tag": tag, "rewind": report.rewinds},
                       step=step)
            if backoff > 0:
                time.sleep(backoff)
            continue
        # healthy step
        step += 1
        report.steps_completed = step
        if mgr.last_good_tag != last_tag_before:
            report.snapshots_taken += 1
        consecutive_rewinds = 0

    try:
        mgr.wait()  # final durability barrier
    except SnapshotError as e:
        # same stance as mid-run: a save failure never outranks completed
        # healthy training — record it, the previous snapshot stays 'latest'
        explicit_failures[0] += 1
        logger.warning(f"run_resilient: final snapshot barrier reported: {e}")
    if guard is not None:
        if guard.requested:
            # the signal landed inside the FINAL step: honor it anyway so
            # the agent sees the preemption exit code, with state durable
            _preempt_exit(int(engine.global_steps))
        guard.uninstall()
    _sync_save_failures()
    report.steps_completed = int(engine.global_steps)
    return report


def snapshot_step(base_dir: str, tag: str) -> int:
    """The optimizer step a committed snapshot holds (manifest 'step')."""
    return int(read_manifest(base_dir, tag)["step"])
