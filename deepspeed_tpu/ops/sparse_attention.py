"""Block-sparse attention.

Reference: ``deepspeed/ops/sparse_attention/`` — Triton block-sparse
matmul/softmax (matmul.py:196, softmax.py:123) driven by ``SparsityConfig``
subclasses (sparsity_config.py: Dense/Fixed/Variable/BigBird/BSLongformer/
Local). Here the sparsity configs generate the SAME block layouts; the XLA
compute path below materializes the full score tensor and masks — correct
everywhere but O(S^2) memory, fine up to a few thousand tokens. For long
sequences, pair the layouts with ``sequence.fpdt.chunked_attention`` or the
Pallas splash-style kernel that SKIPS dead tiles (same layout contract) —
that upgrade is what makes the sparsity a compute win, not just a mask.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


# ----------------------------------------------------------- sparsity configs
@dataclasses.dataclass
class SparsityConfig:
    """Base (reference ``SparsityConfig`` sparsity_config.py): layout is a
    [num_heads, S/blk, S/blk] 0/1 block mask."""

    num_heads: int
    block: int = 16

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError

    def _empty(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block:
            raise ValueError(f"seq_len {seq_len} not divisible by block {self.block}")
        n = seq_len // self.block
        return np.zeros((self.num_heads, n, n), np.int8)


@dataclasses.dataclass
class DenseSparsityConfig(SparsityConfig):
    """All blocks active (reference ``DenseSparsityConfig``)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self._empty(seq_len)
        layout[:] = 1
        return layout


@dataclasses.dataclass
class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Sliding window of ``num_sliding_window_blocks`` (reference
    ``LocalSlidingWindowSparsityConfig``)."""

    num_sliding_window_blocks: int = 3

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self._empty(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks
        for i in range(n):
            lo = max(0, i - w + 1)
            layout[:, i, lo: i + 1] = 1
        return layout


@dataclasses.dataclass
class FixedSparsityConfig(SparsityConfig):
    """Local blocks + periodic global columns (reference
    ``FixedSparsityConfig``: num_local_blocks window, every
    num_global_blocks-th block attends globally)."""

    num_local_blocks: int = 4
    num_global_blocks: int = 1

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self._empty(seq_len)
        n = layout.shape[1]
        L = self.num_local_blocks
        for i in range(n):
            window = i // L * L
            layout[:, i, window: i + 1] = 1  # local band (causal)
            # global: the last block(s) of every previous local window,
            # clamped to <= i so the layout never marks future blocks
            for g in range(L - self.num_global_blocks, i, L):
                if 0 <= g <= i:
                    layout[:, i, g: min(g + self.num_global_blocks, i + 1)] = 1
        return layout


@dataclasses.dataclass
class BigBirdSparsityConfig(SparsityConfig):
    """Random + sliding window + global blocks (reference
    ``BigBirdSparsityConfig``)."""

    num_random_blocks: int = 1
    num_sliding_window_blocks: int = 3
    num_global_blocks: int = 1
    seed: int = 0

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self._empty(seq_len)
        n = layout.shape[1]
        rng = np.random.RandomState(self.seed)
        w = self.num_sliding_window_blocks
        g = self.num_global_blocks
        for h in range(self.num_heads):
            for i in range(n):
                lo = max(0, i - w + 1)
                layout[h, i, lo: i + 1] = 1  # window (causal part)
                layout[h, i, :min(g, i + 1)] = 1  # global prefix
                if i > 0:
                    picks = rng.choice(i + 1, size=min(self.num_random_blocks, i + 1), replace=False)
                    layout[h, i, picks] = 1
        return layout


def _global_ranges(starts, ends):
    """(start, end) block ranges from the reference's paired index lists
    (sparsity_config.py VariableSparsityConfig/BSLongformerSparsityConfig):
    with no ends, each start is a single-block range."""
    starts = tuple(starts)
    if ends is None:
        return tuple((s, s + 1) for s in starts)
    ends = tuple(ends)
    if len(starts) != len(ends):
        raise ValueError(
            f"global_block_indices length {len(starts)} != "
            f"global_block_end_indices length {len(ends)}")
    for s, e in zip(starts, ends):
        if e <= s:
            raise ValueError(f"global block range ({s}, {e}) is empty")
    return tuple(zip(starts, ends))


def _apply_global(layout: np.ndarray, ranges, horizontal: bool) -> None:
    """Global columns (every row attends the global blocks, causally clamped)
    plus optional horizontal rows (global blocks attend everything ≤ them)."""
    n = layout.shape[1]
    for s, e in ranges:
        for i in range(n):
            lo, hi = min(s, i + 1), min(e, i + 1)
            if hi > lo:
                layout[:, i, lo:hi] = 1
        if horizontal:
            for g in range(s, min(e, n)):
                layout[:, g, : g + 1] = 1


@dataclasses.dataclass
class VariableSparsityConfig(SparsityConfig):
    """Variable windows + global ranges + random blocks (reference
    ``VariableSparsityConfig`` sparsity_config.py:250, causal/unidirectional
    form): ``local_window_blocks`` sizes each successive local window (last
    entry repeats), ``global_block_indices``/``global_block_end_indices``
    mark global block ranges, ``num_random_blocks`` adds per-head random
    blocks. Pass tuples (the model config freezes dicts for hashability)."""

    num_random_blocks: int = 0
    local_window_blocks: tuple = (4,)
    global_block_indices: tuple = (0,)
    global_block_end_indices: Optional[tuple] = None
    horizontal_global_attention: bool = False
    seed: int = 0

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self._empty(seq_len)
        n = layout.shape[1]
        # local windows: consecutive spans of the given sizes; within a span
        # rows attend causally to the span's blocks
        sizes = list(self.local_window_blocks)
        start = 0
        while start < n:
            w = sizes[0] if len(sizes) == 1 else sizes.pop(0)
            end = min(start + w, n)
            for i in range(start, end):
                layout[:, i, start: i + 1] = 1
            start = end
        _apply_global(layout,
                      _global_ranges(self.global_block_indices,
                                     self.global_block_end_indices),
                      self.horizontal_global_attention)
        if self.num_random_blocks:
            rng = np.random.RandomState(self.seed)
            for h in range(self.num_heads):
                for i in range(1, n):
                    picks = rng.choice(i + 1, size=min(self.num_random_blocks, i + 1),
                                       replace=False)
                    layout[h, i, picks] = 1
        return layout


@dataclasses.dataclass
class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + global block ranges that both
    attend and are attended (reference ``BSLongformerSparsityConfig``
    sparsity_config.py:555, causal form)."""

    num_sliding_window_blocks: int = 3
    global_block_indices: tuple = (0,)
    global_block_end_indices: Optional[tuple] = None

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self._empty(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks
        for i in range(n):
            lo = max(0, i - w + 1)
            layout[:, i, lo: i + 1] = 1
        # longformer global tokens: vertical AND horizontal (causally clamped)
        _apply_global(layout,
                      _global_ranges(self.global_block_indices,
                                     self.global_block_end_indices),
                      horizontal=True)
        return layout


def get_sparsity_config(name: str, num_heads: int, block: int = 16, **kw) -> SparsityConfig:
    table = {
        "dense": DenseSparsityConfig,
        "fixed": FixedSparsityConfig,
        "bigbird": BigBirdSparsityConfig,
        "local": LocalSlidingWindowSparsityConfig,
        "sliding_window": LocalSlidingWindowSparsityConfig,
        "variable": VariableSparsityConfig,
        "bslongformer": BSLongformerSparsityConfig,
        "longformer": BSLongformerSparsityConfig,
    }
    if name not in table:
        raise ValueError(f"unknown sparsity config {name!r} (have {sorted(table)})")
    return table[name](num_heads=num_heads, block=block, **kw)


# ----------------------------------------------------------- compute path
def block_sparse_attention_dense(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, H, D] (no GQA here; repeat kv first if needed)
    v: jax.Array,
    layout: np.ndarray,  # [H, S/blk, S/blk]
    block: int,
    causal: bool = True,
    alibi_slopes: Optional[jax.Array] = None,  # [H] bloom-style biases
    pad_mask: Optional[jax.Array] = None,  # [B, S] 1=keep (key padding)
) -> jax.Array:
    """Dense-masked fallback + numerical baseline: materializes the full score
    tensor and masks (reference SparseSelfAttention math without the
    block-skipping). The Pallas kernel in ``ops/pallas/sparse_attention.py``
    skips dead tiles and is the dispatched path.
    """
    B, S, H, D = q.shape
    n = S // block
    if layout.shape != (H, n, n):
        raise ValueError(f"layout {layout.shape} != {(H, n, n)}")
    lay = jnp.asarray(layout, jnp.bool_)

    qs = q.astype(jnp.float32) * (D ** -0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qs, k.astype(jnp.float32),
                        precision=jax.lax.Precision.HIGHEST)
    if alibi_slopes is not None:
        # slopes * key position (HF bloom convention; softmax cancels the
        # per-row shift) — same form as ops/attention.causal_attention
        kpos = jnp.arange(S, dtype=jnp.float32)
        scores = scores + (alibi_slopes.astype(jnp.float32)[None, :, None, None]
                           * kpos[None, None, None, :])
    # expand block layout to token resolution: [H, S, S]
    tok_mask = jnp.repeat(jnp.repeat(lay, block, axis=1), block, axis=2)
    keep = tok_mask[None]
    if causal:
        keep = keep & jnp.tril(jnp.ones((S, S), bool))[None, None]
    if pad_mask is not None:
        keep = keep & pad_mask.astype(bool)[:, None, None, :]
    scores = jnp.where(keep, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    # rows with no active blocks (fully-padded rows, or holes in an odd
    # layout) must emit zeros, not a uniform average
    probs = jnp.where(keep.any(-1, keepdims=True), probs, 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)


def block_sparse_attention(q, k, v, layout, block: int, causal: bool = True,
                           impl: str = "auto", alibi_slopes=None,
                           pad_mask=None) -> jax.Array:
    """Block-sparse attention. On TPU, ``auto`` uses the tile-skipping Pallas
    kernel (compute/DMA scale with ``layout.sum()``, reference matmul.py:196);
    off-TPU it falls back to the dense-masked XLA path (the kernel would only
    run under the slow Pallas interpreter there). 'pallas'/'xla' force.

    ALiBi / key-padding compose on the XLA path (round-5; the reference's
    sparse attention composes them the same way through its masked softmax,
    softmax.py:123); fusing them into the tile-skipping kernels is a known
    follow-up, so ``auto`` routes those combos to XLA."""
    extras = alibi_slopes is not None or pad_mask is not None
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
        if extras:
            impl = "xla"  # documented auto-routing for unsupported-by-kernel combos
    elif impl == "pallas" and extras:
        raise NotImplementedError(
            "the tile-skipping Pallas kernels do not fuse alibi/padding yet; "
            "use impl='auto' (routes to xla) or impl='xla'")
    if impl == "xla":
        return block_sparse_attention_dense(q, k, v, layout, block, causal,
                                            alibi_slopes=alibi_slopes,
                                            pad_mask=pad_mask)
    from deepspeed_tpu.ops.pallas.sparse_attention import block_sparse_attention_pallas

    return block_sparse_attention_pallas(q, k, v, layout, block, causal)
