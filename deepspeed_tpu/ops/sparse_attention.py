"""Block-sparse attention.

Reference: ``deepspeed/ops/sparse_attention/`` — Triton block-sparse
matmul/softmax (matmul.py:196, softmax.py:123) driven by ``SparsityConfig``
subclasses (sparsity_config.py: Dense/Fixed/Variable/BigBird/BSLongformer/
Local). Here the sparsity configs generate the SAME block layouts; the XLA
compute path below materializes the full score tensor and masks — correct
everywhere but O(S^2) memory, fine up to a few thousand tokens. For long
sequences, pair the layouts with ``sequence.fpdt.chunked_attention`` or the
Pallas splash-style kernel that SKIPS dead tiles (same layout contract) —
that upgrade is what makes the sparsity a compute win, not just a mask.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


# ----------------------------------------------------------- sparsity configs
@dataclasses.dataclass
class SparsityConfig:
    """Base (reference ``SparsityConfig`` sparsity_config.py): layout is a
    [num_heads, S/blk, S/blk] 0/1 block mask."""

    num_heads: int
    block: int = 16

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError

    def _empty(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block:
            raise ValueError(f"seq_len {seq_len} not divisible by block {self.block}")
        n = seq_len // self.block
        return np.zeros((self.num_heads, n, n), np.int8)


@dataclasses.dataclass
class DenseSparsityConfig(SparsityConfig):
    """All blocks active (reference ``DenseSparsityConfig``)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self._empty(seq_len)
        layout[:] = 1
        return layout


@dataclasses.dataclass
class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Sliding window of ``num_sliding_window_blocks`` (reference
    ``LocalSlidingWindowSparsityConfig``)."""

    num_sliding_window_blocks: int = 3

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self._empty(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks
        for i in range(n):
            lo = max(0, i - w + 1)
            layout[:, i, lo: i + 1] = 1
        return layout


@dataclasses.dataclass
class FixedSparsityConfig(SparsityConfig):
    """Local blocks + periodic global columns (reference
    ``FixedSparsityConfig``: num_local_blocks window, every
    num_global_blocks-th block attends globally)."""

    num_local_blocks: int = 4
    num_global_blocks: int = 1

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self._empty(seq_len)
        n = layout.shape[1]
        L = self.num_local_blocks
        for i in range(n):
            window = i // L * L
            layout[:, i, window: i + 1] = 1  # local band (causal)
            # global: the last block(s) of every previous local window,
            # clamped to <= i so the layout never marks future blocks
            for g in range(L - self.num_global_blocks, i, L):
                if 0 <= g <= i:
                    layout[:, i, g: min(g + self.num_global_blocks, i + 1)] = 1
        return layout


@dataclasses.dataclass
class BigBirdSparsityConfig(SparsityConfig):
    """Random + sliding window + global blocks (reference
    ``BigBirdSparsityConfig``)."""

    num_random_blocks: int = 1
    num_sliding_window_blocks: int = 3
    num_global_blocks: int = 1
    seed: int = 0

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self._empty(seq_len)
        n = layout.shape[1]
        rng = np.random.RandomState(self.seed)
        w = self.num_sliding_window_blocks
        g = self.num_global_blocks
        for h in range(self.num_heads):
            for i in range(n):
                lo = max(0, i - w + 1)
                layout[h, i, lo: i + 1] = 1  # window (causal part)
                layout[h, i, :min(g, i + 1)] = 1  # global prefix
                if i > 0:
                    picks = rng.choice(i + 1, size=min(self.num_random_blocks, i + 1), replace=False)
                    layout[h, i, picks] = 1
        return layout


def get_sparsity_config(name: str, num_heads: int, block: int = 16, **kw) -> SparsityConfig:
    table = {
        "dense": DenseSparsityConfig,
        "fixed": FixedSparsityConfig,
        "bigbird": BigBirdSparsityConfig,
        "local": LocalSlidingWindowSparsityConfig,
        "sliding_window": LocalSlidingWindowSparsityConfig,
    }
    if name not in table:
        raise ValueError(f"unknown sparsity config {name!r} (have {sorted(table)})")
    return table[name](num_heads=num_heads, block=block, **kw)


# ----------------------------------------------------------- compute path
def block_sparse_attention_dense(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, H, D] (no GQA here; repeat kv first if needed)
    v: jax.Array,
    layout: np.ndarray,  # [H, S/blk, S/blk]
    block: int,
    causal: bool = True,
) -> jax.Array:
    """Dense-masked fallback + numerical baseline: materializes the full score
    tensor and masks (reference SparseSelfAttention math without the
    block-skipping). The Pallas kernel in ``ops/pallas/sparse_attention.py``
    skips dead tiles and is the dispatched path.
    """
    B, S, H, D = q.shape
    n = S // block
    if layout.shape != (H, n, n):
        raise ValueError(f"layout {layout.shape} != {(H, n, n)}")
    lay = jnp.asarray(layout, jnp.bool_)

    qs = q.astype(jnp.float32) * (D ** -0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qs, k.astype(jnp.float32),
                        precision=jax.lax.Precision.HIGHEST)
    # expand block layout to token resolution: [H, S, S]
    tok_mask = jnp.repeat(jnp.repeat(lay, block, axis=1), block, axis=2)
    keep = tok_mask[None]
    if causal:
        keep = keep & jnp.tril(jnp.ones((S, S), bool))[None, None]
    scores = jnp.where(keep, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    # rows with no active blocks (shouldn't happen with causal diag) guard:
    probs = jnp.where(keep.any(-1, keepdims=True), probs, 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)


def block_sparse_attention(q, k, v, layout, block: int, causal: bool = True,
                           impl: str = "auto") -> jax.Array:
    """Block-sparse attention. On TPU, ``auto`` uses the tile-skipping Pallas
    kernel (compute/DMA scale with ``layout.sum()``, reference matmul.py:196);
    off-TPU it falls back to the dense-masked XLA path (the kernel would only
    run under the slow Pallas interpreter there). 'pallas'/'xla' force."""
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "xla":
        return block_sparse_attention_dense(q, k, v, layout, block, causal)
    from deepspeed_tpu.ops.pallas.sparse_attention import block_sparse_attention_pallas

    return block_sparse_attention_pallas(q, k, v, layout, block, causal)
