"""Async IO handle (Python surface over the native pool).

Reference parity: ``deepspeed.ops.op_builder.AsyncIOBuilder`` +
``aio_handle`` (csrc/aio/py_lib/py_ds_aio.cpp:17-21 ``aio_read/aio_write``)
— submit reads/writes of numpy buffers against files, overlap with compute,
wait on handles. The buffers are plain numpy arrays (page-cache path); the
reference's pinned-memory variant maps to jax host buffers which already
live in pinned memory on TPU hosts.
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, Optional

import numpy as np

from deepspeed_tpu.ops.op_builder import AsyncIOBuilder
from deepspeed_tpu.utils.logging import logger


class AioHandle:
    """Thread-pooled async pread/pwrite (reference ``aio_handle``)."""

    def __init__(self, num_threads: int = 4, builder: Optional[AsyncIOBuilder] = None):
        self._lib = (builder or AsyncIOBuilder()).load()
        self._pool = self._lib.ds_aio_pool_create(num_threads)
        if not self._pool:
            raise RuntimeError("failed to create aio pool")
        self._live: Dict[int, np.ndarray] = {}  # req id -> buffer keep-alive

    # ------------------------------------------------------------ submit
    def _submit(self, path: str, buf: np.ndarray, offset: int, is_write: bool) -> int:
        if not buf.flags["C_CONTIGUOUS"]:
            raise ValueError("aio buffers must be C-contiguous")
        req = self._lib.ds_aio_submit(
            self._pool, os.fsencode(path),
            buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes, offset, int(is_write),
        )
        self._live[req] = buf  # keep the buffer alive until wait()
        return req

    def async_pwrite(self, buf: np.ndarray, path: str, offset: int = 0) -> int:
        return self._submit(path, buf, offset, is_write=True)

    def async_pread(self, buf: np.ndarray, path: str, offset: int = 0) -> int:
        return self._submit(path, buf, offset, is_write=False)

    # ------------------------------------------------------------ wait
    def wait(self, req: int) -> None:
        rc = self._lib.ds_aio_wait(self._pool, req)
        self._live.pop(req, None)
        if rc != 0:
            raise OSError(-rc if rc < 0 else rc, f"aio request {req} failed (rc={rc})")

    def wait_all(self) -> None:
        failures = self._lib.ds_aio_wait_all(self._pool)
        self._live.clear()
        if failures:
            raise OSError(f"{failures} aio requests failed")

    # ------------------------------------------------------------ sync sugar
    def pwrite(self, buf: np.ndarray, path: str, offset: int = 0) -> None:
        self.wait(self.async_pwrite(buf, path, offset))

    def pread(self, buf: np.ndarray, path: str, offset: int = 0) -> None:
        self.wait(self.async_pread(buf, path, offset))

    def close(self) -> None:
        if self._pool:
            self._lib.ds_aio_pool_destroy(self._pool)
            self._pool = None

    def __del__(self):  # pragma: no cover - gc timing
        try:
            self.close()
        except Exception:
            pass


def aio_available() -> bool:
    """Probe (the ``ds_report`` compatibility-matrix entry)."""
    try:
        return AsyncIOBuilder().is_compatible()
    except Exception:  # noqa: BLE001
        return False
