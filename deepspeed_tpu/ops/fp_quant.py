"""FP8 / int4 block quantization (the FP-quantizer family).

Reference analog: ``csrc/fp_quantizer/fp_quantize.{cpp,cu}`` (fp8/fp6
quantize-dequantize for weight-only-quant inference) and the int4 paths of
``csrc/quantization/pt_binding.cpp:372-401``.

TPU mapping:
  - fp8 uses the native ``float8_e4m3fn`` dtype (MXU-supported on v5e+) with
    per-block fp32 scales — no bit games needed
  - int4 is symmetric [-7, 7] with two values packed per uint8 along the
    flattened order
  - fp6 has no TPU dtype and its 6-bit packing buys 25% over fp8 at real
    unpack cost; fp8/int4 cover the reference's WOQ configurations

Both are one-shot (at weight load) on the quantize side; the dequantize side
runs inside the forward where XLA fuses the convert+scale into the consuming
matmul — a hand-written Pallas dequant would only replicate that fusion, so
these register as 'xla' impls under the same registry names a Pallas kernel
would use.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.registry import dispatch, register

DEFAULT_BLOCK = 2048
_FP8_MAX = 448.0  # float8_e4m3fn max normal


def _blocked(x: jax.Array, block_size: int):
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    block = min(block_size, n)
    nb = -(-n // block)
    if nb * block != n:
        flat = jnp.pad(flat, (0, nb * block - n))
    return flat.reshape(nb, block), n, block


@register("quantize_fp8", "xla")
def _quantize_fp8(x: jax.Array, block_size: int = DEFAULT_BLOCK):
    # THE shared fp8 block math (ops.quant) — same formula as the wire codec,
    # the fused collective hop kernel, and the quantized KV pool
    from deepspeed_tpu.ops.quant import fp8_block_math

    x2, n, _ = _blocked(x, block_size)
    q, scale = fp8_block_math(x2)
    return q.reshape(-1)[:n].reshape(x.shape), scale.reshape(-1)


@register("dequantize_fp8", "xla")
def _dequantize_fp8(values: jax.Array, scales: jax.Array, dtype=jnp.bfloat16,
                    block_size: int = DEFAULT_BLOCK):
    shape = values.shape
    flat = values.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    block = min(block_size, n)
    nb = scales.shape[0]
    if nb * block != n:
        flat = jnp.pad(flat, (0, nb * block - n))
    out = flat.reshape(nb, block) * scales.reshape(nb, 1)
    return out.reshape(-1)[:n].reshape(shape).astype(dtype)


@register("quantize_int4", "xla")
def _quantize_int4(x: jax.Array, block_size: int = DEFAULT_BLOCK):
    """-> (packed uint8 of shape [..., last/2], scales). Last dim must be even."""
    if x.shape[-1] % 2:
        raise ValueError(f"int4 packing needs an even trailing dim, got {x.shape}")
    x2, n, _ = _blocked(x, block_size)
    absmax = jnp.max(jnp.abs(x2), axis=-1, keepdims=True)
    scale = jnp.where(absmax == 0.0, 1.0, absmax / 7.0)
    q = jnp.clip(jnp.round(x2 / scale), -7, 7).astype(jnp.int8)
    flat = q.reshape(-1)[:n]
    # two's-complement nibbles: lo = even indices, hi = odd
    u = (flat.astype(jnp.uint8) & 0xF).reshape(-1, 2)
    packed = (u[:, 0] | (u[:, 1] << 4)).astype(jnp.uint8)
    return packed.reshape(x.shape[:-1] + (x.shape[-1] // 2,)), scale.reshape(-1)


@register("dequantize_int4", "xla")
def _dequantize_int4(packed: jax.Array, scales: jax.Array, dtype=jnp.bfloat16,
                     block_size: int = DEFAULT_BLOCK):
    shape = packed.shape[:-1] + (packed.shape[-1] * 2,)
    flat_p = packed.reshape(-1)
    lo = flat_p & 0xF
    hi = (flat_p >> 4) & 0xF
    nib = jnp.stack([lo, hi], axis=1).reshape(-1)  # original flat order
    # sign-extend 4-bit two's complement
    vals = jnp.where(nib >= 8, nib.astype(jnp.int32) - 16, nib.astype(jnp.int32)).astype(jnp.float32)
    n = vals.shape[0]
    block = min(block_size, n)
    nb = scales.shape[0]
    if nb * block != n:
        vals = jnp.pad(vals, (0, nb * block - n))
    out = vals.reshape(nb, block) * scales.reshape(nb, 1)
    return out.reshape(-1)[:n].reshape(shape).astype(dtype)


def quantize_fp8(x, block_size: int = DEFAULT_BLOCK, impl: str = "auto"):
    return dispatch("quantize_fp8", impl)(x, block_size=block_size)


def dequantize_fp8(values, scales, dtype=jnp.bfloat16, block_size: int = DEFAULT_BLOCK, impl: str = "auto"):
    return dispatch("dequantize_fp8", impl)(values, scales, dtype=dtype, block_size=block_size)


def quantize_int4(x, block_size: int = DEFAULT_BLOCK, impl: str = "auto"):
    return dispatch("quantize_int4", impl)(x, block_size=block_size)


def dequantize_int4(packed, scales, dtype=jnp.bfloat16, block_size: int = DEFAULT_BLOCK, impl: str = "auto"):
    return dispatch("dequantize_int4", impl)(packed, scales, dtype=dtype, block_size=block_size)
