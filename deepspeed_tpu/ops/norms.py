"""Normalization ops (fused RMS/LayerNorm).

Reference analog: ``csrc/transformer/inference/csrc/rms_norm.cu`` /
``layer_norm.cu`` and the v2 core_ops. XLA fuses the jnp fallback well; the
Pallas versions exist for the residual-add-fused variants where measurement
shows wins.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.registry import dispatch, register


@register("rms_norm", "xla")
def _xla_rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-5) -> jax.Array:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)).astype(dtype)


def rms_norm(x, scale, eps: float = 1e-5, impl: str = "auto"):
    return dispatch("rms_norm", impl)(x, scale, eps=eps)


@register("layer_norm", "xla")
def _xla_layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float = 1e-5) -> jax.Array:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)


def layer_norm(x, scale, bias, eps: float = 1e-5, impl: str = "auto"):
    return dispatch("layer_norm", impl)(x, scale, bias, eps=eps)
