"""Importable optimizer constructors (reference ``deepspeed.ops.adam``:
``FusedAdam`` ``ops/adam/fused_adam.py:18``, ``DeepSpeedCPUAdam``
``ops/adam/cpu_adam.py``; lamb analogs in ``ops/lamb``).

Reference users pass these class instances to ``deepspeed.initialize``;
here each is a thin factory returning the corresponding optax
``GradientTransformation`` (the engine accepts it via ``optimizer=``).
"Fused" is literal on TPU — the transformation is traced into the ONE
compiled train step; "CPU" placement is decided by
``zero_optimization.offload_optimizer``, exactly as the reference decides
it by which class you pick — so both spellings build the same math and the
config picks the backend.
"""

from __future__ import annotations

from typing import Any

from deepspeed_tpu.runtime.optimizers import get_optimizer


def _factory(name: str):
    def build(params: Any = None, lr: float = 1e-3, **kwargs) -> Any:
        kwargs = dict(kwargs)
        kwargs.pop("model_params", None)  # reference positional-compat
        tx, _ = get_optimizer(name, {"lr": lr, **kwargs})
        return tx

    build.__name__ = name
    return build


FusedAdam = _factory("adam")
DeepSpeedCPUAdam = _factory("adamw")  # reference CPUAdam defaults adamw_mode=True
FusedLamb = _factory("lamb")
OnebitAdam = _factory("onebitadam")
OnebitLamb = _factory("onebitlamb")
