"""deepspeed_tpu.ops: the kernel layer (op_builder + csrc analog).

Ops are registered per-backend (xla fallback, pallas TPU kernels) and resolved
through the registry at call time. Import order matters only in that the
pallas module registers its implementations on import; it degrades gracefully
off-TPU.
"""

from deepspeed_tpu.ops.registry import available_impls, dispatch, op_report, register
from deepspeed_tpu.ops.attention import causal_attention, evoformer_attention
from deepspeed_tpu.ops.norms import layer_norm, rms_norm
from deepspeed_tpu.ops.rope import rope
from deepspeed_tpu.ops.quant import dequantize_int8, quantize_int8

# Pallas kernels register themselves when importable (TPU or interpret mode).
try:  # pragma: no cover - exercised on TPU
    from deepspeed_tpu.ops.pallas import register_all as _register_pallas

    _register_pallas()
except ModuleNotFoundError:
    pass  # pallas kernel package not built yet
except Exception as _e:  # noqa: BLE001 - degrade to xla impls, but say so
    from deepspeed_tpu.utils.logging import logger as _logger

    _logger.warning(
        f"pallas kernel registration failed ({type(_e).__name__}: {_e}); "
        f"all ops fall back to XLA implementations"
    )
