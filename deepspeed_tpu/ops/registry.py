"""Kernel registry: named ops with per-backend implementations.

TPU-native analog of the reference's op_builder system (``op_builder/builder.py``
— 30 JIT-compiled CUDA extensions selected per accelerator). Here an "op" is a
named function with one or more implementations ('xla' — plain jnp the compiler
fuses; 'pallas' — a hand-written TPU kernel). Dispatch picks pallas on TPU when
registered, with 'xla' as the universal fallback (the reference's
``is_compatible()`` + fallback story, minus C++ compilation).
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Optional

import jax

from deepspeed_tpu.utils.logging import logger

_REGISTRY: Dict[str, Dict[str, Callable]] = {}


def register(op_name: str, impl: str) -> Callable:
    def deco(fn: Callable) -> Callable:
        _REGISTRY.setdefault(op_name, {})[impl] = fn
        return fn

    return deco


@functools.lru_cache(None)
def _default_backend() -> str:
    try:
        return jax.default_backend()
    except Exception:  # pragma: no cover
        return "cpu"


def available_impls(op_name: str) -> Dict[str, Callable]:
    return dict(_REGISTRY.get(op_name, {}))


def dispatch(op_name: str, impl: str = "auto") -> Callable:
    """Resolve an op implementation. 'auto' => pallas on TPU else xla."""
    impls = _REGISTRY.get(op_name)
    if not impls:
        raise KeyError(f"No implementations registered for op {op_name!r}")
    if impl == "auto":
        if _default_backend() == "tpu" and "pallas" in impls:
            return impls["pallas"]
        return impls.get("xla") or next(iter(impls.values()))
    if impl == "flash":  # model-config alias for the pallas attention path
        impl = "pallas" if "pallas" in impls else "xla"
    if impl not in impls:
        logger.warning(f"op {op_name!r}: impl {impl!r} unavailable, falling back to xla")
        return impls.get("xla") or next(iter(impls.values()))
    return impls[impl]


def op_report() -> Dict[str, list]:
    """ds_report analog: which impls exist per op."""
    return {name: sorted(impls) for name, impls in sorted(_REGISTRY.items())}
