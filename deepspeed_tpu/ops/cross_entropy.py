"""Fused LM-head + cross-entropy with chunked vocabulary.

TPU-native analog of the reference's fused softmax/logits kernels
(``csrc/transformer/inference/csrc/softmax.cu``, and the motivation behind
``sequence/fpdt_layer.py:1137 FPDT_LogitsLoss``): the [tokens, vocab] logits
matrix never materializes in HBM. The forward streams vocab chunks through a
``lax.scan`` (running max + sum-exp, exact logsumexp), and the custom VJP
recomputes each chunk's probabilities to accumulate dx/dE — trading one extra
pass of matmul FLOPs for O(tokens x chunk) peak memory instead of
O(tokens x vocab).

For the 125M bench (8192 tokens x 50304 vocab) this removes a 1.65 GB fp32
logits round-trip plus the softmax backward's equal-sized traffic, and is what
unlocks micro-batch 16+ within v5e HBM.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


def _chunked_embed(embed: jax.Array, n_chunks: int):
    """[V, D] -> ([n, C, D], per-chunk valid-column mask [n, C])."""
    V, D = embed.shape
    C = _cdiv(V, n_chunks)
    pad = n_chunks * C - V
    if pad:
        embed = jnp.pad(embed, ((0, pad), (0, 0)))
    cols = jnp.arange(n_chunks * C).reshape(n_chunks, C)
    return embed.reshape(n_chunks, C, D), cols < V


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_linear_cross_entropy(x, embed, labels, weight, n_chunks):
    loss, _ = _fce_fwd_impl(x, embed, labels, weight, n_chunks)
    return loss


def _fce_fwd_impl(x, embed, labels, weight, n_chunks):
    """x: [N, D] (any float dtype); embed: [V, D]; labels: [N] int;
    weight: [N] f32 per-token loss weight (0 for ignored tokens, typically
    1/num_valid for a mean). Returns (loss, logz[N])."""
    N, D = x.shape
    ech, colmask = _chunked_embed(embed, n_chunks)

    def chunk(carry, inp):
        m, s = carry
        e_c, keep = inp
        lg = jax.lax.dot_general(
            x, e_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [N, C]
        lg = jnp.where(keep[None, :], lg, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(lg, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(jnp.exp(lg - m_new[:, None]), axis=-1)
        return (m_new, s), None

    (m, s), _ = jax.lax.scan(
        chunk, (jnp.full((N,), -jnp.inf, jnp.float32), jnp.zeros((N,), jnp.float32)),
        (ech, colmask),
    )
    logz = m + jnp.log(s)

    gold_rows = jnp.take(embed, labels, axis=0)  # [N, D]
    gold = jnp.sum(x.astype(jnp.float32) * gold_rows.astype(jnp.float32), axis=-1)
    loss = jnp.sum((logz - gold) * weight)
    return loss, logz


def _fce_vjp_fwd(x, embed, labels, weight, n_chunks):
    loss, logz = _fce_fwd_impl(x, embed, labels, weight, n_chunks)
    return loss, (x, embed, labels, weight, logz)


def _fce_vjp_bwd(n_chunks, res, g):
    x, embed, labels, weight, logz = res
    N, D = x.shape
    V = embed.shape[0]
    ech, colmask = _chunked_embed(embed, n_chunks)
    w = (weight * g).astype(jnp.float32)  # [N] dL/dlogz per token

    xf = x.astype(jnp.float32)

    def chunk(dx_acc, inp):
        e_c, keep = inp
        lg = jax.lax.dot_general(
            x, e_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        p = jnp.where(keep[None, :], jnp.exp(lg - logz[:, None]), 0.0) * w[:, None]  # [N, C]
        pc = p.astype(x.dtype)
        dx_acc = dx_acc + jax.lax.dot_general(
            pc, e_c, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        de_c = jax.lax.dot_general(
            pc, x, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [C, D]
        return dx_acc, de_c

    dx, de_chunks = jax.lax.scan(chunk, jnp.zeros((N, D), jnp.float32), (ech, colmask))
    de = de_chunks.reshape(-1, D)[:V]

    # gold terms: dloss/dgold = -w
    gold_rows = jnp.take(embed, labels, axis=0).astype(jnp.float32)
    dx = dx - w[:, None] * gold_rows
    de = de.at[labels].add(-w[:, None] * xf)

    dlabels = None
    dweight = logz - jnp.sum(xf * gold_rows, axis=-1)  # dloss/dweight (rarely used)
    return dx.astype(x.dtype), de.astype(embed.dtype), dlabels, (dweight * g).astype(jnp.float32)


fused_linear_cross_entropy.defvjp(_fce_vjp_fwd, _fce_vjp_bwd)


def lm_head_cross_entropy(
    x: jax.Array,  # [B, S, D] final hidden states
    embed: jax.Array,  # [V, D] (tied embedding or lm_head.T)
    labels: jax.Array,  # [B, S] int, ignore_index marks ignored positions
    pad_mask: Optional[jax.Array] = None,  # [B, S] 1=keep
    ignore_index: int = -100,
    chunk_size: int = 8192,
) -> jax.Array:
    """Mean token cross entropy of ``x @ embed.T`` vs labels, fused + chunked.

    Matches ``models.transformer.cross_entropy_loss`` semantics (fp32 math,
    mean over non-ignored tokens) without materializing the logits.
    """
    B, S, D = x.shape
    V = embed.shape[0]
    valid = labels != ignore_index
    if pad_mask is not None:
        valid = valid & (pad_mask > 0)
    flat_valid = valid.reshape(-1)
    n_valid = jnp.maximum(jnp.sum(flat_valid.astype(jnp.float32)), 1.0)
    weight = flat_valid.astype(jnp.float32) / n_valid
    safe_labels = jnp.where(flat_valid, labels.reshape(-1), 0)
    n_chunks = max(1, _cdiv(V, chunk_size))
    return fused_linear_cross_entropy(
        x.reshape(-1, D), embed, safe_labels, weight, n_chunks
    )
