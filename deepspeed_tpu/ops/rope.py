"""Rotary position embedding op.

Reference analog: ``csrc/transformer/inference/csrc/apply_rotary_pos_emb.cu``
and the fused KV+RoPE ragged kernel (``linear_blocked_kv_rotary``). Half-split
(Llama/NeoX) convention: the head dim is split into two halves rotated against
each other.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.registry import dispatch, register


@register("rope", "xla")
def _xla_rope(
    x: jax.Array,  # [B, S, H, D]
    cos: jax.Array,  # [maxS, D/2]
    sin: jax.Array,  # [maxS, D/2]
    positions: jax.Array,  # [B, S] int
) -> jax.Array:
    dtype = x.dtype
    d2 = x.shape[-1] // 2
    cos_p = cos[positions][:, :, None, :].astype(jnp.float32)  # [B,S,1,D/2]
    sin_p = sin[positions][:, :, None, :].astype(jnp.float32)
    x1 = x[..., :d2].astype(jnp.float32)
    x2 = x[..., d2:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos_p - x2 * sin_p, x2 * cos_p + x1 * sin_p], axis=-1)
    return out.astype(dtype)


@register("rope_interleaved", "xla")
def _xla_rope_interleaved(
    x: jax.Array,  # [B, S, H, D]
    cos: jax.Array,  # [maxS, D/2]
    sin: jax.Array,  # [maxS, D/2]
    positions: jax.Array,  # [B, S] int
) -> jax.Array:
    """GPT-J/CodeGen convention: adjacent pairs (x[2i], x[2i+1]) rotate
    together (the reference kernel's rotate_every_two), vs the half-split
    rotation above (llama/neox rotate_half)."""
    dtype = x.dtype
    cos_p = cos[positions][:, :, None, :].astype(jnp.float32)  # [B,S,1,D/2]
    sin_p = sin[positions][:, :, None, :].astype(jnp.float32)
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    r1 = x1 * cos_p - x2 * sin_p
    r2 = x2 * cos_p + x1 * sin_p
    return jnp.stack([r1, r2], axis=-1).reshape(x.shape).astype(dtype)


def rope(x, cos, sin, positions, impl: str = "auto", interleaved: bool = False):
    return dispatch("rope_interleaved" if interleaved else "rope", impl)(
        x, cos, sin, positions)
