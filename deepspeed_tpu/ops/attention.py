"""Attention ops.

The XLA implementation is the universal fallback (fused by the compiler); the
Pallas flash kernel (``ops/pallas/flash_attention.py``) registers under the
same op name and wins dispatch on TPU. Reference analog: the inference/training
softmax+context CUDA kernels (``csrc/transformer/inference/csrc/softmax.cu``
etc.) and Triton flash variants (``ops/transformer/inference/triton/``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.registry import dispatch, register

_NEG_INF = -1e9  # mask fill well below any real score but finite for fp16 safety


@register("causal_attention", "xla")
def _xla_causal_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,  # [B, S, Hkv, D]
    mask: Optional[jax.Array] = None,  # [B, S] 1=keep (padding mask)
) -> jax.Array:
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    assert H % Hkv == 0, f"query heads {H} not a multiple of kv heads {Hkv}"
    G = H // Hkv

    qg = q.reshape(B, S, Hkv, G, D).astype(jnp.float32) * (D**-0.5)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k.astype(jnp.float32))

    causal = jnp.tril(jnp.ones((S, S), bool))
    keep = causal[None, None, None]
    if mask is not None:
        keep = keep & (mask[:, None, None, None, :] > 0)
    scores = jnp.where(keep, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v)
    return out.reshape(B, S, H, D)


def causal_attention(q, k, v, mask=None, impl: str = "auto"):
    return dispatch("causal_attention", impl)(q, k, v, mask=mask)
