"""Attention ops.

The XLA implementation is the universal fallback (fused by the compiler); the
Pallas flash kernel (``ops/pallas/flash_attention.py``) registers under the
same op name and wins dispatch on TPU. Reference analog: the inference/training
softmax+context CUDA kernels (``csrc/transformer/inference/csrc/softmax.cu``
etc.) and Triton flash variants (``ops/transformer/inference/triton/``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.registry import available_impls, dispatch, register

_NEG_INF = -1e9  # mask fill well below any real score but finite for fp16 safety


@register("causal_attention", "xla")
def _xla_causal_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,  # [B, S, Hkv, D]
    mask: Optional[jax.Array] = None,  # [B, S] 1=keep (padding mask)
    alibi_slopes: Optional[jax.Array] = None,  # [H] bloom-style score biases
    bias: Optional[jax.Array] = None,  # [H, S, S] or [B, H, S, S] additive
    causal: bool = True,
) -> jax.Array:
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    assert H % Hkv == 0, f"query heads {H} not a multiple of kv heads {Hkv}"
    G = H // Hkv

    qg = q.reshape(B, S, Hkv, G, D).astype(jnp.float32) * (D**-0.5)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k.astype(jnp.float32))

    if alibi_slopes is not None:
        # slopes * key-position; equal to slopes*(j-i) up to a per-row
        # constant, which softmax cancels (same convention as HF bloom, so
        # ingested checkpoints reproduce bit-comparable logits). XLA fuses
        # this broadcast into the masked add — no [H,S,S] buffer.
        kpos = jnp.arange(S, dtype=jnp.float32)
        scores = scores + (alibi_slopes.reshape(Hkv, G)[None, :, :, None, None]
                           * kpos[None, None, None, None, :])
    if bias is not None:
        # evoformer-style pair bias (reference csrc/deepspeed4science/
        # evoformer_attn): broadcast [.., H, S, S] onto the grouped layout
        b5 = bias if bias.ndim == 4 else bias[None]
        scores = scores + b5.reshape(b5.shape[0], Hkv, G, S, S).astype(jnp.float32)

    keep = None
    if causal:
        keep = jnp.tril(jnp.ones((S, S), bool))[None, None, None]
    if mask is not None:
        m = mask[:, None, None, None, :] > 0
        keep = m if keep is None else keep & m
    if keep is not None:
        scores = jnp.where(keep, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v)
    return out.reshape(B, S, H, D)


def resolves_to_flash(impl: str = "auto") -> bool:
    """Whether a model configured with this ``attn_impl`` would actually run
    the non-materializing Pallas flash kernel — i.e. the SAME resolution
    ``dispatch`` performs at call time, so memory estimates cannot diverge
    from what dispatches (e.g. 'flash' silently falls back to the
    materializing XLA attention when the kernel failed to import). 'sparse'
    and 'fpdt' branch before this op and materialize score-class workspace,
    so they are never flash for estimation purposes."""
    if impl in ("sparse", "fpdt"):
        return False
    pallas = available_impls("causal_attention").get("pallas")
    return pallas is not None and dispatch("causal_attention", impl) is pallas


def causal_attention(q, k, v, mask=None, impl: str = "auto",
                     alibi_slopes=None, bias=None, **kernel_kwargs):
    """Grouped-query causal attention with optional ALiBi slopes and additive
    pair bias. ALiBi is fused into the Pallas flash kernels (slope * column
    iota — no bias tiles) so bloom-style training keeps the flash path; the
    slopes are treated as NON-LEARNED positional constants there (their
    gradient is stopped — pass impl='xla' to differentiate learned slopes).
    Dense pair bias rides the XLA path (fully differentiable — the evoformer
    training case needs d_bias).

    kernel_kwargs (block_q / block_k / k_splits) are Pallas scheduling knobs
    with identical math — they are forwarded only when dispatch resolves to
    the pallas kernel and dropped on the XLA path (which has no blocking)."""
    if bias is not None:
        return _xla_causal_attention(q, k, v, mask=mask,
                                     alibi_slopes=alibi_slopes, bias=bias)
    fn = dispatch("causal_attention", impl)
    if kernel_kwargs and fn is not available_impls("causal_attention").get("pallas"):
        kernel_kwargs = {}
    if alibi_slopes is not None:
        return fn(q, k, v, mask=mask, alibi_slopes=alibi_slopes, **kernel_kwargs)
    return fn(q, k, v, mask=mask, **kernel_kwargs)


def evoformer_attention(q, k, v, pair_bias=None, mask=None):
    """DS4Science evoformer attention (reference
    ``csrc/deepspeed4science/evoformer_attn/`` — CUTLASS attention with
    broadcast bias for AlphaFold-family models): BIDIRECTIONAL attention over
    residue/MSA axes with an additive pair-representation bias and an optional
    keep-mask. Fully differentiable including d(pair_bias).

    q/k/v: [B, S, H, D]; pair_bias: [H, S, S] or [B, H, S, S]; mask: [B, S].
    """
    return _xla_causal_attention(q, k, v, mask=mask, bias=pair_bias, causal=False)
