"""JIT builder for native (C++) ops.

TPU-native analog of the reference op_builder (``op_builder/builder.py:117
OpBuilder.load`` — JIT-compiles csrc into a loadable extension the first time
an op is used, then caches). Differences by environment: no CUDA, no
pybind11 — plain ``g++ -shared -fPIC`` producing a C-ABI .so loaded with
ctypes. Sources live under ``csrc/`` exactly like the reference.
"""

from __future__ import annotations

import ctypes
import functools
import hashlib
import os
import subprocess
from pathlib import Path
from typing import List, Optional

from deepspeed_tpu.utils.logging import logger

_REPO_ROOT = Path(__file__).resolve().parents[2]
_DEFAULT_BUILD_DIR = _REPO_ROOT / "build" / "ops"


@functools.lru_cache(None)
def _compiler_fingerprint(cxx: str) -> str:
    """Path + version of the compiler, so an in-place toolchain upgrade
    invalidates cached .so files (path alone would not)."""
    from shutil import which

    path = which(cxx) or cxx
    try:
        ver = subprocess.run(
            [path, "--version"], capture_output=True, text=True, timeout=10
        ).stdout.splitlines()[0]
    except Exception:
        ver = "unknown"
    return f"{path}::{ver}"


class NativeOpBuilder:
    """Compile-and-load one native library (reference ``OpBuilder``)."""

    NAME: str = "base"
    SOURCES: List[str] = []
    EXTRA_FLAGS: List[str] = []

    def __init__(self, build_dir: Optional[str] = None):
        explicit = build_dir or os.environ.get("DS_TPU_BUILD_DIR")
        self.build_dir = Path(explicit) if explicit else Path(_DEFAULT_BUILD_DIR)
        # An explicitly requested dir must never be silently redirected — a
        # misconfiguration should surface, not land .so files in ~/.cache.
        self._explicit_build_dir = explicit is not None
        self._lib: Optional[ctypes.CDLL] = None

    def absolute_sources(self) -> List[Path]:
        # DS_TPU_CSRC_DIR lets a non-editable install (no csrc/ next to the
        # package) point at an unpacked source tree.
        root = Path(os.environ.get("DS_TPU_CSRC_DIR", _REPO_ROOT))
        return [root / s for s in self.SOURCES]

    def is_compatible(self) -> bool:
        """Reference ``is_compatible``: do we have a toolchain + sources?"""
        from shutil import which

        return which(self._cxx()) is not None and all(p.exists() for p in self.absolute_sources())

    @staticmethod
    def _cxx() -> str:
        return os.environ.get("CXX", "g++")

    def _so_path(self) -> Path:
        # content-hash sources + flags + platform/arch/compiler so edits
        # trigger rebuilds and a .so built on another OS/arch/toolchain never
        # satisfies the cache (a foreign binary would dlopen-fail with a
        # confusing 'invalid ELF header' instead of rebuilding)
        import platform
        import sys
        from shutil import which

        h = hashlib.sha256()
        for p in self.absolute_sources():
            h.update(p.read_bytes())
        h.update(" ".join(self.EXTRA_FLAGS).encode())
        h.update(f"{sys.platform}-{platform.machine()}".encode())
        h.update(_compiler_fingerprint(self._cxx()).encode())
        return self.build_dir / f"lib_{self.NAME}_{h.hexdigest()[:12]}.so"

    @staticmethod
    def _writable_dir(d: Path) -> bool:
        try:
            d.mkdir(parents=True, exist_ok=True)
        except OSError:
            return False
        return os.access(d, os.W_OK)

    def build(self) -> Path:
        missing = [str(p) for p in self.absolute_sources() if not p.exists()]
        if missing:
            raise RuntimeError(
                f"native op '{self.NAME}' sources not found: {missing}. "
                "Non-editable installs do not ship csrc/ — install with "
                "'pip install -e .' or set DS_TPU_CSRC_DIR to an unpacked "
                "source tree."
            )
        if not self._explicit_build_dir and not self._writable_dir(self.build_dir):
            # Default build dir can be read-only (checkout owned by another
            # user / read-only editable install) — fall back to a user cache
            # the way the reference falls back to TORCH_EXTENSIONS_DIR. An
            # EXPLICIT dir (arg or DS_TPU_BUILD_DIR) is honored or errors.
            self.build_dir = Path.home() / ".cache" / "deepspeed_tpu" / "ops"
        so = self._so_path()
        if so.exists():
            return so
        so.parent.mkdir(parents=True, exist_ok=True)
        cmd = [
            self._cxx(), "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
            *self.EXTRA_FLAGS,
            *[str(p) for p in self.absolute_sources()],
            "-o", str(so),
        ]
        logger.info(f"building native op '{self.NAME}': {' '.join(cmd)}")
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"native build of '{self.NAME}' failed:\n{e.stderr[-2000:]}"
            ) from e
        return so

    def load(self) -> ctypes.CDLL:
        """JIT build + dlopen (reference ``OpBuilder.load`` builder.py:523)."""
        if self._lib is None:
            self._lib = ctypes.CDLL(str(self.build()))
        return self._lib


class AsyncIOBuilder(NativeOpBuilder):
    """The DeepNVMe/AIO library (reference ``op_builder/async_io.py``)."""

    NAME = "aio"
    SOURCES = ["csrc/aio/ds_aio.cpp"]

    def load(self) -> ctypes.CDLL:
        lib = super().load()
        lib.ds_aio_pool_create.restype = ctypes.c_void_p
        lib.ds_aio_pool_create.argtypes = [ctypes.c_int]
        lib.ds_aio_pool_destroy.argtypes = [ctypes.c_void_p]
        lib.ds_aio_submit.restype = ctypes.c_long
        lib.ds_aio_submit.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
            ctypes.c_long, ctypes.c_long, ctypes.c_int,
        ]
        lib.ds_aio_wait.restype = ctypes.c_int
        lib.ds_aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.ds_aio_wait_all.restype = ctypes.c_int
        lib.ds_aio_wait_all.argtypes = [ctypes.c_void_p]
        return lib
