"""Block int8 quantize/dequantize — XLA fallback implementations.

Reference analog: ``deepspeed/ops/quantizer`` (``csrc/quantization``) symmetric
block quantization. The Pallas versions (``ops/pallas/quantizer.py``) register
under the same op names and win dispatch on TPU; these jnp versions are the
universal fallback and the numerical baseline in tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.registry import dispatch, register

DEFAULT_BLOCK = 2048
FP8_MAX = 448.0  # float8_e4m3fn max normal


# -- shared block math -------------------------------------------------------
# THE symmetric block-quant formulas, written on [nb, block] fp32 tiles so
# the same code runs as the XLA fallback, inside the Pallas quantizer kernel
# (ops/pallas/quantizer.py), in the wire codecs (collectives/codecs.py), and
# in the fused collective hop kernel's VMEM body
# (collectives/pallas_backend.py). One wire format everywhere.


def int8_block_math(x2: jax.Array):
    """``[nb, block] fp32 -> (int8 values [nb, block], fp32 scales [nb, 1])``
    — symmetric per-block absmax, nearest rounding."""
    absmax = jnp.max(jnp.abs(x2), axis=-1, keepdims=True)
    scale = jnp.where(absmax == 0.0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(x2 / scale), -127, 127).astype(jnp.int8)
    return q, scale


def int8_block_dequant(q2: jax.Array, scale: jax.Array) -> jax.Array:
    """Inverse of :func:`int8_block_math` (fp32 out; caller casts)."""
    return q2.astype(jnp.float32) * scale


def fp8_block_math(x2: jax.Array):
    """``[nb, block] fp32 -> (e4m3 values, fp32 scales [nb, 1])`` — absmax
    mapped onto the fp8 dynamic range (emulated via ml_dtypes off-TPU)."""
    absmax = jnp.max(jnp.abs(x2), axis=-1, keepdims=True)
    scale = jnp.where(absmax == 0.0, 1.0, absmax / FP8_MAX)
    q = (x2 / scale).astype(jnp.float8_e4m3fn)
    return q, scale


def fp8_block_dequant(q2: jax.Array, scale: jax.Array) -> jax.Array:
    return q2.astype(jnp.float32) * scale


@register("quantize_int8", "xla")
def _xla_quantize_int8(x: jax.Array, block_size: int = DEFAULT_BLOCK, stochastic: bool = False, seed: int = 0):
    del stochastic, seed  # nearest rounding only in the fallback
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    block = min(block_size, n)
    nb = -(-n // block)
    if nb * block != n:
        flat = jnp.pad(flat, (0, nb * block - n))
    q, scale = int8_block_math(flat.reshape(nb, block))
    return q.reshape(-1)[:n], scale.reshape(-1)


@register("dequantize_int8", "xla")
def _xla_dequantize_int8(values: jax.Array, scales: jax.Array, shape, dtype=jnp.bfloat16, block_size: int = DEFAULT_BLOCK):
    n = int(values.shape[0])
    block = min(block_size, n)
    nb = scales.shape[0]
    flat = values
    if nb * block != n:
        flat = jnp.pad(flat, (0, nb * block - n))
    v2 = flat.reshape(nb, block).astype(jnp.float32) * scales.reshape(nb, 1)
    return v2.reshape(-1)[:n].reshape(shape).astype(dtype)


def quantize_int8(x, block_size: int = DEFAULT_BLOCK, stochastic: bool = False, seed: int = 0, impl: str = "auto"):
    return dispatch("quantize_int8", impl)(x, block_size=block_size, stochastic=stochastic, seed=seed)


def dequantize_int8(values, scales, shape, dtype=jnp.bfloat16, block_size: int = DEFAULT_BLOCK, impl: str = "auto"):
    return dispatch("dequantize_int8", impl)(values, scales, shape, dtype=dtype, block_size=block_size)
