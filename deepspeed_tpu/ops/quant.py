"""Block int8 quantize/dequantize — XLA fallback implementations.

Reference analog: ``deepspeed/ops/quantizer`` (``csrc/quantization``) symmetric
block quantization. The Pallas versions (``ops/pallas/quantizer.py``) register
under the same op names and win dispatch on TPU; these jnp versions are the
universal fallback and the numerical baseline in tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.registry import dispatch, register

DEFAULT_BLOCK = 2048


@register("quantize_int8", "xla")
def _xla_quantize_int8(x: jax.Array, block_size: int = DEFAULT_BLOCK, stochastic: bool = False, seed: int = 0):
    del stochastic, seed  # nearest rounding only in the fallback
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    block = min(block_size, n)
    nb = -(-n // block)
    if nb * block != n:
        flat = jnp.pad(flat, (0, nb * block - n))
    x2 = flat.reshape(nb, block)
    absmax = jnp.max(jnp.abs(x2), axis=-1, keepdims=True)
    scale = jnp.where(absmax == 0.0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(x2 / scale), -127, 127).astype(jnp.int8)
    return q.reshape(-1)[:n], scale.reshape(-1)


@register("dequantize_int8", "xla")
def _xla_dequantize_int8(values: jax.Array, scales: jax.Array, shape, dtype=jnp.bfloat16, block_size: int = DEFAULT_BLOCK):
    n = int(values.shape[0])
    block = min(block_size, n)
    nb = scales.shape[0]
    flat = values
    if nb * block != n:
        flat = jnp.pad(flat, (0, nb * block - n))
    v2 = flat.reshape(nb, block).astype(jnp.float32) * scales.reshape(nb, 1)
    return v2.reshape(-1)[:n].reshape(shape).astype(dtype)


def quantize_int8(x, block_size: int = DEFAULT_BLOCK, stochastic: bool = False, seed: int = 0, impl: str = "auto"):
    return dispatch("quantize_int8", impl)(x, block_size=block_size, stochastic=stochastic, seed=seed)


def dequantize_int8(values, scales, shape, dtype=jnp.bfloat16, block_size: int = DEFAULT_BLOCK, impl: str = "auto"):
    return dispatch("dequantize_int8", impl)(values, scales, shape, dtype=dtype, block_size=block_size)
