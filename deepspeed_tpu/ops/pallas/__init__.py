"""Pallas TPU kernels.

``register_all()`` imports each kernel module, which registers its 'pallas'
implementations in the op registry (``deepspeed_tpu/ops/registry.py``). Each
module is the TPU-native answer to a CUDA kernel family in the reference
(cited per-module). On non-TPU backends the kernels run in interpreter mode
so the same code paths are exercised by the CPU test harness.
"""


def register_all() -> None:
    from deepspeed_tpu.ops.pallas import flash_attention  # noqa: F401
    from deepspeed_tpu.ops.pallas import norms  # noqa: F401
    from deepspeed_tpu.ops.pallas import quantizer  # noqa: F401
