"""Paged (block-table) flash-decode attention as a Pallas TPU kernel.

TPU-native analog of FastGen's ``blocked_flash`` kernel
(``inference/v2/kernels/ragged_ops/blocked_flash/`` — paged attention over a
blocked KV cache) — the kernel the reference's 2.3x-vs-vLLM claim lives in
(``blogs/deepspeed-fastgen/README.md:28``).

Design: the KV pool stays in HBM (``memory_space=ANY``); the block table rides
scalar prefetch so the kernel issues manual DMAs of exactly the pages each
sequence owns — no dense gather ever materializes. Grid is
``(rows, kv_heads, page_chunks)``; each step copies ``pages_per_block`` pages
into VMEM, runs one online-softmax update for all query heads in the GQA
group, and page-chunks past a row's live length are skipped entirely
(compute AND DMA — the guard wraps the copies).

Against the XLA fallback (gather pages to dense then masked attention) this
removes the gathered-copy write+read and the [rows, tokens] fp32 score
round-trip: decode becomes one streaming read of the live KV pages, which is
the bandwidth floor for paged attention.

The KV-insert+RoPE side of the reference's kernel pair
(``linear_blocked_kv_rotary``) stays an XLA scatter: ``.at[slots].set`` with
the RoPE rotation feeding it fuses into a single scatter program under XLA,
so a hand kernel buys nothing there.

Quantized KV pools (int8/e4m3 values + per-(slot, head) fp32 scales — see
``inference/paged.py``): the scale pages DMA alongside the value pages and
dequantization happens on the VMEM tiles right after the block load, so the
full-precision pool never materializes anywhere — HBM holds the quantized
bytes, VMEM holds one dequantized page-chunk at a time.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.utils.compat import tpu_compiler_params

from deepspeed_tpu.ops.registry import register

_NEG_INF = float(jnp.finfo(jnp.float32).min)
_LANES = 8
DEFAULT_PAGES_PER_BLOCK = 8


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


def _decode_kernel(bt_ref, ap_ref, *refs, bs, ppcb, alibi=False, quantized=False):
    refs = list(refs)
    q_ref, qpos_ref = refs.pop(0), refs.pop(0)
    slopes_ref = refs.pop(0) if alibi else None
    (k_hbm, v_hbm) = refs.pop(0), refs.pop(0)
    ks_hbm = vs_hbm = None
    if quantized:
        ks_hbm, vs_hbm = refs.pop(0), refs.pop(0)
    o_ref = refs.pop(0)
    kbuf, vbuf = refs.pop(0), refs.pop(0)
    ksbuf = vsbuf = None
    if quantized:
        ksbuf, vsbuf = refs.pop(0), refs.pop(0)
    acc_ref, m_ref, l_ref, sem_k, sem_v = refs
    n = pl.program_id(0)
    kh = pl.program_id(1)
    pc = pl.program_id(2)
    npc = pl.num_programs(2)

    @pl.when(pc == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _compute():
        copies = []
        for i in range(ppcb):
            page = bt_ref[n, pc * ppcb + i]
            copies.append(pltpu.make_async_copy(
                k_hbm.at[pl.ds(page * bs, bs), pl.ds(kh, 1)],
                kbuf.at[pl.ds(i * bs, bs)], sem_k))
            copies.append(pltpu.make_async_copy(
                v_hbm.at[pl.ds(page * bs, bs), pl.ds(kh, 1)],
                vbuf.at[pl.ds(i * bs, bs)], sem_v))
            if quantized:
                # the per-(slot, head) scales ride the same page DMAs — the
                # fp-precision pool never exists anywhere, the dequant below
                # happens on the VMEM tiles right after the block load
                copies.append(pltpu.make_async_copy(
                    ks_hbm.at[pl.ds(page * bs, bs), pl.ds(kh, 1)],
                    ksbuf.at[pl.ds(i * bs, bs)], sem_k))
                copies.append(pltpu.make_async_copy(
                    vs_hbm.at[pl.ds(page * bs, bs), pl.ds(kh, 1)],
                    vsbuf.at[pl.ds(i * bs, bs)], sem_v))
        for c in copies:
            c.start()
        for c in copies:
            c.wait()

        q = q_ref[0, 0]  # [Cg, hd] (pre-scaled)
        if quantized:
            # fused block-load dequant: int8/e4m3 tile * its per-slot scale,
            # cast to the compute dtype (matches the XLA fallback's math)
            k = (kbuf[:, 0].astype(jnp.float32) * ksbuf[:, 0][:, None]).astype(q_ref.dtype)
            v = (vbuf[:, 0].astype(jnp.float32) * vsbuf[:, 0][:, None]).astype(q_ref.dtype)
        else:
            k = kbuf[:, 0]  # [ppcb*bs, hd]
            v = vbuf[:, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [Cg, T]
        # causality over SEQUENCE positions: token j of this page-chunk is at
        # global position pc*ppcb*bs + j; visible iff <= the query's position
        j = pc * ppcb * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if alibi:
            # bloom convention slope * key-position (slot index == position);
            # slopes arrive row-aligned with the (c, g) query layout
            s = s + slopes_ref[0][:, None] * j.astype(jnp.float32)
        qpos = qpos_ref[0]  # [Cg]
        s = jnp.where(j <= qpos[:, None], s, _NEG_INF)

        m_prev = jnp.max(m_ref[:], axis=-1, keepdims=True)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        m_safe = jnp.where(m_cur == _NEG_INF, 0.0, m_cur)
        p = jnp.exp(s - m_safe)
        alpha = jnp.where(m_prev == _NEG_INF, 0.0, jnp.exp(m_prev - m_safe))
        l_prev = jnp.max(l_ref[:], axis=-1, keepdims=True)
        l_ref[:] = jnp.broadcast_to(alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
        m_ref[:] = jnp.broadcast_to(m_cur, m_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    # skip page-chunks entirely beyond the row's live pages (guard wraps the
    # DMAs too — dead pages cost no bandwidth)
    pl.when(pc * ppcb < ap_ref[n])(_compute)

    @pl.when(pc == npc - 1)
    def _finalize():
        l = jnp.max(l_ref[:], axis=-1, keepdims=True)
        o_ref[0, 0] = (acc_ref[:] / jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


@register("paged_attention", "pallas")
def flash_decode_paged(
    q: jax.Array,  # [N, C, H, hd]
    pool_k_l: jax.Array,  # [S_flat, kvH, hd]
    pool_v_l: jax.Array,
    block_tables: jax.Array,  # [N, P] int32
    q_positions: jax.Array,  # [N, C] int32
    block_size: int,
    new_lens: jax.Array = None,  # [N] live tokens (for page skipping)
    pages_per_block: int = DEFAULT_PAGES_PER_BLOCK,
    alibi_slopes: jax.Array = None,  # [H] fp32 (bloom ALiBi, fused in-kernel)
    k_scale: jax.Array = None,  # [S_flat, kvH, 1] fp32 — quantized pool scales
    v_scale: jax.Array = None,
) -> jax.Array:
    N, C, H, hd = q.shape
    kvH = pool_k_l.shape[1]
    G = H // kvH
    P = block_tables.shape[1]
    bs = block_size
    ppcb = min(pages_per_block, P)
    Pp = _cdiv(P, ppcb) * ppcb
    if Pp != P:
        block_tables = jnp.pad(block_tables, ((0, 0), (0, Pp - P)))
    npc = Pp // ppcb

    Cg = C * G
    Cgp = _cdiv(Cg, _LANES) * _LANES

    # [N, kvH, Cg, hd] query layout; rows are (c, g) pairs, padded to sublanes
    scale = jnp.asarray(hd ** -0.5, q.dtype)
    q5 = (q * scale).reshape(N, C, kvH, G, hd).transpose(0, 2, 1, 3, 4).reshape(N, kvH, Cg, hd)
    qpos_rows = jnp.broadcast_to(q_positions[:, :, None], (N, C, G)).reshape(N, Cg)
    if Cgp != Cg:
        q5 = jnp.pad(q5, ((0, 0), (0, 0), (0, Cgp - Cg), (0, 0)))
        # padded rows see nothing (position -1 masks every token)
        qpos_rows = jnp.pad(qpos_rows, ((0, 0), (0, Cgp - Cg)), constant_values=-1)

    # live pages per row: positions are ascending within the live prefix
    if new_lens is None:
        max_pos = jnp.max(q_positions, axis=1)
    else:
        last = jnp.maximum(new_lens - 1, 0)
        max_pos = jnp.take_along_axis(q_positions, last[:, None], axis=1)[:, 0]
    active_pages = (max_pos + 1 + bs - 1) // bs  # [N]

    alibi = alibi_slopes is not None
    extra = ()
    in_specs = [
        pl.BlockSpec((1, 1, Cgp, hd), lambda n, kh, pc, bt, ap: (n, kh, 0, 0)),
        pl.BlockSpec((1, Cgp), lambda n, kh, pc, bt, ap: (n, 0)),
    ]
    if alibi:
        # row-aligned slopes: row (c, g) of kv head kh uses slope[kh*G + g]
        srows = jnp.broadcast_to(
            alibi_slopes.astype(jnp.float32).reshape(kvH, 1, G), (kvH, C, G)
        ).reshape(kvH, Cg)
        if Cgp != Cg:
            srows = jnp.pad(srows, ((0, 0), (0, Cgp - Cg)))
        extra = (srows,)
        in_specs.append(pl.BlockSpec((1, Cgp), lambda n, kh, pc, bt, ap: (kh, 0)))
    in_specs += [
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    quantized = k_scale is not None
    pools = (pool_k_l, pool_v_l)
    scratch = [
        pltpu.VMEM((ppcb * bs, 1, hd), pool_k_l.dtype),
        pltpu.VMEM((ppcb * bs, 1, hd), pool_v_l.dtype),
    ]
    if quantized:
        # scales stream with their pages: [S_flat, kvH] fp32 in HBM, [bs, 1]
        # slices DMA'd next to each value page
        in_specs += [
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ]
        pools = pools + (k_scale.reshape(k_scale.shape[0], kvH),
                         v_scale.reshape(v_scale.shape[0], kvH))
        scratch += [
            pltpu.VMEM((ppcb * bs, 1), jnp.float32),
            pltpu.VMEM((ppcb * bs, 1), jnp.float32),
        ]

    kernel = functools.partial(_decode_kernel, bs=bs, ppcb=ppcb, alibi=alibi,
                               quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # block_tables, active_pages
            grid=(N, kvH, npc),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, Cgp, hd), lambda n, kh, pc, bt, ap: (n, kh, 0, 0)),
            scratch_shapes=scratch + [
                pltpu.VMEM((Cgp, hd), jnp.float32),
                pltpu.VMEM((Cgp, _LANES), jnp.float32),
                pltpu.VMEM((Cgp, _LANES), jnp.float32),
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((N, kvH, Cgp, hd), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(block_tables, active_pages, q5, qpos_rows, *extra, *pools)

    out = out[:, :, :Cg].reshape(N, kvH, C, G, hd).transpose(0, 2, 1, 3, 4)
    return out.reshape(N, C, H, hd)
