"""Block int8 quantize/dequantize Pallas kernels.

TPU-native answer to the reference's quantizer family
(``csrc/quantization/pt_binding.cpp`` — sym/asym block quant, stochastic
rounding, swizzled quant for ZeRO++ qgZ). Symmetric per-block absmax int8 is
the workhorse: it backs quantized weight allgather (qwZ analog), quantized
gradient reduction (qgZ analog — quantize → all_to_all → dequant-reduce
composed in shard_map, see parallel/quant_collectives), and weight-only-quant
inference.

Layout: the flat input is reshaped to [num_blocks, block_size]; each block
gets one f32 scale. Stochastic rounding uses the on-core PRNG
(``pltpu.prng_random_bits``) — deterministic nearest-rounding elsewhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.registry import register

DEFAULT_BLOCK = 2048
_ROWS_PER_STEP = 64


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


from deepspeed_tpu.utils.compat import shape_dtype_struct as _sds


def _quant_kernel(x_ref, vals_ref, scales_ref):
    from deepspeed_tpu.ops.quant import int8_block_math

    q, scale = int8_block_math(x_ref[:].astype(jnp.float32))  # [rows, block]
    vals_ref[:] = q
    scales_ref[:] = scale.astype(jnp.float32)


def _quant_kernel_stochastic(seed_ref, x_ref, vals_ref, scales_ref):
    pltpu.prng_seed(seed_ref[0])
    x = x_ref[:].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(absmax == 0.0, 1.0, absmax / 127.0)
    scaled = x / scale
    # stochastic rounding: add uniform [0,1) then floor
    bits = pltpu.prng_random_bits(scaled.shape)
    u = (bits >> 8).astype(jnp.float32) * (1.0 / (1 << 24))
    q = jnp.clip(jnp.floor(scaled + u), -127, 127)
    vals_ref[:] = q.astype(jnp.int8)
    scales_ref[:] = scale.astype(jnp.float32)


def _dequant_kernel(vals_ref, scales_ref, o_ref, *, dtype):
    o_ref[:] = (vals_ref[:].astype(jnp.float32) * scales_ref[:]).astype(dtype)


@register("quantize_int8", "pallas")
def pallas_quantize_int8(x: jax.Array, block_size: int = DEFAULT_BLOCK, stochastic: bool = False, seed: int = 0):
    """Flat symmetric int8 block quantization. Returns (values int8 [N], scales f32 [nb])."""
    flat = x.reshape(-1)
    n = flat.shape[0]
    block = min(block_size, n)
    nb = -(-n // block)
    if nb * block != n:
        flat = jnp.pad(flat, (0, nb * block - n))
    x2 = flat.reshape(nb, block)
    rows = min(_ROWS_PER_STEP, nb)

    if stochastic and not _interpret():
        seed_arr = jnp.asarray([seed], jnp.int32)
        vals, scales = pl.pallas_call(
            _quant_kernel_stochastic,
            grid=(pl.cdiv(nb, rows),),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((rows, block), lambda i: (i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((rows, block), lambda i: (i, 0)),
                pl.BlockSpec((rows, 1), lambda i: (i, 0)),
            ],
            out_shape=[
                _sds((nb, block), jnp.int8, x2),
                _sds((nb, 1), jnp.float32, x2),
            ],
        )(seed_arr, x2)
    else:
        vals, scales = pl.pallas_call(
            _quant_kernel,
            grid=(pl.cdiv(nb, rows),),
            in_specs=[pl.BlockSpec((rows, block), lambda i: (i, 0))],
            out_specs=[
                pl.BlockSpec((rows, block), lambda i: (i, 0)),
                pl.BlockSpec((rows, 1), lambda i: (i, 0)),
            ],
            out_shape=[
                _sds((nb, block), jnp.int8, x2),
                _sds((nb, 1), jnp.float32, x2),
            ],
            interpret=_interpret(),
        )(x2)
    return vals.reshape(-1)[:n], scales.reshape(-1)


@register("dequantize_int8", "pallas")
def pallas_dequantize_int8(values: jax.Array, scales: jax.Array, shape, dtype=jnp.bfloat16, block_size: int = DEFAULT_BLOCK):
    n = int(values.shape[0])
    block = min(block_size, n)
    nb = scales.shape[0]
    flat = values
    if nb * block != n:
        flat = jnp.pad(flat, (0, nb * block - n))
    v2 = flat.reshape(nb, block)
    rows = min(_ROWS_PER_STEP, nb)
    out = pl.pallas_call(
        functools.partial(_dequant_kernel, dtype=dtype),
        grid=(pl.cdiv(nb, rows),),
        in_specs=[
            pl.BlockSpec((rows, block), lambda i: (i, 0)),
            pl.BlockSpec((rows, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((rows, block), lambda i: (i, 0)),
        out_shape=_sds((nb, block), dtype, v2, scales),
        interpret=_interpret(),
    )(v2, scales.reshape(nb, 1))
    return out.reshape(-1)[:n].reshape(shape)
