"""Block-sparse attention Pallas kernel: dead (qblk, kblk) tiles are SKIPPED.

Reference analog: ``deepspeed/ops/sparse_attention/matmul.py:196`` — the
Triton sdd/dsd block-skipping matmuls that make BigBird/Longformer layouts a
real compute/memory win rather than a mask.

Design: the (static numpy) block layout compiles into per-(head, qblock)
active-column lists. The grid's last axis runs only to ``max_active`` columns
(not n_blocks), the column index rides scalar prefetch into the K/V BlockSpec
index maps, and rows with fewer active columns guard the tail — so both the
DMA and the MXU work scale with ``layout.sum()`` instead of ``n^2``. Online
softmax accumulates across a row's active tiles exactly as in the dense flash
kernel.

Backward (reference ``matmul.py:196`` / ``softmax.py:123`` — the Triton
sdd/dsd kernels have backward passes, so BigBird/Longformer layouts TRAIN
sparse): two tile-skipping kernels sharing the forward's layout-list
contract. ``dq`` re-walks each query row's active columns (same ``cols``/
``ncols`` lists, p recomputed from the forward's saved logsumexp); ``dk/dv``
walk the TRANSPOSED lists (per key column, its active query rows) so each
key tile's gradients accumulate over exactly the live tiles that touched it.
Scores are never materialized beyond one [block, block] VMEM tile — the
backward's HBM residency is O(S*D + S) (dq/dk/dv + lse/delta), not O(S^2).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.utils.compat import tpu_compiler_params

_NEG_INF = float(jnp.finfo(jnp.float32).min)
_LANES = 8


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def layout_to_lists(layout: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """[H, n, n] 0/1 -> (cols [H, n, A], ncols [H, n]); padded entries repeat
    the row's last active column (their compute is guarded off, and a valid
    index keeps the prefetched DMA in range)."""
    H, n, _ = layout.shape
    ncols = layout.sum(-1).astype(np.int32)
    A = max(1, int(ncols.max()))
    cols = np.zeros((H, n, A), np.int32)
    for h in range(H):
        for i in range(n):
            act = np.nonzero(layout[h, i])[0]
            if act.size:
                cols[h, i, :act.size] = act
                cols[h, i, act.size:] = act[-1]
    return cols, ncols


def layout_to_lists_t(layout: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Transposed lists for the dk/dv walk: [H, n, n] 0/1 ->
    (rows [H, n, Ar], nrows [H, n]) — for key column ki, the active query
    rows. Padding repeats the column's last active row (guarded off)."""
    return layout_to_lists(np.swapaxes(layout, -1, -2))


def _score_tile(q_ref, k_ref, row_blk, col_blk, block, causal):
    """One [block, block] fp32 score tile with the shared causal diagonal
    mask — the single masking definition all three kernels (fwd/dq/dkv) use,
    so forward and backward provably mask identically."""
    s = jax.lax.dot_general(q_ref[0, 0], k_ref[0, 0], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if causal:
        # only the diagonal tile needs the iota mask
        rows = row_blk * block + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = col_blk * block + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where((col_blk != row_blk) | (cols <= rows), s, _NEG_INF)
    return s


def _sparse_fwd_kernel(cols_ref, ncols_ref, q_ref, k_ref, v_ref, o_ref,
                       lse_ref, acc_ref, m_ref, l_ref, *, block, causal):
    h = pl.program_id(1)
    qi = pl.program_id(2)
    j = pl.program_id(3)
    A = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    kj = cols_ref[h, qi, j]
    live = j < ncols_ref[h, qi]
    if causal:
        live = live & (kj <= qi)

    def _compute():
        # q pre-scaled by 1/sqrt(D)
        s = _score_tile(q_ref, k_ref, qi, kj, block, causal)

        m_prev = jnp.max(m_ref[:], axis=-1, keepdims=True)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        m_safe = jnp.where(m_cur == _NEG_INF, 0.0, m_cur)
        p = jnp.exp(s - m_safe)
        alpha = jnp.where(m_prev == _NEG_INF, 0.0, jnp.exp(m_prev - m_safe))
        l_prev = jnp.max(l_ref[:], axis=-1, keepdims=True)
        l_ref[:] = jnp.broadcast_to(alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
        m_ref[:] = jnp.broadcast_to(m_cur, m_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    pl.when(live)(_compute)

    @pl.when(j == A - 1)
    def _finalize():
        l = jnp.max(l_ref[:], axis=-1, keepdims=True)
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        m = jnp.max(m_ref[:], axis=-1, keepdims=True)
        # base-e logsumexp per row; rows with no live tile get -inf (their
        # output is 0 and the backward walks no tiles for them)
        lse = jnp.where(l == 0.0, _NEG_INF, m + jnp.log(l_safe))
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _sparse_fwd(q, k, v, cols, ncols, block, causal):
    """q/k/v: [B, H, S, D] (q pre-scaled). Returns (out [B,H,S,D], lse)."""
    B, H, S, D = q.shape
    n = S // block
    A = cols.shape[-1]

    out, lse = pl.pallas_call(
        functools.partial(_sparse_fwd_kernel, block=block, causal=causal),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # cols, ncols
            grid=(B, H, n, A),
            in_specs=[
                pl.BlockSpec((1, 1, block, D), lambda b, h, qi, j, cols, ncols: (b, h, qi, 0)),
                pl.BlockSpec((1, 1, block, D), lambda b, h, qi, j, cols, ncols: (b, h, cols[h, qi, j], 0)),
                pl.BlockSpec((1, 1, block, D), lambda b, h, qi, j, cols, ncols: (b, h, cols[h, qi, j], 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, block, D), lambda b, h, qi, j, cols, ncols: (b, h, qi, 0)),
                pl.BlockSpec((1, 1, block, _LANES), lambda b, h, qi, j, cols, ncols: (b, h, qi, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block, D), jnp.float32),
                pltpu.VMEM((block, _LANES), jnp.float32),
                pltpu.VMEM((block, _LANES), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, _LANES), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(cols, ncols, q, k, v)
    return out, lse


def _sparse_dq_kernel(cols_ref, ncols_ref, q_ref, k_ref, v_ref, do_ref,
                      lse_ref, delta_ref, dq_ref, acc_ref, *, block, causal):
    h = pl.program_id(1)
    qi = pl.program_id(2)
    j = pl.program_id(3)
    A = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    kj = cols_ref[h, qi, j]
    live = j < ncols_ref[h, qi]
    if causal:
        live = live & (kj <= qi)

    def _compute():
        s = _score_tile(q_ref, k_ref, qi, kj, block, causal)  # q pre-scaled
        k = k_ref[0, 0]
        lse = jnp.max(lse_ref[0, 0], axis=-1, keepdims=True)
        p = jnp.exp(s - jnp.where(lse == _NEG_INF, 0.0, lse))
        dp = jax.lax.dot_general(do_ref[0, 0], v_ref[0, 0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - jnp.max(delta_ref[0, 0], axis=-1, keepdims=True))
        acc_ref[:] += jax.lax.dot_general(ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)

    pl.when(live)(_compute)

    @pl.when(j == A - 1)
    def _finalize():
        dq_ref[0, 0] = acc_ref[:].astype(dq_ref.dtype)


def _sparse_dkv_kernel(rows_ref, nrows_ref, q_ref, k_ref, v_ref, do_ref,
                       lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                       *, block, causal):
    h = pl.program_id(1)
    ki = pl.program_id(2)
    t = pl.program_id(3)
    Ar = pl.num_programs(3)

    @pl.when(t == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    qt = rows_ref[h, ki, t]
    live = t < nrows_ref[h, ki]
    if causal:
        live = live & (qt >= ki)

    def _compute():
        # q block at row qt (pre-scaled), k/v blocks at column ki
        s = _score_tile(q_ref, k_ref, qt, ki, block, causal)
        q = q_ref[0, 0]
        lse = jnp.max(lse_ref[0, 0], axis=-1, keepdims=True)
        p = jnp.exp(s - jnp.where(lse == _NEG_INF, 0.0, lse))
        do = do_ref[0, 0]
        dv_acc[:] += jax.lax.dot_general(p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_ref[0, 0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - jnp.max(delta_ref[0, 0], axis=-1, keepdims=True))
        dk_acc[:] += jax.lax.dot_general(ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    pl.when(live)(_compute)

    @pl.when(t == Ar - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _sparse_bwd(q, k, v, do, out, lse, cols, ncols, rows, nrows, block, causal):
    """All arrays [B, H, S, D] (q pre-scaled). Returns (dq, dk, dv) fp32.

    dq walks each row's active columns (cols/ncols); dk/dv walk each column's
    active rows (rows/nrows) — both grids end at the layout population, so
    the backward skips exactly the tiles the forward skipped."""
    B, H, S, D = q.shape
    n = S // block
    A = cols.shape[-1]
    Ar = rows.shape[-1]

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, _LANES))

    qrow = lambda b, h, qi, j, cols, ncols: (b, h, qi, 0)  # noqa: E731
    kcol = lambda b, h, qi, j, cols, ncols: (b, h, cols[h, qi, j], 0)  # noqa: E731
    dq = pl.pallas_call(
        functools.partial(_sparse_dq_kernel, block=block, causal=causal),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # cols, ncols
            grid=(B, H, n, A),
            in_specs=[
                pl.BlockSpec((1, 1, block, D), qrow),
                pl.BlockSpec((1, 1, block, D), kcol),
                pl.BlockSpec((1, 1, block, D), kcol),
                pl.BlockSpec((1, 1, block, D), qrow),
                pl.BlockSpec((1, 1, block, _LANES), qrow),
                pl.BlockSpec((1, 1, block, _LANES), qrow),
            ],
            out_specs=pl.BlockSpec((1, 1, block, D), qrow),
            scratch_shapes=[pltpu.VMEM((block, D), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), jnp.float32),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(cols, ncols, q, k, v, do, lse, delta)

    # transposed walk: the "row" block index comes from the rows list
    qrow_t = lambda b, h, ki, t, rows, nrows: (b, h, rows[h, ki, t], 0)  # noqa: E731
    kcol_t = lambda b, h, ki, t, rows, nrows: (b, h, ki, 0)  # noqa: E731
    dk, dv = pl.pallas_call(
        functools.partial(_sparse_dkv_kernel, block=block, causal=causal),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # rows, nrows
            grid=(B, H, n, Ar),
            in_specs=[
                pl.BlockSpec((1, 1, block, D), qrow_t),
                pl.BlockSpec((1, 1, block, D), kcol_t),
                pl.BlockSpec((1, 1, block, D), kcol_t),
                pl.BlockSpec((1, 1, block, D), qrow_t),
                pl.BlockSpec((1, 1, block, _LANES), qrow_t),
                pl.BlockSpec((1, 1, block, _LANES), qrow_t),
            ],
            out_specs=[pl.BlockSpec((1, 1, block, D), kcol_t)] * 2,
            scratch_shapes=[pltpu.VMEM((block, D), jnp.float32),
                            pltpu.VMEM((block, D), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((B, H, S, D), jnp.float32)] * 2,
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(rows, nrows, q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _sparse_attention(q, k, v, layout_key, block, causal):
    return _sparse_fwd_wrap(q, k, v, layout_key, block, causal)


# LRU-bounded layout cache: entries pin host + device arrays, and callers may
# regenerate layouts (random BigBird blocks, varying seq lens). The key is
# SELF-DESCRIBING (shape, dtype, raw bytes), so eviction is always safe: a
# pending custom-VJP backward that looks up an evicted key just rebuilds the
# arrays from the key itself.
_LAYOUTS: "dict" = {}  # insertion-ordered; oldest evicted past the cap
_LAYOUT_CAP = 32


def _register_layout(layout: np.ndarray):
    key = (layout.shape, layout.dtype.str, layout.tobytes())
    _layout_arrays(key)
    return key


def _layout_arrays(key):
    """(layout, cols, ncols, rows, nrows) for a registry key, rebuilding
    after eviction (cols/ncols drive fwd + dq; rows/nrows drive dk/dv)."""
    if key in _LAYOUTS:
        _LAYOUTS[key] = _LAYOUTS.pop(key)  # refresh LRU position
    else:
        shape, dtype, raw = key
        layout = np.frombuffer(raw, dtype=np.dtype(dtype)).reshape(shape)
        cols, ncols = layout_to_lists(layout)
        rows, nrows = layout_to_lists_t(layout)
        _LAYOUTS[key] = (layout, jnp.asarray(cols), jnp.asarray(ncols),
                         jnp.asarray(rows), jnp.asarray(nrows))
        while len(_LAYOUTS) > _LAYOUT_CAP:
            _LAYOUTS.pop(next(iter(_LAYOUTS)))
    return _LAYOUTS[key]


def _sparse_core(q, k, v, layout_key, block, causal):
    _, cols, ncols, _, _ = _layout_arrays(layout_key)
    scale = q.shape[-1] ** -0.5
    qt = (q * jnp.asarray(scale, q.dtype)).transpose(0, 2, 1, 3)  # [B,H,S,D]
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out, lse = _sparse_fwd(qt, kt, vt, cols, ncols, block, causal)
    return out.transpose(0, 2, 1, 3), (qt, kt, vt, lse, out)


def _sparse_fwd_wrap(q, k, v, layout_key, block, causal):
    return _sparse_core(q, k, v, layout_key, block, causal)[0]


# the VJP forward's (primal, residuals) contract is exactly _sparse_core's
_sparse_vjp_fwd = _sparse_core


def _sparse_vjp_bwd(layout_key, block, causal, res, g):
    qt, kt, vt, lse, out_bhsd = res
    _, cols, ncols, rows, nrows = _layout_arrays(layout_key)
    do = g.transpose(0, 2, 1, 3)
    dq, dk, dv = _sparse_bwd(qt, kt, vt, do, out_bhsd, lse,
                             cols, ncols, rows, nrows, block, causal)
    # dq was accumulated against unscaled k but for the PRE-SCALED q input:
    # apply the 1/sqrt(D) factor here in fp32. dk used the pre-scaled q, so
    # it already carries the factor.
    scale = qt.shape[-1] ** -0.5
    dq = (dq * scale).transpose(0, 2, 1, 3).astype(qt.dtype)
    dk = dk.transpose(0, 2, 1, 3).astype(kt.dtype)
    dv = dv.transpose(0, 2, 1, 3).astype(vt.dtype)
    return dq, dk, dv


_sparse_attention.defvjp(_sparse_vjp_fwd, _sparse_vjp_bwd)


def block_sparse_attention_pallas(q, k, v, layout: np.ndarray, block: int, causal: bool = True):
    """Public entry: tile-skipping kernel forward + exact backward."""
    key = _register_layout(np.asarray(layout))
    return _sparse_attention(q, k, v, key, block, causal)
