"""Block-sparse attention Pallas kernel: dead (qblk, kblk) tiles are SKIPPED.

Reference analog: ``deepspeed/ops/sparse_attention/matmul.py:196`` — the
Triton sdd/dsd block-skipping matmuls that make BigBird/Longformer layouts a
real compute/memory win rather than a mask.

Design: the (static numpy) block layout compiles into per-(head, qblock)
active-column lists. The grid's last axis runs only to ``max_active`` columns
(not n_blocks), the column index rides scalar prefetch into the K/V BlockSpec
index maps, and rows with fewer active columns guard the tail — so both the
DMA and the MXU work scale with ``layout.sum()`` instead of ``n^2``. Online
softmax accumulates across a row's active tiles exactly as in the dense flash
kernel.

Backward: a custom VJP recomputes through the XLA dense-masked path (forward
memory win is preserved; the backward pays O(S^2) scores — the two sparse
backward kernels are the follow-up, same layout-list contract transposed).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float(jnp.finfo(jnp.float32).min)
_LANES = 8


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def layout_to_lists(layout: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """[H, n, n] 0/1 -> (cols [H, n, A], ncols [H, n]); padded entries repeat
    the row's last active column (their compute is guarded off, and a valid
    index keeps the prefetched DMA in range)."""
    H, n, _ = layout.shape
    ncols = layout.sum(-1).astype(np.int32)
    A = max(1, int(ncols.max()))
    cols = np.zeros((H, n, A), np.int32)
    for h in range(H):
        for i in range(n):
            act = np.nonzero(layout[h, i])[0]
            if act.size:
                cols[h, i, :act.size] = act
                cols[h, i, act.size:] = act[-1]
    return cols, ncols


def _sparse_fwd_kernel(cols_ref, ncols_ref, q_ref, k_ref, v_ref, o_ref,
                       acc_ref, m_ref, l_ref, *, block, causal):
    h = pl.program_id(1)
    qi = pl.program_id(2)
    j = pl.program_id(3)
    A = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    kj = cols_ref[h, qi, j]
    live = j < ncols_ref[h, qi]
    if causal:
        live = live & (kj <= qi)

    def _compute():
        q = q_ref[0, 0]  # [block, D] pre-scaled
        k = k_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            # only the diagonal tile needs the iota mask
            rows = qi * block + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            colS = kj * block + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where((kj != qi) | (colS <= rows), s, _NEG_INF)

        m_prev = jnp.max(m_ref[:], axis=-1, keepdims=True)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        m_safe = jnp.where(m_cur == _NEG_INF, 0.0, m_cur)
        p = jnp.exp(s - m_safe)
        alpha = jnp.where(m_prev == _NEG_INF, 0.0, jnp.exp(m_prev - m_safe))
        l_prev = jnp.max(l_ref[:], axis=-1, keepdims=True)
        l_ref[:] = jnp.broadcast_to(alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
        m_ref[:] = jnp.broadcast_to(m_cur, m_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    pl.when(live)(_compute)

    @pl.when(j == A - 1)
    def _finalize():
        l = jnp.max(l_ref[:], axis=-1, keepdims=True)
        o_ref[0, 0] = (acc_ref[:] / jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def _sparse_fwd(q, k, v, cols, ncols, block, causal):
    """q/k/v: [B, H, S, D] (q pre-scaled). Returns [B, H, S, D]."""
    B, H, S, D = q.shape
    n = S // block
    A = cols.shape[-1]

    out = pl.pallas_call(
        functools.partial(_sparse_fwd_kernel, block=block, causal=causal),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # cols, ncols
            grid=(B, H, n, A),
            in_specs=[
                pl.BlockSpec((1, 1, block, D), lambda b, h, qi, j, cols, ncols: (b, h, qi, 0)),
                pl.BlockSpec((1, 1, block, D), lambda b, h, qi, j, cols, ncols: (b, h, cols[h, qi, j], 0)),
                pl.BlockSpec((1, 1, block, D), lambda b, h, qi, j, cols, ncols: (b, h, cols[h, qi, j], 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, block, D), lambda b, h, qi, j, cols, ncols: (b, h, qi, 0)),
            scratch_shapes=[
                pltpu.VMEM((block, D), jnp.float32),
                pltpu.VMEM((block, _LANES), jnp.float32),
                pltpu.VMEM((block, _LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(cols, ncols, q, k, v)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _sparse_attention(q, k, v, layout_key, block, causal):
    return _sparse_fwd_wrap(q, k, v, layout_key, block, causal)


# LRU-bounded layout cache: entries pin host + device arrays, and callers may
# regenerate layouts (random BigBird blocks, varying seq lens). The key is
# SELF-DESCRIBING (shape, dtype, raw bytes), so eviction is always safe: a
# pending custom-VJP backward that looks up an evicted key just rebuilds the
# arrays from the key itself.
_LAYOUTS: "dict" = {}  # insertion-ordered; oldest evicted past the cap
_LAYOUT_CAP = 32


def _register_layout(layout: np.ndarray):
    key = (layout.shape, layout.dtype.str, layout.tobytes())
    _layout_arrays(key)
    return key


def _layout_arrays(key):
    """(layout, cols, ncols) for a registry key, rebuilding after eviction."""
    if key in _LAYOUTS:
        _LAYOUTS[key] = _LAYOUTS.pop(key)  # refresh LRU position
    else:
        shape, dtype, raw = key
        layout = np.frombuffer(raw, dtype=np.dtype(dtype)).reshape(shape)
        cols, ncols = layout_to_lists(layout)
        _LAYOUTS[key] = (layout, jnp.asarray(cols), jnp.asarray(ncols))
        while len(_LAYOUTS) > _LAYOUT_CAP:
            _LAYOUTS.pop(next(iter(_LAYOUTS)))
    return _LAYOUTS[key]


def _sparse_fwd_wrap(q, k, v, layout_key, block, causal):
    _, cols, ncols = _layout_arrays(layout_key)
    scale = q.shape[-1] ** -0.5
    qt = (q * jnp.asarray(scale, q.dtype)).transpose(0, 2, 1, 3)  # [B,H,S,D]
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _sparse_fwd(qt, kt, vt, cols, ncols, block, causal)
    return out.transpose(0, 2, 1, 3)


def _sparse_vjp_fwd(q, k, v, layout_key, block, causal):
    return _sparse_fwd_wrap(q, k, v, layout_key, block, causal), (q, k, v)


def _sparse_vjp_bwd(layout_key, block, causal, res, g):
    # recompute through the dense-masked XLA path: exact gradients, O(S^2)
    # scores only in the backward (see module docstring)
    from deepspeed_tpu.ops.sparse_attention import block_sparse_attention_dense

    q, k, v = res
    layout, _, _ = _layout_arrays(layout_key)

    def f(q, k, v):
        return block_sparse_attention_dense(q, k, v, layout, block, causal)

    _, vjp = jax.vjp(f, q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv


_sparse_attention.defvjp(_sparse_vjp_fwd, _sparse_vjp_bwd)


def block_sparse_attention_pallas(q, k, v, layout: np.ndarray, block: int, causal: bool = True):
    """Public entry: tile-skipping kernel forward + exact backward."""
    key = _register_layout(np.asarray(layout))
    return _sparse_attention(q, k, v, key, block, causal)
