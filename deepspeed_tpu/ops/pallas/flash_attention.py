"""Flash attention as a Pallas TPU kernel (fwd + custom-VJP bwd).

TPU-native replacement for the reference's attention kernel families:
``csrc/transformer/inference/csrc/softmax.cu`` (masked/alibi softmax),
``csrc/transformer/softmax_kernels.cu`` and the Triton flash variants
(``deepspeed/ops/transformer/inference/triton/attention.py``). Design is
blockwise online-softmax (Flash-Attention-2 style): the score matrix is never
materialized in HBM; K/V stream through VMEM in (block_k x head_dim) tiles
while running max/denominator/accumulator live in VMEM scratch.

Layout: inputs are [B, S, H, D] (framework-native); the kernel works on
[B, H, S, D]. GQA/MQA is handled in the index maps (kv head = q head // G),
so grouped heads re-read the same KV tile — no KV replication in HBM.

Performance notes (measured on v5e):
  - every matmul is input-dtype (bf16) with fp32 accumulation; fp32 operands
    run the MXU at ~1/4 rate
  - blocks that sit strictly below the causal diagonal skip ALL mask work
    (iota/compare/select are VPU passes over [block_q, block_k] and dominate
    the kernel when applied to every block); only diagonal-crossing blocks
    mask, and the padding keep-mask is applied only when the caller passed one
  - grid dims (b, h, q) are declared parallel so Mosaic double-buffers the
    next block's DMA across grid steps
  - for causal + no user mask, tail padding introduced by the wrapper needs no
    masking at all: padded key columns are only visible to padded query rows,
    whose outputs are sliced off (and whose incoming gradients are zero)

Causality and padding are one combined mask on the diagonal path, so in-kernel
there is a single masking code path per block class.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.utils.compat import tpu_compiler_params

from deepspeed_tpu.ops.registry import register

_NEG_INF = float(jnp.finfo(jnp.float32).min)
# The kernels run the softmax in BASE 2: XLA/Mosaic lower exp(x) as
# exp2(x * log2(e)), so folding log2(e) into the query pre-scale removes one
# full [block_q, block_k] VPU multiply per exp site (fwd + both backwards).
# The ln2 factor that base-2 softmax gradients pick up is applied exactly on
# the wrapper side: dq's ln2*log2e cancels to 1, dk gets one fp32 multiply
# (see _flash_vjp_bwd) — no extra in-kernel passes, no bf16 rounding bias.
_LOG2E = 1.4426950408889634
_LN2 = 0.6931471805599453

DEFAULT_BLOCK_Q = 512
_LANES = 8  # lse/delta lane width in HBM (block last dim == array last dim satisfies Mosaic tiling); m/l scratch pad internally
DEFAULT_BLOCK_K = 512


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# out_shape structs carry the inputs' varying-manual-axes where this jax
# tracks them (jax>=0.9 check_vma); plain structs on 0.4.x
from deepspeed_tpu.utils.compat import shape_dtype_struct as _sds


def _cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


def _block_classes(qi, ki, block_q, block_k):
    """(full_below, crosses_diag) for causal attention.

    full_below: every (row, col) in the block satisfies col <= row — no mask.
    crosses_diag: block intersects the diagonal — needs the iota mask.
    Blocks strictly above the diagonal are skipped entirely.
    """
    full_below = ki * block_k + block_k - 1 <= qi * block_q
    touches = ki * block_k <= qi * block_q + block_q - 1
    return full_below, touches & ~full_below


def _causal_keep(qi, ki, shape, block_q, block_k, col_off=0):
    rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    cols = ki * block_k + col_off + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    return cols <= rows


# The squashed grids ship their (qi, ki) enumeration as scalar-prefetch SMEM
# arrays of n(n+1)/2 entries. Past this cap the SMEM cost outweighs the
# skipped above-diagonal DMAs and the wrappers fall back to the dense causal
# grid (which skips the same compute via block classes, just not the DMAs).
# At block 512 this covers sequences up to ~90k tokens per device.
_MAX_SQUASHED_CELLS = 16384


def _squash_ok(nq: int, nk: int, block_q: int, block_k: int, causal: bool) -> bool:
    return (causal and block_q == block_k and nq == nk
            and nq * (nq + 1) // 2 <= _MAX_SQUASHED_CELLS)


def _tri_maps(n: int):
    """Row-major lower-triangle enumeration: for each query row qi, the active
    key columns ki in [0, qi]. The causal grid runs ONLY these n(n+1)/2 cells
    (vs n^2): above-diagonal cells would DMA K/V and then skip all compute.
    Pure arange arithmetic — no O(n^2) Python pair list at trace time."""
    import numpy as np

    counts = np.arange(1, n + 1)
    qs = np.repeat(np.arange(n), counts)
    starts = np.repeat(np.cumsum(counts) - counts, counts)
    ks = np.arange(qs.size) - starts
    return jnp.asarray(qs, jnp.int32), jnp.asarray(ks, jnp.int32)


def _wedge_maps(n: int):
    """Column-major enumeration of the same triangle: for each key column ki,
    the query rows qi in [ki, n-1] contiguously (dk/dv accumulate per column)."""
    import numpy as np

    counts = np.arange(n, 0, -1)
    ks = np.repeat(np.arange(n), counts)
    starts = np.repeat(np.cumsum(counts) - counts, counts)
    qs = (np.arange(ks.size) - starts) + ks
    return jnp.asarray(qs, jnp.int32), jnp.asarray(ks, jnp.int32)


# Grid-argument decoders: every BlockSpec index map below is written against
# canonical (b, h, qi, ki) and composed with the decoder for the grid in use,
# so the squashed (scalar-prefetch) and dense variants share one spec list.
_DEC_SQUASHED = lambda b, h, t, qm, km: (b, h, qm[t], km[t])  # noqa: E731
_DEC_DENSE = lambda b, h, qi, ki: (b, h, qi, ki)  # noqa: E731
_DEC_DENSE_KQ = lambda b, h, ki, qi: (b, h, qi, ki)  # noqa: E731  (dkv grid order)


def _spec(shape, f, dec):
    return pl.BlockSpec(shape, lambda *a: f(*dec(*a)))


def _qkv_in_specs(dec, block_q, block_k, D, G, alibi=False):
    """mask, [slopes], q, k, v input specs (shared by fwd and both backward
    kernels). The alibi slopes ride as a tiny [H, _LANES] fp32 array blocked
    per query head."""
    specs = [_spec((1, 1, block_k), lambda b, h, qi, ki: (b, 0, ki), dec)]
    if alibi:
        specs.append(_spec((1, _LANES), lambda b, h, qi, ki: (h, 0), dec))
    specs += [
        _spec((1, 1, block_q, D), lambda b, h, qi, ki: (b, h, qi, 0), dec),
        _spec((1, 1, block_k, D), lambda b, h, qi, ki: (b, h // G, ki, 0), dec),
        _spec((1, 1, block_k, D), lambda b, h, qi, ki: (b, h // G, ki, 0), dec),
    ]
    return specs


def _alibi_add(s, slopes_ref, ki, block_k, col_off=0):
    """s += slope[h] * key-position, in the caller's softmax scale (the
    wrapper pre-folds log2e into the slopes for the base-2 kernels). The HF
    bloom convention (slopes * j); softmax cancels the per-row shift vs
    slopes * (j - i)."""
    cols = ki * block_k + col_off + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return s + slopes_ref[0, 0] * cols.astype(jnp.float32)


def _qrow_specs(dec, block_q, D):
    """do, lse, delta input specs (backward) / o, lse output specs (forward)
    — everything blocked along the query row."""
    qrow = lambda b, h, qi, ki: (b, h, qi, 0)  # noqa: E731
    return {
        "qD": _spec((1, 1, block_q, D), qrow, dec),
        "qL": _spec((1, 1, block_q, _LANES), qrow, dec),
    }


def _kcol_spec(dec, block_k, D):
    """dk/dv output spec — blocked along the key column."""
    return _spec((1, 1, block_k, D), lambda b, h, qi, ki: (b, h, ki, 0), dec)


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------


def _sub_slices(block_k: int, k_splits: int):
    """Static row/col ranges splitting a block_k tile into k_splits chunks."""
    c = block_k // k_splits
    return [(i * c, c) for i in range(k_splits)]


def _sub_score(q, k, mask_ref, slopes_ref, qi, ki, off, c, *, block_q, block_k,
               masked, mask_block, alibi):
    """Masked scores for one sub-chunk: s = q @ k[off:off+c]^T (+alibi, +mask).

    The one scoring implementation shared by the forward and both backward
    kernels — the mask/bias math must never diverge between passes."""
    s = jax.lax.dot_general(
        q, k[off:off + c], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [block_q, c]
    if alibi:
        s = _alibi_add(s, slopes_ref, ki, block_k, col_off=off)
    if mask_block or masked:
        keep = None
        if masked:
            keep = jnp.broadcast_to(mask_ref[0, 0, off:off + c] > 0, s.shape)
        if mask_block:
            ck = _causal_keep(qi, ki, s.shape, block_q, block_k, col_off=off)
            keep = ck if keep is None else keep & ck
        s = jnp.where(keep, s, _NEG_INF)
    return s


def _fwd_kernel(*refs, block_q, block_k, causal, masked, squashed, alibi=False,
                k_splits=1):
    if squashed:
        (qm_ref, km_ref, mask_ref, *rest) = refs
        slopes_ref = rest.pop(0) if alibi else None
        (q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref) = rest
        t = pl.program_id(2)
        qi, ki = qm_ref[t], km_ref[t]
        first, last = ki == 0, ki == qi
    else:
        (mask_ref, *rest) = refs
        slopes_ref = rest.pop(0) if alibi else None
        (q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref) = rest
        qi, ki = pl.program_id(2), pl.program_id(3)
        first, last = ki == 0, ki == pl.num_programs(3) - 1

    @pl.when(first)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _compute(mask_block):
        q = q_ref[0, 0]  # [block_q, D]  (pre-scaled by 1/sqrt(D))
        k = k_ref[0, 0]  # [block_k, D]
        v = v_ref[0, 0]
        sub = _sub_slices(block_k, k_splits)

        def _score(off, c):
            return _sub_score(q, k, mask_ref, slopes_ref, qi, ki, off, c,
                              block_q=block_q, block_k=block_k, masked=masked,
                              mask_block=mask_block, alibi=alibi)

        s_next = _score(*sub[0])
        for idx, (off, c) in enumerate(sub):
            s = s_next
            if idx + 1 < k_splits:
                # Hoisted ahead of this chunk's softmax: the next QK^T reads
                # nothing from m/l/acc, so the MXU can run it while the VPU
                # does the exp2/renormalize passes below.
                s_next = _score(*sub[idx + 1])

            m_prev = jnp.max(m_ref[:], axis=-1, keepdims=True)  # [block_q, 1] (lanes equal)
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            # All-masked rows keep m at -inf; guard exp against (-inf) - (-inf).
            m_safe = jnp.where(m_cur == _NEG_INF, 0.0, m_cur)
            p = jnp.exp2(s - m_safe)  # masked entries: exp2(NEG_INF - finite) == 0

            alpha = jnp.where(m_prev == _NEG_INF, 0.0, jnp.exp2(m_prev - m_safe))
            l_prev = jnp.max(l_ref[:], axis=-1, keepdims=True)
            l_cur = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
            l_ref[:] = jnp.broadcast_to(l_cur, l_ref.shape)
            m_ref[:] = jnp.broadcast_to(m_cur, m_ref.shape)
            acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
                p.astype(v.dtype), v[off:off + c], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    if causal and squashed:
        # the grid enumerates only ki <= qi; the diagonal cell masks in-block
        pl.when(ki < qi)(lambda: _compute(False))
        pl.when(ki == qi)(lambda: _compute(True))
    elif causal:
        full_below, diag = _block_classes(qi, ki, block_q, block_k)
        pl.when(full_below)(lambda: _compute(False))
        pl.when(diag)(lambda: _compute(True))
    else:
        _compute(False)

    @pl.when(last)
    def _finalize():
        l = jnp.max(l_ref[:], axis=-1, keepdims=True)
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        m = jnp.max(m_ref[:], axis=-1, keepdims=True)
        # base-2 logsumexp per row (lane-broadcast); fully-masked rows get -inf.
        lse = jnp.where(l == 0.0, _NEG_INF, m + jnp.log2(l_safe))
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


_PARALLEL_SEMANTICS = ("parallel", "parallel", "parallel", "arbitrary")


def _flash_fwd(q, k, v, mask, slopes, block_q: int, block_k: int, causal: bool,
               masked: bool, alibi: bool, k_splits: int = 1):
    """q,k,v: [B, H(q/kv), S, D] (q pre-scaled). mask: [B, S] int32.
    slopes: [H, _LANES] fp32 (log2e-scaled; ignored unless alibi).
    Returns (out, lse)."""
    B, H, S, D = q.shape
    Hkv = k.shape[1]
    G = H // Hkv
    nq, nk = _cdiv(S, block_q), _cdiv(S, block_k)
    squashed = _squash_ok(nq, nk, block_q, block_k, causal)

    out_shape = [
        _sds((B, H, S, D), q.dtype, q, k, v, mask),
        _sds((B, H, S, _LANES), jnp.float32, q, k, v, mask),
    ]
    scratch_shapes = [
        pltpu.VMEM((block_q, D), jnp.float32),
        pltpu.VMEM((block_q, _LANES), jnp.float32),
        pltpu.VMEM((block_q, _LANES), jnp.float32),
    ]
    kernel = functools.partial(_fwd_kernel, block_q=block_q, block_k=block_k,
                               causal=causal, masked=masked, squashed=squashed,
                               alibi=alibi, k_splits=k_splits)
    dec = _DEC_SQUASHED if squashed else _DEC_DENSE
    in_specs = _qkv_in_specs(dec, block_q, block_k, D, G, alibi=alibi)
    qrow = _qrow_specs(dec, block_q, D)
    out_specs = [qrow["qD"], qrow["qL"]]
    extra = (slopes,) if alibi else ()

    if squashed:
        qm, km = _tri_maps(nq)
        out, lse = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,  # qmap, kmap
                grid=(B, H, qm.shape[0]),
                in_specs=in_specs,
                out_specs=out_specs,
                scratch_shapes=scratch_shapes,
            ),
            out_shape=out_shape,
            compiler_params=tpu_compiler_params(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=_interpret(),
        )(qm, km, mask, *extra, q, k, v)
        return out, lse

    out, lse = pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch_shapes,
        compiler_params=tpu_compiler_params(dimension_semantics=_PARALLEL_SEMANTICS),
        interpret=_interpret(),
    )(mask, *extra, q, k, v)
    return out, lse


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------


def _bwd_dq_kernel(*refs, block_q, block_k, causal, masked, squashed, alibi=False,
                   k_splits=1):
    if squashed:
        (qm_ref, km_ref, mask_ref, *rest) = refs
        slopes_ref = rest.pop(0) if alibi else None
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc_ref) = rest
        t = pl.program_id(2)
        qi, ki = qm_ref[t], km_ref[t]
        first, last = ki == 0, ki == qi
    else:
        (mask_ref, *rest) = refs
        slopes_ref = rest.pop(0) if alibi else None
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc_ref) = rest
        qi, ki = pl.program_id(2), pl.program_id(3)
        first, last = ki == 0, ki == pl.num_programs(3) - 1

    @pl.when(first)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _compute(mask_block):
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = jnp.max(lse_ref[0, 0], axis=-1, keepdims=True)  # [block_q, 1]
        lse_safe = jnp.where(lse == _NEG_INF, 0.0, lse)
        delta = jnp.max(delta_ref[0, 0], axis=-1, keepdims=True)
        sub = _sub_slices(block_k, k_splits)

        def _score(off, c):
            return _sub_score(q, k, mask_ref, slopes_ref, qi, ki, off, c,
                              block_q=block_q, block_k=block_k, masked=masked,
                              mask_block=mask_block, alibi=alibi)

        s_next = _score(*sub[0])
        for idx, (off, c) in enumerate(sub):
            s = s_next
            if idx + 1 < k_splits:
                s_next = _score(*sub[idx + 1])  # MXU overlaps the VPU passes below
            p = jnp.exp2(s - lse_safe)
            # bf16 x bf16 matmul with fp32 accumulation: fp32 operands would run
            # the MXU at a fraction of its bf16 rate (measured 4x slower on v5e).
            dp = jax.lax.dot_general(
                do, v[off:off + c], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta)
            acc_ref[:] += jax.lax.dot_general(
                ds.astype(k.dtype), k[off:off + c], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    if causal and squashed:
        pl.when(ki < qi)(lambda: _compute(False))
        pl.when(ki == qi)(lambda: _compute(True))
    elif causal:
        full_below, diag = _block_classes(qi, ki, block_q, block_k)
        pl.when(full_below)(lambda: _compute(False))
        pl.when(diag)(lambda: _compute(True))
    else:
        _compute(False)

    @pl.when(last)
    def _finalize():
        dq_ref[0, 0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, block_q, block_k, causal, masked, squashed, nq_total,
                    alibi=False, k_splits=1):
    if squashed:
        (qm_ref, km_ref, mask_ref, *rest) = refs
        slopes_ref = rest.pop(0) if alibi else None
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = rest
        t = pl.program_id(2)
        qi, ki = qm_ref[t], km_ref[t]
        first, last = qi == ki, qi == nq_total - 1
    else:
        (mask_ref, *rest) = refs
        slopes_ref = rest.pop(0) if alibi else None
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = rest
        ki, qi = pl.program_id(2), pl.program_id(3)
        first, last = qi == 0, qi == pl.num_programs(3) - 1

    @pl.when(first)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute(mask_block):
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = jnp.max(lse_ref[0, 0], axis=-1, keepdims=True)
        lse_safe = jnp.where(lse == _NEG_INF, 0.0, lse)
        delta = jnp.max(delta_ref[0, 0], axis=-1, keepdims=True)
        sub = _sub_slices(block_k, k_splits)

        def _score(off, c):
            return _sub_score(q, k, mask_ref, slopes_ref, qi, ki, off, c,
                              block_q=block_q, block_k=block_k, masked=masked,
                              mask_block=mask_block, alibi=alibi)

        s_next = _score(*sub[0])
        for idx, (off, c) in enumerate(sub):
            s = s_next
            if idx + 1 < k_splits:
                s_next = _score(*sub[idx + 1])  # MXU overlaps the VPU passes below
            p = jnp.exp2(s - lse_safe)
            # keep every matmul in the input dtype (bf16) with fp32 accumulation —
            # fp32 operands would cut the MXU rate ~4x (see _bwd_dq_kernel note)
            dv_acc[off:off + c] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = jax.lax.dot_general(do, v[off:off + c], (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta)
            dk_acc[off:off + c] += jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    if causal and squashed:
        pl.when(qi > ki)(lambda: _compute(False))
        pl.when(qi == ki)(lambda: _compute(True))
    elif causal:
        full_below, diag = _block_classes(qi, ki, block_q, block_k)
        pl.when(full_below)(lambda: _compute(False))
        pl.when(diag)(lambda: _compute(True))
    else:
        _compute(False)

    @pl.when(last)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, mask, slopes, out, lse, do, block_q: int, block_k: int,
               causal: bool, masked: bool, alibi: bool, k_splits: int = 1):
    B, H, S, D = q.shape
    Hkv = k.shape[1]
    G = H // Hkv
    nq, nk = _cdiv(S, block_q), _cdiv(S, block_k)
    squashed = _squash_ok(nq, nk, block_q, block_k, causal)

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)  # [B,H,S]
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, _LANES))

    dq_kernel = functools.partial(_bwd_dq_kernel, block_q=block_q, block_k=block_k,
                                  causal=causal, masked=masked, squashed=squashed,
                                  alibi=alibi, k_splits=k_splits)
    dkv_kernel = functools.partial(_bwd_dkv_kernel, block_q=block_q, block_k=block_k,
                                   causal=causal, masked=masked, squashed=squashed,
                                   nq_total=nq, alibi=alibi, k_splits=k_splits)
    extra = (slopes,) if alibi else ()
    dq_scratch = [pltpu.VMEM((block_q, D), jnp.float32)]
    dkv_scratch = [pltpu.VMEM((block_k, D), jnp.float32),
                   pltpu.VMEM((block_k, D), jnp.float32)]
    dq_shape = _sds((B, H, S, D), jnp.float32, q, k, v, mask, do)
    dkv_shape = [dq_shape, dq_shape]

    def bwd_in_specs(dec):
        qrow = _qrow_specs(dec, block_q, D)
        return (_qkv_in_specs(dec, block_q, block_k, D, G, alibi=alibi)
                + [qrow["qD"], qrow["qL"], qrow["qL"]])

    if squashed:
        arb = tpu_compiler_params(dimension_semantics=("parallel", "parallel", "arbitrary"))
        qm, km = _tri_maps(nq)
        dq = pl.pallas_call(
            dq_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(B, H, qm.shape[0]),
                in_specs=bwd_in_specs(_DEC_SQUASHED),
                out_specs=_qrow_specs(_DEC_SQUASHED, block_q, D)["qD"],
                scratch_shapes=dq_scratch,
            ),
            out_shape=dq_shape,
            compiler_params=arb,
            interpret=_interpret(),
        )(qm, km, mask, *extra, q, k, v, do, lse, delta)

        # dk/dv are per *query* head here; grouped heads are summed below.
        wqm, wkm = _wedge_maps(nk)
        dk, dv = pl.pallas_call(
            dkv_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(B, H, wqm.shape[0]),
                in_specs=bwd_in_specs(_DEC_SQUASHED),
                out_specs=[_kcol_spec(_DEC_SQUASHED, block_k, D)] * 2,
                scratch_shapes=dkv_scratch,
            ),
            out_shape=dkv_shape,
            compiler_params=arb,
            interpret=_interpret(),
        )(wqm, wkm, mask, *extra, q, k, v, do, lse, delta)
    else:
        dq = pl.pallas_call(
            dq_kernel,
            grid=(B, H, nq, nk),
            in_specs=bwd_in_specs(_DEC_DENSE),
            out_specs=_qrow_specs(_DEC_DENSE, block_q, D)["qD"],
            out_shape=dq_shape,
            scratch_shapes=dq_scratch,
            compiler_params=tpu_compiler_params(dimension_semantics=_PARALLEL_SEMANTICS),
            interpret=_interpret(),
        )(mask, *extra, q, k, v, do, lse, delta)

        # dk/dv are per *query* head here; grouped heads are summed below. The
        # dense dkv grid iterates (ki outer, qi inner) — _DEC_DENSE_KQ restores
        # the canonical (qi, ki) order for the shared specs.
        dk, dv = pl.pallas_call(
            dkv_kernel,
            grid=(B, H, nk, nq),
            in_specs=bwd_in_specs(_DEC_DENSE_KQ),
            out_specs=[_kcol_spec(_DEC_DENSE_KQ, block_k, D)] * 2,
            out_shape=dkv_shape,
            scratch_shapes=dkv_scratch,
            compiler_params=tpu_compiler_params(dimension_semantics=_PARALLEL_SEMANTICS),
            interpret=_interpret(),
        )(mask, *extra, q, k, v, do, lse, delta)

    if G > 1:
        dk = dk.reshape(B, Hkv, G, S, D).sum(axis=2)
        dv = dv.reshape(B, Hkv, G, S, D).sum(axis=2)
    return dq, dk, dv


# --------------------------------------------------------------------------
# public op: [B, S, H, D] layout, custom VJP, padding + causal handling
# --------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_attention(q, k, v, mask, slopes, block_q, block_k, causal, masked, alibi,
                     k_splits=1):
    out, _ = _flash_core(q, k, v, mask, slopes, block_q, block_k, causal, masked,
                         alibi, k_splits)
    return out


def _flash_core(q, k, v, mask, slopes, block_q, block_k, causal, masked, alibi,
                k_splits=1):
    scale = q.shape[-1] ** -0.5 * _LOG2E  # base-2 softmax (see module header)
    qs = (q * jnp.asarray(scale, q.dtype)).transpose(0, 2, 1, 3)  # [B,H,S,D]
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out, lse = _flash_fwd(qs, kt, vt, mask, slopes, block_q, block_k, causal, masked,
                          alibi, k_splits)
    return out.transpose(0, 2, 1, 3), (qs, kt, vt, lse, out)


def _flash_vjp_fwd(q, k, v, mask, slopes, block_q, block_k, causal, masked, alibi,
                   k_splits=1):
    out, (qs, kt, vt, lse, out_bhsd) = _flash_core(q, k, v, mask, slopes, block_q,
                                                   block_k, causal, masked, alibi,
                                                   k_splits)
    return out, (qs, kt, vt, mask, slopes, lse, out_bhsd)


def _flash_vjp_bwd(block_q, block_k, causal, masked, alibi, k_splits, res, g):
    qs, kt, vt, mask, slopes, lse, out_bhsd = res
    do = g.transpose(0, 2, 1, 3)
    dq, dk, dv = _flash_bwd(qs, kt, vt, mask, slopes, out_bhsd, lse, do,
                            block_q, block_k, causal, masked, alibi, k_splits)
    # Base-2 gradient bookkeeping (kernels compute the base-e ds = p*(dp-δ)):
    # dq needs scale*log2e*ln2 == plain scale (exact — no ln2 rounding), and
    # dk, accumulated against the log2e-pre-scaled q, needs ln2 applied here
    # in fp32 before the downcast.
    scale = qs.shape[-1] ** -0.5
    dq = (dq * scale).transpose(0, 2, 1, 3).astype(qs.dtype)
    dk = (dk * _LN2).transpose(0, 2, 1, 3).astype(kt.dtype)
    dv = dv.transpose(0, 2, 1, 3).astype(vt.dtype)
    return dq, dk, dv, None, None


_flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@register("causal_attention", "pallas")
def flash_causal_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,  # [B, S, Hkv, D]
    mask: Optional[jax.Array] = None,  # [B, S] 1=keep
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    alibi_slopes: Optional[jax.Array] = None,  # [H] fp32 (bloom ALiBi)
    k_splits: int = 1,
) -> jax.Array:
    B, S, H, D = q.shape
    block_q = min(block_q, max(S, 8))
    block_k = min(block_k, max(S, 8))
    # k_splits > 1 processes each block_k tile as k_splits sub-chunks with the
    # next sub-chunk's QK^T hoisted ahead of the previous one's softmax, so the
    # MXU matmul overlaps the VPU exp2/renormalize passes (the named TF/s
    # bottleneck, PERF.md). Pure instruction-level restructuring: identical
    # math, A/B via tools/profile_bench.py --stage attn-sweep. A fixed k_splits must stay
    # valid when short sequences clamp block_k, so degrade to the largest
    # compatible divisor (sub-chunks divide block_k; >=128 lanes on hardware).
    while k_splits > 1 and (block_k % k_splits != 0
                            or (not _interpret() and (block_k // k_splits) % 128 != 0)):
        k_splits -= 1
    Sp = _cdiv(S, max(block_q, block_k)) * max(block_q, block_k)

    # masked=False avoids every padding-mask VPU pass in-kernel. Wrapper tail
    # padding is invisible under a causal mask (padded keys only reach padded
    # queries, which are sliced off and receive zero cotangents), so the
    # synthesized all-ones mask never needs to be applied.
    masked = mask is not None
    keep = jnp.ones((B, S), jnp.int32) if mask is None else mask.astype(jnp.int32)
    if Sp != S:
        pad = Sp - S
        q = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        keep = jnp.pad(keep, ((0, 0), (0, pad)))

    alibi = alibi_slopes is not None
    if alibi:
        # The kernels run base-2 softmax: fold log2e into the slopes so the
        # in-kernel bias lands in the same scale as the pre-scaled scores.
        # Slopes are NON-DIFFERENTIABLE on this path (stop_gradient makes it
        # explicit): they are positional constants in ALiBi models; to train
        # learned per-head slopes, use causal_attention(..., impl='xla').
        slopes = jnp.broadcast_to(
            (jax.lax.stop_gradient(alibi_slopes).astype(jnp.float32)
             * _LOG2E)[:, None], (H, _LANES))
    else:
        slopes = jnp.zeros((H, _LANES), jnp.float32)

    out = _flash_attention(q, k, v, keep[:, None, :], slopes,
                           block_q, block_k, True, masked, alibi, k_splits)
    return out[:, :S]
