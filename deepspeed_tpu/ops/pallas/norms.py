"""Fused RMSNorm / LayerNorm Pallas kernels.

TPU-native answer to the reference's ``csrc/transformer/inference/csrc/
rms_norm.cu`` / ``layer_norm.cu`` and v2 core_ops (``inference/v2/kernels/
core_ops/cuda_rms_norm``, ``cuda_layer_norm``). The forward is a single
VMEM-resident row-block kernel (one HBM read + one write per element); the
backward uses the analytic VJP in jnp — it is a pure elementwise+reduction
expression that XLA fuses into adjacent matmul backward passes, so a
hand-written kernel buys nothing there.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deepspeed_tpu.ops.registry import register

_BLOCK_ROWS = 256


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


from deepspeed_tpu.utils.compat import shape_dtype_struct as _sds


def _rms_fwd_kernel(x_ref, scale_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * scale_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _ln_fwd_kernel(x_ref, scale_ref, bias_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * scale_ref[:].astype(jnp.float32) + bias_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _row_blocks(n_rows: int) -> int:
    return min(_BLOCK_ROWS, n_rows)


def _rms_fwd(x2, scale, eps):
    R, Dm = x2.shape
    br = _row_blocks(R)
    return pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps),
        grid=(pl.cdiv(R, br),),
        in_specs=[
            pl.BlockSpec((br, Dm), lambda i: (i, 0)),
            pl.BlockSpec((Dm,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((br, Dm), lambda i: (i, 0)),
        out_shape=_sds((R, Dm), x2.dtype, x2, scale),
        interpret=_interpret(),
    )(x2, scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms_norm_p(x2, scale, eps):
    return _rms_fwd(x2, scale, eps)


def _rms_p_fwd(x2, scale, eps):
    return _rms_fwd(x2, scale, eps), (x2, scale)


def _rms_p_bwd(eps, res, g):
    x2, scale = res
    x = x2.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    s = scale.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = x * inv
    gy = gf * s
    # d/dx of x * rsqrt(mean(x^2)+eps):
    dx = inv * (gy - xhat * jnp.mean(gy * xhat, axis=-1, keepdims=True))
    dscale = jnp.sum(gf * xhat, axis=0)
    return dx.astype(x2.dtype), dscale.astype(scale.dtype)


_rms_norm_p.defvjp(_rms_p_fwd, _rms_p_bwd)


@register("rms_norm", "pallas")
def pallas_rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-5) -> jax.Array:
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    return _rms_norm_p(x2, scale, eps).reshape(shape)


def _ln_fwd(x2, scale, bias, eps):
    R, Dm = x2.shape
    br = _row_blocks(R)
    return pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(pl.cdiv(R, br),),
        in_specs=[
            pl.BlockSpec((br, Dm), lambda i: (i, 0)),
            pl.BlockSpec((Dm,), lambda i: (0,)),
            pl.BlockSpec((Dm,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((br, Dm), lambda i: (i, 0)),
        out_shape=_sds((R, Dm), x2.dtype, x2, scale, bias),
        interpret=_interpret(),
    )(x2, scale, bias)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _layer_norm_p(x2, scale, bias, eps):
    return _ln_fwd(x2, scale, bias, eps)


def _ln_p_fwd(x2, scale, bias, eps):
    return _ln_fwd(x2, scale, bias, eps), (x2, scale)


def _ln_p_bwd(eps, res, g):
    x2, scale = res
    x = x2.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    s = scale.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * inv
    gy = gf * s
    dx = inv * (gy - jnp.mean(gy, axis=-1, keepdims=True) - xhat * jnp.mean(gy * xhat, axis=-1, keepdims=True))
    dscale = jnp.sum(gf * xhat, axis=0)
    dbias = jnp.sum(gf, axis=0)
    return dx.astype(x2.dtype), dscale.astype(scale.dtype), dbias.astype(scale.dtype)


_layer_norm_p.defvjp(_ln_p_fwd, _ln_p_bwd)


@register("layer_norm", "pallas")
def pallas_layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float = 1e-5) -> jax.Array:
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    return _layer_norm_p(x2, scale, bias, eps).reshape(shape)
