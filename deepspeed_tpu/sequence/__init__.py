"""deepspeed_tpu.sequence: long-context attention machinery.

Reference: ``deepspeed/sequence/`` — Ulysses (``layer.py``, implemented in
``deepspeed_tpu.parallel.ulysses``) and FPDT/Ulysses-Offload
(``fpdt_layer.py``, implemented here in ``fpdt.py``); ring attention
(``deepspeed_tpu.parallel.ring_attention``) is a TPU-native addition.
"""

from deepspeed_tpu.sequence.fpdt import (
    FPDTAttention,
    chunked_attention,
    fpdt_attention,
)
