"""FPDT: fully pipelined chunked attention with host offload.

Reference: ``sequence/fpdt_layer.py`` — ``FPDT_Attention`` (:971),
``_FPDTGPUOffloadingAttentionImpl_`` (:510), ``SequenceChunk`` (:462):
process a sequence too long for HBM by chunking queries, streaming K/V
chunks from host memory with double buffering, and merging per-chunk
attention with online softmax (16× longer sequences at ~55% MFU on the
reference's hardware).

TPU design:
  - ``chunked_attention``: on-device ``lax.scan`` over K/V chunks with
    flash-style (m, l, o) accumulation — peak memory O(S·chunk) instead of
    O(S²); this is the compute core and also serves as a standalone
    memory-efficient attention.
  - ``FPDTAttention``: host-resident K/V (numpy), query chunks processed in
    sequence; the NEXT K/V chunk's host→device transfer is issued before
    computing the current one, so JAX's async dispatch overlaps DMA with
    compute (the reference's double-buffered CUDA streams).

Multi-chip status (honest-docs, round-6): host offload is SINGLE-CHIP only
on this jax/XLA version — the SPMD partitioner rejects host-memory placement
annotations, and the engine refuses ``fpdt_offload`` on multi-device meshes
(``runtime/engine.py``). The supported multi-chip long-context paths are
no-offload FPDT composed with Ulysses SP, and ring attention
(``parallel/ring_attention.py``); both cap sequence length at HBM rather
than host RAM. The reference's defining 16×-longer-via-host-offload claim is
NOT reproduced multi-chip here.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.parallel.ring_attention import _NEG_INF, _block_attend


def chunked_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, Hkv, D]
    v: jax.Array,
    chunk_size: int = 1024,
    causal: bool = True,
    q_offset: int = 0,
    alibi_slopes: Optional[jax.Array] = None,  # [H] bloom ALiBi
) -> jax.Array:
    """Exact attention via online-softmax over K/V chunks (one compiled scan).

    ``q_offset``: global position of q[0] relative to k[0] (FPDT query-chunk
    processing passes the chunk's start; 0 for self-attention).
    """
    B, Sq, H, D = q.shape
    Sk, Hkv = k.shape[1], k.shape[2]
    G = H // Hkv
    slopes2 = (None if alibi_slopes is None
               else alibi_slopes.astype(jnp.float32).reshape(Hkv, G))
    C = min(chunk_size, Sk)
    if Sk % C:
        raise ValueError(f"kv length {Sk} not divisible by chunk {C}")
    n_chunks = Sk // C

    qg = q.reshape(B, Sq, Hkv, G, D).astype(jnp.float32) * (D ** -0.5)
    kc = k.reshape(B, n_chunks, C, Hkv, D).transpose(1, 0, 2, 3, 4)
    vc = v.reshape(B, n_chunks, C, Hkv, D).transpose(1, 0, 2, 3, 4)

    m0 = jnp.full((B, Hkv, G, Sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hkv, G, Sq), jnp.float32)
    o0 = jnp.zeros((B, Sq, Hkv, G, D), jnp.float32)

    def body(carry, xs):
        m, l, o = carry
        i, kb, vb = xs
        m, l, o = _block_attend(qg, kb, vb, m, l, o, q_offset, i * C, causal,
                                slopes=slopes2)
        return (m, l, o), None

    (m, l, o), _ = jax.lax.scan(body, (m0, l0, o0), (jnp.arange(n_chunks), kc, vc))
    return _normalize_out(o, l).reshape(B, Sq, H, D).astype(q.dtype)


def _normalize_out(o, l):
    """Online-softmax epilogue shared by the compiled scan and the FPDT host
    loop: o [B,Sq,Hkv,G,D] normalized by the accumulated l [B,Hkv,G,Sq]."""
    return o / jnp.maximum(l.transpose(0, 3, 1, 2)[..., None], 1e-30)


# --------------------------------------------------------------- training VJP

def fpdt_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, Hkv, D]
    v: jax.Array,
    q_chunk: int = 1024,
    kv_chunk: int = 1024,
    causal: bool = True,
    q_offset: int = 0,
    alibi_slopes: Optional[jax.Array] = None,  # [H]
    offload: bool = False,
) -> jax.Array:
    """Differentiable chunked attention — the FPDT *training* core.

    Reference ``sequence/fpdt_layer.py:510 _FPDTGPUOffloadingAttentionImpl_``
    implements forward AND backward over (query-chunk, kv-chunk) tiles so
    training sequences scale past attention's O(S²) memory; this is the same
    math as one custom-VJP function: a double ``lax.scan`` online-softmax
    forward saving only (out, logsumexp), and a flash-style backward that
    recomputes each tile's probabilities from the saved logsumexp. Peak
    residual memory is O(S·D) (the inputs + out + lse) with O(Cq·Ck) score
    tiles — never O(S²). Causally-dead tiles are skipped with ``lax.cond``
    in both passes. Composes with Ulysses SP (heads already sharded by the
    surrounding all-to-all).

    ``offload=True`` parks the large residuals (q/k/v/out) in host memory
    between forward and backward via ``device_put`` transfers XLA schedules
    asynchronously — the reference's double-buffered host offload
    (fpdt_layer.py:462 SequenceChunk). **Single-chip only on this stack**:
    the XLA SPMD partitioner rejects host-memory placement annotations on
    multi-device meshes ("Side-effect HLO must have sharding"), and
    ``runtime/engine.py`` raises if ``fpdt_offload`` meets a multi-device
    mesh. Multi-chip long context uses no-offload FPDT (``attn_impl='fpdt'``,
    composes with Ulysses SP via the surrounding all-to-all) or ring
    attention (``sp_impl='ring'``) — sequence length capped by HBM, not by
    host RAM. See docs/parallelism.md "long context beyond HBM".
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    Cq, Ck = min(q_chunk, Sq), min(kv_chunk, Sk)
    if Sq % Cq or Sk % Ck:
        raise ValueError(f"seq {Sq}/{Sk} must divide by q_chunk {Cq} / kv_chunk {Ck}")
    return _fpdt(q, k, v, alibi_slopes, Cq, Ck, causal, q_offset, offload)


def _fpdt_prep(q, k, v, slopes, Cq, Ck):
    """Shared fwd/bwd reshapes: chunk-leading layouts + pre-scaled fp32 q."""
    B, Sq, H, D = q.shape
    Sk, Hkv = k.shape[1], k.shape[2]
    G = H // Hkv
    nq, nk = Sq // Cq, Sk // Ck
    qg = (q.reshape(B, nq, Cq, Hkv, G, D).transpose(1, 0, 2, 3, 4, 5)
          .astype(jnp.float32)) * (D ** -0.5)
    kc = k.reshape(B, nk, Ck, Hkv, D).transpose(1, 0, 2, 3, 4)
    vc = v.reshape(B, nk, Ck, Hkv, D).transpose(1, 0, 2, 3, 4)
    slopes2 = (None if slopes is None
               else slopes.astype(jnp.float32).reshape(Hkv, G))
    return qg, kc, vc, slopes2, (B, Sq, H, D, Sk, Hkv, G, nq, nk)


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _fpdt(q, k, v, slopes, Cq, Ck, causal, q_offset, offload):
    out, _ = _fpdt_fwd(q, k, v, slopes, Cq, Ck, causal, q_offset, offload)
    return out


def _fpdt_fwd(q, k, v, slopes, Cq, Ck, causal, q_offset, offload):
    qg, kc, vc, slopes2, (B, Sq, H, D, Sk, Hkv, G, nq, nk) = \
        _fpdt_prep(q, k, v, slopes, Cq, Ck)

    def q_body(_, xs):
        i, qi = xs  # qi [B, Cq, Hkv, G, D]
        q_start = q_offset + i * Cq
        m0 = jnp.full((B, Hkv, G, Cq), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, Hkv, G, Cq), jnp.float32)
        o0 = jnp.zeros((B, Cq, Hkv, G, D), jnp.float32)

        def kv_body(carry, ys):
            j, kb, vb = ys
            attend = lambda c: _block_attend(qi, kb, vb, *c, q_start, j * Ck,  # noqa: E731
                                             causal, slopes=slopes2)
            if causal:  # skip causally-dead tiles (real XLA branch, not select)
                carry = jax.lax.cond(j * Ck <= q_start + Cq - 1, attend,
                                     lambda c: c, carry)
            else:
                carry = attend(carry)
            return carry, None

        (m, l, o), _ = jax.lax.scan(kv_body, (m0, l0, o0),
                                    (jnp.arange(nk), kc, vc))
        out_i = _normalize_out(o, l)                        # [B,Cq,Hkv,G,D]
        lse_i = m + jnp.log(jnp.maximum(l, 1e-30))          # [B,Hkv,G,Cq]
        return None, (out_i, lse_i)

    _, (outs, lses) = jax.lax.scan(q_body, None, (jnp.arange(nq), qg))
    out = outs.transpose(1, 0, 2, 3, 4, 5).reshape(B, Sq, H, D).astype(q.dtype)
    if offload:
        # big residuals park in (pinned) host memory until the backward;
        # single-device placement only — the SPMD partitioner rejects these
        # annotations on multi-device meshes (engine guards the combination)
        from deepspeed_tpu.utils.compat import memory_space

        host = lambda x: jax.device_put(x, memory_space("host"))  # noqa: E731
        return out, (host(q), host(k), host(v), slopes, host(out), lses)
    return out, (q, k, v, slopes, out, lses)


def _fpdt_bwd(Cq, Ck, causal, q_offset, offload, res, dout):
    q, k, v, slopes, out, lses = res      # lses [nq, B, Hkv, G, Cq]
    if offload:
        from deepspeed_tpu.utils.compat import memory_space

        dev = lambda x: jax.device_put(x, memory_space("device"))  # noqa: E731
        q, k, v, out = dev(q), dev(k), dev(v), dev(out)
    qg, kc, vc, slopes2, (B, Sq, H, D, Sk, Hkv, G, nq, nk) = \
        _fpdt_prep(q, k, v, slopes, Cq, Ck)
    scale = D ** -0.5
    dog = (dout.reshape(B, nq, Cq, Hkv, G, D)
           .transpose(1, 0, 2, 3, 4, 5).astype(jnp.float32))
    # delta_i = rowsum(dout * out) — the softmax-jacobian diagonal term
    delta = ((dout.astype(jnp.float32) * out.astype(jnp.float32))
             .sum(-1).reshape(B, nq, Cq, Hkv, G)
             .transpose(1, 0, 3, 4, 2))                     # [nq,B,Hkv,G,Cq]

    def tile_scores(qi, kb, q_start, k_start):
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qi, kb.astype(jnp.float32),
                       precision=jax.lax.Precision.HIGHEST)
        if slopes2 is not None:
            kpos = (k_start + jnp.arange(Ck)).astype(jnp.float32)
            s = s + slopes2[None, :, :, None, None] * kpos[None, None, None, None, :]
        if causal:
            keep = (q_start + jnp.arange(Cq))[:, None] >= (k_start + jnp.arange(Ck))[None, :]
            s = jnp.where(keep[None, None, None], s, _NEG_INF)
        return s

    def q_body(carry, xs):
        dk, dv = carry  # [nk, B, Ck, Hkv, D] fp32 accumulators
        i, qi, doi, lsei, deltai = xs
        q_start = q_offset + i * Cq
        dq0 = jnp.zeros((B, Cq, Hkv, G, D), jnp.float32)

        def kv_body(carry2, ys):
            dq_i, dk, dv = carry2
            j, kb, vb = ys

            def live_fn(dq_i, dk, dv):
                s = tile_scores(qi, kb, q_start, j * Ck)
                p = jnp.exp(s - lsei[..., None])
                p = jnp.where(s <= _NEG_INF / 2, 0.0, p)    # fully-masked rows
                dv_t = jnp.einsum("bhgqk,bqhgd->bkhd", p, doi,
                                  precision=jax.lax.Precision.HIGHEST)
                dp = jnp.einsum("bqhgd,bkhd->bhgqk", doi, vb.astype(jnp.float32),
                                precision=jax.lax.Precision.HIGHEST)
                ds = p * (dp - deltai[..., None])
                dq_t = jnp.einsum("bhgqk,bkhd->bqhgd", ds, kb.astype(jnp.float32),
                                  precision=jax.lax.Precision.HIGHEST)
                dk_t = jnp.einsum("bhgqk,bqhgd->bkhd", ds, qi,
                                  precision=jax.lax.Precision.HIGHEST)
                return (dq_i + dq_t, dk.at[j].add(dk_t), dv.at[j].add(dv_t))

            if causal:
                return jax.lax.cond(
                    j * Ck <= q_start + Cq - 1, live_fn,
                    lambda a, b, c: (a, b, c), dq_i, dk, dv), None
            return live_fn(dq_i, dk, dv), None

        (dq_i, dk, dv), _ = jax.lax.scan(
            kv_body, (dq0, dk, dv), (jnp.arange(nk), kc, vc))
        return (dk, dv), dq_i

    dk0 = jnp.zeros((nk, B, Ck, Hkv, D), jnp.float32)
    dv0 = jnp.zeros((nk, B, Ck, Hkv, D), jnp.float32)
    (dk, dv), dqs = jax.lax.scan(q_body, (dk0, dv0),
                                 (jnp.arange(nq), qg, dog, lses, delta))
    dq = (dqs.transpose(1, 0, 2, 3, 4, 5).reshape(B, Sq, H, D) * scale).astype(q.dtype)
    dk = dk.transpose(1, 0, 2, 3, 4).reshape(B, Sk, Hkv, D).astype(k.dtype)
    dv = dv.transpose(1, 0, 2, 3, 4).reshape(B, Sk, Hkv, D).astype(v.dtype)
    dslopes = None if slopes is None else jnp.zeros_like(slopes)
    return dq, dk, dv, dslopes


_fpdt.defvjp(_fpdt_fwd, _fpdt_bwd)


class FPDTAttention:
    """Host-offloaded double-buffered chunked attention (reference
    ``_FPDTGPUOffloadingAttentionImpl_`` fpdt_layer.py:510).

    K/V live on host; each (query-chunk, kv-chunk) tile runs on device with
    the next kv chunk's transfer in flight. Handles sequences far beyond HBM.

    Pipelining (the reference's double-buffered CUDA streams, via JAX async
    dispatch — round-3 verdict weak item 5):
      - each kv prefetch copies its chunk into an OWNED contiguous buffer
        and issues ``device_put`` before the current tile is dispatched, so
        the H2D DMA rides under the tile compute (per-chunk copies, never a
        second full-K/V materialization — the class targets K/V near host
        RAM). Callers that can store K/V chunk-major (``[n, B, C, Hkv, D]``)
        pass ``chunk_major=True`` for zero-copy prefetches;
      - each query chunk's result stays ON DEVICE until the next chunk's
        tiles have been dispatched, so the D2H readback overlaps compute
        instead of stalling the loop at every chunk boundary.

    Forward-only by design: training at these lengths goes through the
    differentiable on-device ``chunked_attention`` (+ remat), which XLA
    schedules; this class is the inference/scoring path for sequences whose
    K/V exceed HBM.
    """

    def __init__(self, q_chunk: int = 2048, kv_chunk: int = 2048, causal: bool = True):
        self.q_chunk = q_chunk
        self.kv_chunk = kv_chunk
        self.causal = causal
        self._tile = jax.jit(self._tile_fn, static_argnames=("causal",))
        self._finish = jax.jit(self._finish_fn, static_argnames=("dtype",))

    @staticmethod
    def _tile_fn(qg, kb, vb, m, l, o, q_start, k_start, causal):
        return _block_attend(qg, kb, vb, m, l, o, q_start, k_start, causal)

    @staticmethod
    def _finish_fn(o, l, dtype):
        res = _normalize_out(o, l)
        B, Cq = res.shape[0], res.shape[1]
        return res.reshape(B, Cq, -1, res.shape[-1]).astype(dtype)

    def __call__(self, q: np.ndarray, k: np.ndarray, v: np.ndarray,
                 chunk_major: bool = False) -> np.ndarray:
        B, S, H, D = q.shape
        if chunk_major:
            n_kv, Ck = k.shape[0], k.shape[2]
            S_kv, Hkv = n_kv * Ck, k.shape[3]
        else:
            S_kv, Hkv = k.shape[1], k.shape[2]
            Ck = min(self.kv_chunk, S_kv)
            n_kv = S_kv // Ck
        G = H // Hkv
        Cq = min(self.q_chunk, S)
        if S % Cq or S_kv % Ck:
            raise ValueError(f"seq {S}/{S_kv} must divide by q_chunk {Cq} and kv_chunk {Ck}")

        def fetch(i):
            # owned per-chunk buffers: safe to hand to an async device_put
            if chunk_major:
                return jax.device_put(k[i]), jax.device_put(v[i])
            s = i * Ck
            return (jax.device_put(np.ascontiguousarray(k[:, s: s + Ck])),
                    jax.device_put(np.ascontiguousarray(v[:, s: s + Ck])))

        out = np.empty_like(q)
        pending = None  # (row slice, device result) — deferred D2H

        for qi in range(S // Cq):
            q_start = qi * Cq
            qg = jax.device_put(
                np.ascontiguousarray(
                    q[:, q_start: q_start + Cq].reshape(B, Cq, Hkv, G, D),
                    dtype=np.float32)
            ) * (D ** -0.5)
            m = jnp.full((B, Hkv, G, Cq), _NEG_INF, jnp.float32)
            l = jnp.zeros((B, Hkv, G, Cq), jnp.float32)
            o = jnp.zeros((B, Cq, Hkv, G, D), jnp.float32)
            # causal: kv chunks beyond this query chunk contribute nothing
            last_kv = n_kv if not self.causal else (q_start + Cq + Ck - 1) // Ck
            # prime the pipeline: first chunk's H2D in flight
            nxt = fetch(0)
            for ki in range(last_kv):
                kb, vb = nxt
                if ki + 1 < last_kv:
                    # issue the NEXT transfer before computing — async dispatch
                    # overlaps the contiguous DMA with the tile compute
                    nxt = fetch(ki + 1)
                m, l, o = self._tile(qg, kb, vb, m, l, o, q_start, ki * Ck, causal=self.causal)
            res = self._finish(o, l, dtype=q.dtype)
            if pending is not None:
                # fetch the PREVIOUS chunk now that this chunk's work is
                # queued — the readback rides under the current compute
                sl, prev = pending
                out[:, sl] = np.asarray(prev)
            pending = (slice(q_start, q_start + Cq), res)
        sl, prev = pending
        out[:, sl] = np.asarray(prev)
        return out
