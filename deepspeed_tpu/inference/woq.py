"""Weight-only quantization for inference (WOQ).

Reference analog: ``deepspeed/inference/quantization/`` (int4/int8 WOQ layers
+ context) and the fp-quantizer weight path (``ops/fp_quantizer/quantize.py:43
FP_Quantize``). Where the reference swaps nn.Linear for QuantizedLinear
modules, here the quantized weight is a ``WOQTensor`` — a pytree-registered
wrapper whose ``astype()`` dequantizes. Every weight read in the functional
inference model is ``leaf["kernel"].astype(cfg.dtype)``, so quantized params
drop in with no model changes, the int4/int8/fp8 bytes are what live in HBM,
and XLA fuses the dequant into the consuming matmul.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.fp_quant import (
    dequantize_fp8,
    dequantize_int4,
    quantize_fp8,
    quantize_int4,
)
from deepspeed_tpu.ops.quant import dequantize_int8, quantize_int8

_BLOCK = 2048


def _to_device(x, dev_sharding):
    """In-program host->device stream (ZeRO-Inference read path): a sharding
    constraint whose memory kind is device memory compiles to the transfer
    (same mechanism as the training engine's 'memories' offload mode).

    The spec right-aligns to the value's rank: scan over stacked layer params
    hands the wrapper a per-layer slice (leading dim gone)."""
    if dev_sharding is None:
        return x
    from jax.sharding import NamedSharding, PartitionSpec

    entries = list(dev_sharding.spec)
    if len(entries) > x.ndim:
        entries = entries[len(entries) - x.ndim:]
    elif len(entries) < x.ndim:
        entries = [None] * (x.ndim - len(entries)) + entries
    from deepspeed_tpu.utils.compat import with_memory_kind

    # the compat fallback keeps the read path traceable on backends with a
    # single memory space (CPU: the transfer degrades to a no-op placement)
    sh = with_memory_kind(
        NamedSharding(dev_sharding.mesh, PartitionSpec(*entries)), "device")
    # device_put is traceable and compiles to the host->device DMA (the
    # `memories` API); with_sharding_constraint would only annotate layout
    return jax.device_put(x, sh)


@jax.tree_util.register_pytree_node_class
class WOQTensor:
    """Quantized weight leaf. ``fmt``: 'int8' | 'int4' | 'fp8'.

    ``dev_sharding`` (set when pinned-host resident) makes ``astype`` stream
    the (small) quantized bytes to device memory before dequantizing — the
    ZeRO-Inference + WOQ composition.

    ``stacked`` marks a leaf quantized PER LEADING SLICE (the scan-layers
    ``[L, ...]`` stack): quantization blocks never cross layer boundaries,
    so ``lax.scan`` can slice the wrapper per layer (pytree children lose
    the leading dim; the static ``_shape`` aux stays the full stacked
    shape). ``astype`` tells the two states apart by the scale's rank.
    """

    def __init__(self, q: jax.Array, scale: jax.Array, fmt: str, shape: tuple,
                 dev_sharding=None, stacked: bool = False):
        self.q = q
        self.scale = scale
        self.fmt = fmt
        self._shape = tuple(shape)
        self.dev_sharding = dev_sharding
        self.stacked = stacked

    # --- pytree protocol -------------------------------------------------
    def tree_flatten(self):
        return (self.q, self.scale), (self.fmt, self._shape, self.dev_sharding, self.stacked)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1], aux[2], aux[3])

    # --- array-like surface the model reads ------------------------------
    @property
    def shape(self):
        return self._shape

    @property
    def size(self):
        n = 1
        for d in self._shape:
            n *= d
        return n

    def _dequant(self, q, scale, shape, dtype):
        if self.fmt == "int8":
            return dequantize_int8(q, scale, shape, dtype=dtype, block_size=_BLOCK)
        if self.fmt == "int4":
            return dequantize_int4(q, scale, dtype=dtype, block_size=_BLOCK).reshape(shape)
        if self.fmt == "fp8":
            return dequantize_fp8(q, scale, dtype=dtype, block_size=_BLOCK)
        raise ValueError(f"unknown WOQ format {self.fmt!r}")

    def astype(self, dtype):
        q, scale = self.q, self.scale
        if self.dev_sharding is not None:
            q = _to_device(q, self.dev_sharding[0])
            scale = _to_device(scale, self.dev_sharding[1])
        if not self.stacked:
            return self._dequant(q, scale, self._shape, dtype)
        per_shape = self._shape[1:]
        if scale.ndim >= 2:
            # full stacked read (dequantize_params / teacher-forcing path)
            return jax.vmap(lambda qq, ss: self._dequant(qq, ss, per_shape, dtype))(q, scale)
        # inside lax.scan: the wrapper was sliced to one layer
        return self._dequant(q, scale, per_shape, dtype)

    def __repr__(self):
        return (f"WOQTensor({self.fmt}, shape={self._shape}, "
                f"stacked={self.stacked}, offloaded={self.dev_sharding is not None})")


@jax.tree_util.register_pytree_node_class
class OffloadedTensor:
    """Dense weight resident in pinned host memory; ``astype`` streams it to
    the device inside the compiled forward (ZeRO-Inference without quant)."""

    def __init__(self, x: jax.Array, dev_sharding=None):
        self.x = x
        self.dev_sharding = dev_sharding

    def tree_flatten(self):
        return (self.x,), (self.dev_sharding,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux[0])

    @property
    def shape(self):
        return self.x.shape

    @property
    def size(self):
        return self.x.size

    @property
    def dtype(self):
        return self.x.dtype

    def astype(self, dtype):
        return _to_device(self.x, self.dev_sharding).astype(dtype)

    def __repr__(self):
        return f"OffloadedTensor(shape={self.x.shape})"


def _quantize_leaf(x: jax.Array, fmt: str, stacked: bool = False) -> WOQTensor:
    if fmt == "int8":
        fn = lambda v: quantize_int8(v, block_size=_BLOCK)  # noqa: E731
    elif fmt == "int4":
        fn = lambda v: quantize_int4(v, block_size=_BLOCK)  # noqa: E731
    elif fmt == "fp8":
        fn = lambda v: quantize_fp8(v, block_size=_BLOCK)  # noqa: E731
    else:
        raise ValueError(f"unknown WOQ format {fmt!r} (int8/int4/fp8)")
    if stacked:
        # per-layer quantization of a [L, ...] stack: blocks never span
        # layers, so scan slicing stays valid (see WOQTensor.stacked)
        q, s = jax.vmap(fn)(x)
    else:
        q, s = fn(x)
    return WOQTensor(q, s, fmt, x.shape, stacked=stacked)


def woq_format(quant_cfg) -> str:
    """QuantConfig -> format string. bits: 8 -> int8, 4 -> int4; dtype-style
    'fp8' accepted via bits == 8 and qtype == 'fp'."""
    qtype = getattr(quant_cfg, "qtype", "int")
    if qtype == "fp" or getattr(quant_cfg, "fp8", False):
        return "fp8"
    if quant_cfg.bits == 8:
        return "int8"
    if quant_cfg.bits == 4:
        return "int4"
    raise ValueError(f"unsupported WOQ bits={quant_cfg.bits} (8 or 4)")


# Per-tensor-class selection (``QuantConfig.tensor_classes``): which weight
# families get quantized storage. Matching is on quoted path tokens (the
# ``keystr`` idiom used everywhere in this file) so e.g. 'wo' never matches
# inside another name.
TENSOR_CLASSES = {
    "attn": ("'wq'", "'wk'", "'wv'", "'wo'"),
    "mlp": ("'w_up'", "'w_gate'", "'w_down'"),
    "experts": ("'experts'",),
    "lm_head": ("'lm_head'",),
}


def _class_selected(key: str, classes) -> bool:
    if classes is None:
        return True
    for c in classes:
        if c not in TENSOR_CLASSES:
            raise ValueError(
                f"unknown WOQ tensor class {c!r} (choose from {sorted(TENSOR_CLASSES)})")
        if any(tok in key for tok in TENSOR_CLASSES[c]):
            return True
    return False


def _eligible(key: str, shape, size: int, fmt: str, min_size: int, classes) -> bool:
    """THE quantization predicate — shared by :func:`quantize_params` and the
    pre-flight byte estimate so the guard's math can't drift from what
    actually quantizes. ``shape``/``size`` only (works on abstract leaves)."""
    if "embed" in key:
        return False
    if len(shape) < 2 or size < min_size:
        return False
    if shape[-1] % 2 and fmt == "int4":
        return False  # odd trailing dim: leave dense
    if "'layers'" in key and len(shape) < 3:
        return False  # a [L, n] stack quantizes per-row poorly; leave dense
    return _class_selected(key, classes)


def quantize_params(params: Any, fmt: str, min_size: int = 1 << 16,
                    classes=None) -> Any:
    """Quantize every 2D+ floating kernel above ``min_size`` elements.

    Norm scales, biases, and small tensors stay in the compute dtype (the
    reference WOQ also only swaps the large linears). Embeddings stay dense:
    the token-lookup (``jnp.take``) and tied-head (``.T``) sites consume the
    raw array, and the reference WOQ leaves nn.Embedding alone too.

    Leaves under a stacked ``'layers'`` subtree (scan_layers layout) are
    quantized per leading slice so ``lax.scan`` over the stack stays valid.

    ``classes`` (None = everything eligible) restricts quantization to the
    named :data:`TENSOR_CLASSES` — the reference exposes per-matrix-type WOQ
    config the same way (attention vs MLP vs head).
    """

    def leaf(path, x):
        if not isinstance(x, jax.Array) or not jnp.issubdtype(x.dtype, jnp.floating):
            return x
        key = jax.tree_util.keystr(path)
        if not _eligible(key, x.shape, x.size, fmt, min_size, classes):
            return x
        return _quantize_leaf(x, fmt, stacked="'layers'" in key)

    return jax.tree_util.tree_map_with_path(leaf, params)


def quantized_bytes_estimate(params: Any, fmt: str, min_size: int = 1 << 16,
                             classes=None, dense_itemsize: int = 2,
                             block: int = _BLOCK) -> int:
    """HBM bytes the tree will occupy AFTER :func:`quantize_params` — without
    quantizing anything (the pre-flight guard runs BEFORE materialization).

    Uses the same :func:`_eligible` predicate as the real pass: quantized
    leaves cost ``size * fmt_bytes + ceil(size/block) * 4`` (values + fp32
    scales), everything else stays at ``dense_itemsize`` (floats; integer
    leaves keep their own itemsize).
    """
    per_el = {"int8": 1.0, "fp8": 1.0, "int4": 0.5}[fmt]
    total = 0

    def leaf(path, x):
        nonlocal total
        size = int(x.size)
        floating = jnp.issubdtype(jnp.asarray(x).dtype if not hasattr(x, "dtype")
                                  else x.dtype, jnp.floating)
        if not floating:
            total += size * jnp.dtype(x.dtype).itemsize
            return x
        key = jax.tree_util.keystr(path)
        if _eligible(key, x.shape, size, fmt, min_size, classes):
            # stacked leaves quantize per layer slice; the block count is the
            # same total either way (blocks never span layers)
            if "'layers'" in key and len(x.shape) >= 3:
                per_layer = size // x.shape[0]
                nb = x.shape[0] * (-(-per_layer // min(block, max(per_layer, 1))))
            else:
                nb = -(-size // min(block, max(size, 1)))
            total += int(size * per_el) + nb * 4
        else:
            total += size * dense_itemsize
        return x

    jax.tree_util.tree_map_with_path(leaf, params)
    return total


def dequantize_params(params: Any, dtype) -> Any:
    """Dense copy (for code paths that need plain arrays, e.g. flax apply)."""
    wrapped = (WOQTensor, OffloadedTensor)
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if isinstance(x, wrapped) else x,
        params,
        is_leaf=lambda x: isinstance(x, wrapped),
    )


def offload_params(params: Any, min_size: int = 1 << 16) -> Any:
    """ZeRO-Inference placement: big non-embedding leaves move to pinned host
    memory behind stream-on-read wrappers; small leaves and the embedding
    (consumed by gather, which cannot read host operands) stay on device.

    Memory kinds resolve through ``utils/compat.with_memory_kind``: CPU
    backends expose only ``unpinned_host``, where the host/device split
    degrades to same-space placement (the offload machinery still runs
    end-to-end, it just has nowhere colder to put the bytes)."""
    from deepspeed_tpu.utils.compat import with_memory_kind

    def host(x):
        return jax.device_put(x, with_memory_kind(x.sharding, "pinned_host"))

    def leaf(path, x):
        if isinstance(x, WOQTensor):
            dev = (with_memory_kind(x.q.sharding, "device"),
                   with_memory_kind(x.scale.sharding, "device"))
            return WOQTensor(host(x.q), host(x.scale), x.fmt, x.shape,
                             dev_sharding=dev, stacked=x.stacked)
        key = jax.tree_util.keystr(path)
        # only the matmul weights go behind the stream-on-read wrapper: norm
        # scales/biases are consumed raw (no .astype read site) and embeddings
        # feed gather
        if not isinstance(x, jax.Array) or "embed" in key:
            return x
        if "'kernel'" not in key and "'experts'" not in key:
            return x
        if x.ndim < 2 or x.size < min_size:
            return x
        return OffloadedTensor(host(x), dev_sharding=with_memory_kind(x.sharding, "device"))

    return jax.tree_util.tree_map_with_path(
        leaf, params, is_leaf=lambda x: isinstance(x, WOQTensor)
    )


def woq_bytes(params: Any) -> int:
    """HBM bytes of the quantized tree (evidence the memory win is real)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total
