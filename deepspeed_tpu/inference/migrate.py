"""KV-block migration transport for disaggregated prefill/decode serving.

ISSUE 14 tentpole leg 2: after a prefill completes on a prefill-pool
replica, the request's KV blocks move to a decode-pool replica as a remote
DMA of pool pages — the T3 fused-hop pattern (PAPERS.md) pointed at pool
memory instead of a wire. Two backends, one buffer format
(:class:`~deepspeed_tpu.inference.paged.MigrationBuffer` — quantized values
+ fp32 scale pages, block-table-ordered, bytes verbatim):

- **device copy** (same process): the export gather's output arrays ARE the
  wire — the destination engine's import scatter consumes them directly.
  jax dispatch is asynchronous, so an export dispatched at a prefill
  boundary streams while the host assembles and dispatches the NEXT
  prefill; the router caps in-flight exports per source at
  ``DEFAULT_MIGRATION_DEPTH`` slots (double-buffered: page streaming of
  request N overlaps the prefill of request N+1, exactly the ``overlap.py``
  T3 discipline at migration granularity).
- **remote DMA** (real chip boundaries): :func:`remote_copy_pages` moves the
  buffer leaves between two mesh ranks through the PR-8 hop kernel —
  ``pallas_backend.permute_wire`` runs ONE ``make_async_remote_copy``
  program per hop carrying every leaf (values + scales), under a
  point-to-point permutation (:func:`transposition_perm`). Where the
  interpreter cannot discharge remote DMA (multi-axis CPU meshes) the hop
  falls back to ``lax.ppermute`` with identical semantics — the same
  honest-transport story as the collective backend.

Failure contract (the router's side of it): a migration that cannot import
(destination capacity, layout mismatch, any exception) leaves the request
live on its SOURCE replica, which degrades to mixed-mode serving for it —
an admitted request is never dropped.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

# in-flight export cap per source replica: 2 = double-buffered (the export
# of request N streams while the source prefills request N+1; a third
# would just queue behind the first on the device stream)
DEFAULT_MIGRATION_DEPTH = 2


@dataclasses.dataclass
class MigrationTicket:
    """One in-flight post-prefill migration, source replica -> destination
    replica. The export dict is the source engine's
    ``export_request`` result (buffer + geometry); ``tokens`` is the
    request's full context (prompt + generated) at export time — the
    destination re-admits with it and re-indexes its prefix cache from the
    imported (bit-identical) blocks."""

    idx: int                 # request index in the current serve() call
    uid: int                 # uid on the SOURCE replica
    src: int                 # source replica index
    dst: int                 # destination replica index
    export: Dict[str, Any]   # buffer, n_blocks, seen_tokens, pages
    tokens: np.ndarray       # full context at export time
    t_start: float           # export dispatch stamp (migration_ms anchor)
    status: str = "inflight"  # -> "done" | "failed"
    new_uid: Optional[int] = None  # uid on the destination, once imported


def transposition_perm(n: int, src: int, dst: int) -> List[Tuple[int, int]]:
    """Point-to-point migration as a full permutation of ``n`` ranks: the
    src<->dst transposition completed with identity self-edges — the shape
    both ``lax.ppermute`` and the remote-DMA hop kernel accept (the hop
    primitive is a permutation; a migration is the degenerate one)."""
    if not (0 <= src < n and 0 <= dst < n):
        raise ValueError(f"src={src}/dst={dst} out of range for {n} ranks")
    if src == dst:
        return [(i, i) for i in range(n)]
    perm = [(i, i) for i in range(n) if i not in (src, dst)]
    perm += [(src, dst), (dst, src)]
    return perm


def remote_copy_pages(leaves: Sequence[jax.Array], mesh, axis_name: str,
                      src: int, dst: int):
    """Move migration-buffer leaves from mesh rank ``src`` to rank ``dst``
    over the PR-8 remote-DMA hop kernel.

    ``leaves`` are [n, ...] arrays sharded over ``axis_name`` on their
    leading dim — rank r's shard is ITS local pages (for a migration only
    rank ``src`` carries payload; the others ride the permutation's
    identity edges). Returns leaves of the same shape where rank ``dst``'s
    shard holds rank ``src``'s pages, bytes verbatim. On a real TPU every
    hop is one ``make_async_remote_copy`` Pallas program carrying ALL
    leaves (values + scale pages together); in interpret mode on meshes the
    interpreter cannot discharge, the transport falls back to
    ``lax.ppermute`` — same permutation, same bytes.
    """
    from deepspeed_tpu.collectives import pallas_backend
    from deepspeed_tpu.utils.compat import shard_map

    n = mesh.shape[axis_name]
    perm = transposition_perm(n, src, dst)
    leaves = list(leaves)

    def hop(*shards):
        if pallas_backend.remote_dma_supported():
            moved = pallas_backend.remote_permute_leaves(
                list(shards), axis_name, perm)
        else:
            moved = [lax.ppermute(s, axis_name, perm) for s in shards]
        return tuple(moved)

    spec = P(axis_name)
    # check_vma=False: jax 0.4.x has no replication rule for pallas_call
    # (the PR-8 collective kernels disable it the same way)
    f = shard_map(hop, mesh=mesh,
                  in_specs=tuple(spec for _ in leaves),
                  out_specs=tuple(spec for _ in leaves),
                  check_vma=False)
    return list(f(*leaves))
