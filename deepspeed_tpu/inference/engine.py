"""Inference engine (v1): TP-sharded generation over a device mesh.

TPU-native analog of the reference ``InferenceEngine`` (``inference/engine.py:40``)
+ ``init_inference`` (``__init__.py:291``). Where the reference mutates the
torch module (kernel injection via ``replace_transformer_layer``, weight
slicing per policy, CUDA-graph capture), here:

  - model-parallel "group creation" = building a mesh with a ``tp`` axis and
    placing params by the model's partition rules (the AutoTP analog —
    reference ``_create_model_parallel_group`` :247 + ``module_inject``)
  - "kernel injection" = the ops registry already routes attention/norms to
    Pallas TPU kernels; no module surgery
  - "CUDA graph capture" = ``jax.jit``: the whole generate loop (prefill +
    ``lax.scan`` over decode steps + sampling) is ONE compiled XLA program
  - prompt lengths are bucketed (``seq_bucket``) so recompiles are rare
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.inference.config import InferenceConfig
from deepspeed_tpu.inference.model import KVCache, decode_step, init_cache, prefill
from deepspeed_tpu.inference.sampling import sample_logits
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig, causal_lm_partition_rules
from deepspeed_tpu.parallel.autotp import place_parameters
from deepspeed_tpu.inference.ragged import _round_up
from deepspeed_tpu.topology.mesh import build_mesh, set_mesh
from deepspeed_tpu.utils.logging import log_dist, logger


class InferenceEngine:
    """Generation engine over a TP(×DP) mesh (reference ``InferenceEngine``)."""

    def __init__(
        self,
        model_config: TransformerConfig,
        params: Any,
        config: InferenceConfig,
        mesh: Optional[Mesh] = None,
    ):
        self.model_config = model_config
        self.config = config
        tp = config.tensor_parallel.tp_size if config.tensor_parallel.enabled else 1
        if mesh is None:
            mesh = build_mesh(axis_sizes={"tp": tp, "dp": -1})
        self.mesh = mesh
        set_mesh(mesh)
        self.module = CausalLM(model_config)

        # Place params: TP partition rules over the mesh, inference dtype.
        dtype = config.jax_dtype
        if dtype == jnp.int8:
            raise ValueError(
                "dtype='int8' would truncate weights via astype; int8 weights "
                "are weight-only quantization — use quant={'enabled': True, 'bits': 8}"
            )
        nvme_mode = config.zero_inference.enabled and config.zero_inference.offload == "nvme"
        woq_on = config.quant.enabled and not nvme_mode
        tp_size = max(mesh.shape["tp"], 1)
        # WOQ ordering vs placement: on a tp=1 mesh quantization runs BEFORE
        # placement, so the dense weights never materialize on device and the
        # guard's quantized byte formula is the true placement peak. On tp>1
        # the pre-quantized flat layout can't ride the name-based dim rules
        # (it would place replicated — MORE per-device bytes than a dense tp
        # shard for tp>2), so those meshes keep the original flow: place the
        # dense shards, then quantize in place.
        pre_quant = woq_on and tp_size == 1
        if config.hbm_check != "off" and not config.zero_inference.enabled:
            # refuse/warn BEFORE placement (an over-budget materialization
            # wedges this platform without raising); skipped when
            # zero_inference keeps the big weights off-device. With
            # pre-placement WOQ the estimate is the QUANTIZED byte formula
            # (values + scales through the same eligibility predicate the
            # real pass applies) — a model that only fits quantized must be
            # admitted; tp>1 keeps the dense-shard upper bound (that IS the
            # placement peak there).
            from deepspeed_tpu.utils.hbm import check_hbm_fit

            dtype_b = jnp.dtype(dtype).itemsize
            if pre_quant:
                from deepspeed_tpu.inference.woq import (
                    quantized_bytes_estimate,
                    woq_format,
                )

                need = quantized_bytes_estimate(
                    params, woq_format(config.quant),
                    min_size=config.quant.min_leaf_size,
                    classes=config.quant.tensor_classes, dense_itemsize=dtype_b)
            else:
                n_elems = sum(x.size for x in jax.tree_util.tree_leaves(params))
                need = n_elems * dtype_b // tp_size
            check_hbm_fit(need, what="init_inference param placement",
                          mode=config.hbm_check)
        if woq_on:
            # WOQ: int8/int4/fp8 bytes in HBM, dequant fused into each matmul
            # (reference inference/quantization + fp_quantizer; see woq.py).
            # In NVMe mode quantization happens per layer slice inside
            # NVMeStreamedParams instead (stacked-tree quant breaks slicing).
            from deepspeed_tpu.inference.woq import quantize_params, woq_bytes, woq_format

            fmt = woq_format(config.quant)
            min_size = config.quant.min_leaf_size
            classes = config.quant.tensor_classes
            dense_bytes = sum(
                x.size * jnp.dtype(dtype).itemsize
                for x in jax.tree_util.tree_leaves(params))
            if pre_quant:
                params = quantize_params(params, fmt, min_size=min_size,
                                         classes=classes)
                q_bytes = woq_bytes(params)
            self.params = place_parameters(params, mesh, causal_lm_partition_rules, dtype)
            if not pre_quant:
                # tp>1: quantize the placed shards (sharding preserved by the
                # jitted per-leaf math; transient peak = dense + quantized)
                self.params = jax.jit(lambda p: quantize_params(
                    p, fmt, min_size=min_size, classes=classes))(self.params)
                q_bytes = woq_bytes(self.params)
            log_dist(
                f"WOQ[{fmt}]: weights {dense_bytes/1e6:.0f} MB -> {q_bytes/1e6:.0f} MB",
                ranks=[0],
            )
        else:
            self.params = place_parameters(params, mesh, causal_lm_partition_rules, dtype)

        self._streamed = None  # NVMe mode: layer-streamed forward/generate
        if config.zero_inference.enabled:
            # ZeRO-Inference: big weights (quantized or dense) leave HBM.
            # 'cpu': pinned host memory behind stream-on-read wrappers — the
            # compiled forward transfers each layer's weights as it needs
            # them. 'nvme': weights live ON DISK through the AIO pool, at
            # most num_buffers layers in RAM — serves models larger than
            # host memory (reference partitioned_param_swapper.py:37). Both
            # compose with WOQ: 4x smaller weights -> 4x less link/disk
            # traffic, the reference's headline ZeRO-Inference + quant combo.
            zcfg = config.zero_inference
            if zcfg.offload == "cpu":
                from deepspeed_tpu.inference.woq import offload_params

                self.params = offload_params(self.params, min_size=zcfg.min_leaf_size)
            elif zcfg.offload == "nvme":
                if not zcfg.nvme_path:
                    raise ValueError("zero_inference.offload='nvme' requires 'nvme_path'")
                from deepspeed_tpu.inference.zero_inference import (
                    NVMeStreamedParams,
                    StreamedForward,
                )

                quant_fmt = None
                if config.quant.enabled:
                    from deepspeed_tpu.inference.woq import woq_format

                    quant_fmt = woq_format(config.quant)
                streamed_params = NVMeStreamedParams(
                    self.params, zcfg.nvme_path, num_buffers=zcfg.num_buffers,
                    quant_fmt=quant_fmt, quant_min_size=config.quant.min_leaf_size)
                self._streamed = StreamedForward(streamed_params, model_config, dtype)
                # only the resident (non-layer) params stay in self.params
                self.params = streamed_params.resident
            else:
                raise ValueError(f"zero_inference.offload={zcfg.offload!r} (cpu|nvme)")

        n_params = sum(x.size for x in jax.tree_util.tree_leaves(self.params))
        log_dist(f"InferenceEngine: {n_params/1e6:.1f}M params, mesh={dict(mesh.shape)}, dtype={config.dtype}")
        self._generate_cache: Dict[tuple, Any] = {}

        def fwd(p, batch):
            if config.quant.enabled or config.zero_inference.enabled:
                from deepspeed_tpu.inference.woq import dequantize_params

                p = dequantize_params(p, dtype)  # flax path needs plain arrays
            return self.module.apply({"params": p}, batch, train=False)

        # Recompile detection (diagnostics/recompile.py): the seq_bucket
        # design claims recompiles are rare — with the detector that claim is
        # checked on every dispatch, and a violation names the argument that
        # drifted (e.g. an unbucketed mask shape).
        self._fwd_detector = self._gen_detector = None
        if config.recompile_warnings:
            from deepspeed_tpu.diagnostics.recompile import RecompileDetector

            self._fwd_detector = RecompileDetector(
                "inference.forward", arg_names=("params", "batch"))
            self._gen_detector = RecompileDetector(
                "inference.generate", arg_names=("params", "ids", "mask", "rng"))
        self._forward = jax.jit(fwd)
        if self._fwd_detector is not None:
            self._forward = self._fwd_detector.wrap(self._forward)

    # ------------------------------------------------------------------
    def close(self) -> None:
        """Release held resources (NVMe mode: AIO thread pool + layer files).

        Reference parity: the engine-loop teardown around
        ``AsyncPartitionedParameterSwapper``; safe to call on any engine."""
        if self._streamed is not None:
            self._streamed.p.close()
            self._streamed = None

    # ------------------------------------------------------------------
    def refresh_params(self, params: Any) -> None:
        """Swap in new parameter VALUES keeping placements and compiled
        functions (the hybrid-engine fast path: same shapes/shardings, so the
        jit caches stay valid — no retrace, no recompile)."""
        if self.config.quant.enabled or self.config.zero_inference.enabled:
            raise NotImplementedError(
                "refresh_params on a WOQ/ZeRO-Inference engine: the param tree "
                "holds wrapped (quantized/host-offloaded) leaves that cannot be "
                "value-swapped in place; run the hybrid engine without these modes"
            )
        dtype = self.config.jax_dtype

        def _replace(old, new):
            arr = jnp.asarray(new)
            if jnp.issubdtype(arr.dtype, jnp.floating):
                arr = arr.astype(dtype)
            return jax.device_put(arr, old.sharding)

        self.params = jax.tree_util.tree_map(_replace, self.params, params)

    # ------------------------------------------------------------------
    def forward(self, batch) -> jax.Array:
        """Full-sequence forward -> logits (teacher-forcing / scoring path)."""
        if self._streamed is not None:
            raise NotImplementedError(
                "full-sequence forward() under zero_inference.offload='nvme': "
                "the layer-streamed engine serves generate(); score with a "
                "cpu-offload or resident engine")
        if not isinstance(batch, dict):
            batch = {"input_ids": jnp.asarray(batch)}
        _, logits = self._forward(self.params, batch)
        return logits

    __call__ = forward

    # ------------------------------------------------------------------
    def _build_generate(self, B, S_pad, new_tokens, sample_cfg, eos_id, pad_id):
        cfg = self.model_config
        kv_dtype = self.config.kv_dtype
        max_len = S_pad + new_tokens

        def gen(params, ids, mask, rng):
            cache = init_cache(cfg, B, max_len, kv_dtype)
            logits, cache = prefill(params, cfg, cache, ids, mask)
            rngs = jax.random.split(rng, new_tokens)
            tok = sample_logits(logits, rngs[0], **sample_cfg)
            done = tok == eos_id if eos_id is not None else jnp.zeros((B,), jnp.bool_)

            def body(carry, step_rng):
                cache, tok, done = carry
                logits, cache = decode_step(params, cfg, cache, tok)
                nxt = sample_logits(logits, step_rng, **sample_cfg)
                if eos_id is not None:
                    nxt = jnp.where(done, pad_id, nxt)
                    done = done | (nxt == eos_id)
                return (cache, nxt, done), nxt

            (_, _, _), rest = jax.lax.scan(body, (cache, tok, done), rngs[1:])
            return jnp.concatenate([tok[:, None], rest.T], axis=1)  # [B, new_tokens]

        return jax.jit(gen)

    def generate(
        self,
        input_ids,
        attention_mask=None,
        max_new_tokens: int = 32,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: int = 0,
        top_p: float = 1.0,
        eos_token_id: Optional[int] = None,
        pad_token_id: int = 0,
        seed: int = 0,
    ) -> np.ndarray:
        """Generate continuations for right-padded prompts.

        Returns the full sequences ``[B, S + max_new_tokens]`` (prompt + new
        tokens; rows stop emitting after ``eos_token_id``).
        """
        ids = np.asarray(input_ids)
        B, S = ids.shape
        if attention_mask is None:
            attention_mask = np.ones((B, S), np.bool_)
        amask = np.array(attention_mask, np.bool_)  # copy: never mutate caller's mask
        # Cache slots are written in order, so slot index must equal token
        # position: normalize HF-style left-padded rows to right-padding by
        # compacting each row's real tokens to the front.
        if not (amask[:, :-1] >= amask[:, 1:]).all():
            ids = ids.copy()
            for r in range(B):
                keep = ids[r, amask[r]]
                ids[r, : keep.size] = keep
                ids[r, keep.size:] = 0
                amask[r, : keep.size] = True
                amask[r, keep.size:] = False
        if self.config.max_out_tokens and max_new_tokens > self.config.max_out_tokens:
            raise ValueError(
                f"max_new_tokens={max_new_tokens} exceeds config max_out_tokens={self.config.max_out_tokens}"
            )
        if self.config.max_batch_size and B > self.config.max_batch_size:
            raise ValueError(f"batch {B} exceeds config max_batch_size={self.config.max_batch_size}")
        S_pad = _round_up(max(S, 1), self.config.seq_bucket)
        if S_pad + max_new_tokens > self.model_config.max_seq_len:
            raise ValueError(
                f"prompt (padded to {S_pad}) + max_new_tokens={max_new_tokens} exceeds "
                f"model max_seq_len={self.model_config.max_seq_len}; position tables would clamp"
            )
        mask = np.zeros((B, S_pad), np.bool_)
        mask[:, :S] = amask
        padded = np.zeros((B, S_pad), ids.dtype)
        padded[:, :S] = ids

        sample_cfg = dict(do_sample=do_sample, temperature=temperature, top_k=top_k, top_p=top_p)
        if self._streamed is not None:
            from deepspeed_tpu.inference.zero_inference import streamed_generate

            new = streamed_generate(
                self._streamed, self.model_config, self.config.kv_dtype,
                padded, mask, max_new_tokens, sample_cfg,
                eos_token_id, pad_token_id, jax.random.PRNGKey(seed))
            return np.concatenate([ids, new], axis=1)
        key = (B, S_pad, max_new_tokens, tuple(sorted(sample_cfg.items())), eos_token_id, pad_token_id)
        if key not in self._generate_cache:
            gen_fn = self._build_generate(
                B, S_pad, max_new_tokens, sample_cfg, eos_token_id, pad_token_id
            )
            if self._gen_detector is not None:
                # each bucket's first compile is expected (that IS the
                # bucketing design); a compile after that on the same bucket
                # is a real recompile and warns with the shape diff
                gen_fn = self._gen_detector.wrap(
                    gen_fn, label=f"generate[B={B},S={S_pad},new={max_new_tokens}]")
                n = len(self._generate_cache) + 1
                if n > self.config.max_generate_buckets:
                    logger.warning(
                        f"generate compile cache at {n} programs (> "
                        f"max_generate_buckets={self.config.max_generate_buckets}):"
                        " unbounded (B, S_pad, max_new_tokens) variety defeats "
                        "the bucketing — coarsen seq_bucket or fix "
                        "max_new_tokens")
            self._generate_cache[key] = gen_fn
        rng = jax.random.PRNGKey(seed)
        new = np.asarray(self._generate_cache[key](self.params, jnp.asarray(padded), jnp.asarray(mask), rng))
        return np.concatenate([ids, new], axis=1)


def init_inference(
    model: Union[TransformerConfig, Any] = None,
    config: Union[InferenceConfig, Dict, None] = None,
    params: Any = None,
    model_config: Optional[TransformerConfig] = None,
    mesh: Optional[Mesh] = None,
    **kwargs,
) -> InferenceEngine:
    """Build an inference engine (reference ``deepspeed.init_inference``
    ``__init__.py:291``). Accepts a ``TransformerConfig`` + params pytree, or a
    training engine (its master params are reused — the HybridEngine-lite
    path)."""
    if config is None:
        config = {}
    if isinstance(config, dict):
        config = InferenceConfig(**{**config, **kwargs})
    # accept a training engine directly
    if hasattr(model, "state") and hasattr(model, "model"):
        engine = model
        params = jax.device_get(engine.state.params)
        mcfg = getattr(engine.model, "transformer_config", None) or model_config
        if mcfg is None:
            raise ValueError("pass model_config= when initializing from a training engine")
        return InferenceEngine(mcfg, params, config, mesh=mesh)
    if isinstance(model, TransformerConfig):
        if params is None:
            raise ValueError("params pytree required alongside a TransformerConfig")
        return InferenceEngine(model, params, config, mesh=mesh)
    raise TypeError(f"unsupported model argument {type(model)}")
