"""Ragged-batch state management for continuous batching.

TPU-native analog of the reference FastGen ragged layer
(``inference/v2/ragged/``): ``BlockedAllocator`` (blocked_allocator.py:11),
``DSSequenceDescriptor`` (sequence_descriptor.py), ``DSStateManager``
(ragged_manager.py:19), and ``RaggedBatchWrapper`` (ragged_wrapper.py).

All of this is host-side bookkeeping (numpy, no device work): the device sees
only the dense arrays a ``RaggedBatch`` assembles — padded token/position
matrices plus per-sequence block tables into the paged KV pool. Static shape
buckets keep XLA recompiles rare; the pad rows write to a dedicated trash slot
in the pool (see ``paged.py``).

Because this layer sits on the serving hot path (one assembly per dispatched
step), everything here is O(1)-per-item and vectorized:

  - ``BlockedAllocator`` is a preallocated int32 free *stack* plus a boolean
    free bitmap — allocate/free are numpy slice copies, no Python-level
    per-block work (the reference's torch-tensor free list, same idea).
  - ``SequenceDescriptor`` carries its block table as a preallocated numpy
    row, so copying it into the batch's ``block_tables`` is one memcpy.
  - ``BatchStaging`` keeps one set of pinned staging buffers per
    (rows, chunk) bucket, reused across steps — steady-state assembly does
    zero allocation and writes tokens/positions with vectorized masked
    scatters instead of per-token Python loops.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class BlockedAllocator:
    """O(1)-per-block free-list allocator for KV-cache blocks (reference
    ``BlockedAllocator`` inference/v2/ragged/blocked_allocator.py:11).

    A list free stack plus a ``bytearray`` free bitmap: C-level slice
    pops/extends move whole batches, the bitmap gives ~40ns double-free
    detection per block, and no numpy call overhead rides the small-alloc
    path (a decode step allocates a handful of blocks; numpy's per-call
    fixed cost would dominate it). ``allocate`` returns an int32 ndarray so
    downstream block-table writes stay vectorized. ``free`` validates the
    whole batch before mutating — a bad call leaves the allocator unchanged.

    Ref-counted sharing (prefix cache, ISSUE 12): every allocated block
    carries a reference count (``allocate`` sets it to 1). ``share`` adds a
    holder, ``release`` drops one and returns the block to the free stack at
    zero. ``free`` keeps the strict single-owner contract: freeing a block
    another holder still references raises. All four ops validate the whole
    batch before mutating and roll back on error — a bad call leaves the
    bitmap, the refcounts, and the stack unchanged (invariant:
    ``_refs[b] == 0  <=>  _state[b] == 1`` i.e. free).
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"need at least 1 block, got {num_blocks}")
        self.num_blocks = num_blocks
        self._free_stack: List[int] = list(range(num_blocks - 1, -1, -1))
        self._state = bytearray(b"\x01" * num_blocks)  # 1 = free
        self._refs: List[int] = [0] * num_blocks  # holders per block

    @property
    def free_blocks(self) -> int:
        return len(self._free_stack)

    def refcount(self, block: int) -> int:
        """Holders of ``block`` (0 = free)."""
        return self._refs[block]

    def allocate(self, n: int) -> np.ndarray:
        stack = self._free_stack
        if n > len(stack):
            raise RuntimeError(f"cannot allocate {n} blocks ({len(stack)} free)")
        if n == 0:
            return np.empty((0,), np.int32)
        out = stack[-n:]
        del stack[-n:]
        state = self._state
        refs = self._refs
        for b in out:
            state[b] = 0
            refs[b] = 1
        return np.asarray(out, dtype=np.int32)

    def free(self, blocks: Sequence[int]) -> None:
        """Strict single-owner free: every block must have exactly one holder.
        Freeing a shared block (refcount > 1) raises — use ``release`` for
        refcounted holders."""
        lst = blocks.tolist() if isinstance(blocks, np.ndarray) else list(blocks)
        if not lst:
            return
        state = self._state
        refs = self._refs
        num = self.num_blocks
        i = 0
        try:
            for i, b in enumerate(lst):
                if b < 0 or b >= num or state[b]:  # bitmap catches in-call dupes too
                    raise ValueError(f"bad free of block {b}")
                if refs[b] != 1:
                    raise ValueError(
                        f"free of shared block {b} (refcount {refs[b]}); "
                        "holders must release, not free")
                state[b] = 1
                refs[b] = 0
        except ValueError:
            for b in lst[:i]:  # roll back: a bad call leaves state unchanged
                state[b] = 0
                refs[b] = 1
            raise
        self._free_stack.extend(lst)

    def share(self, blocks: Sequence[int]) -> None:
        """Add one holder to each allocated block (batch-validated: a bad id
        or a free block anywhere in the call leaves every refcount
        unchanged)."""
        lst = blocks.tolist() if isinstance(blocks, np.ndarray) else list(blocks)
        if not lst:
            return
        refs = self._refs
        num = self.num_blocks
        i = 0
        try:
            for i, b in enumerate(lst):
                if b < 0 or b >= num or refs[b] < 1:
                    raise ValueError(f"share of unallocated block {b}")
                refs[b] += 1
        except ValueError:
            for b in lst[:i]:
                refs[b] -= 1
            raise
        return None

    def release(self, blocks: Sequence[int]) -> int:
        """Drop one holder from each block; blocks reaching zero holders
        return to the free stack. Releasing a free block (double release)
        raises, with full rollback. Returns how many blocks became free."""
        lst = blocks.tolist() if isinstance(blocks, np.ndarray) else list(blocks)
        if not lst:
            return 0
        state = self._state
        refs = self._refs
        num = self.num_blocks
        freed: List[int] = []
        i = 0
        try:
            for i, b in enumerate(lst):
                if b < 0 or b >= num or refs[b] < 1:
                    raise ValueError(f"double release of block {b}")
                refs[b] -= 1
                if refs[b] == 0:
                    state[b] = 1
                    freed.append(b)
        except ValueError:
            for b in lst[:i]:  # roll back refcounts AND the bitmap
                if refs[b] == 0:
                    state[b] = 0
                refs[b] += 1
            raise
        self._free_stack.extend(freed)
        return len(freed)


@dataclasses.dataclass
class SequenceDescriptor:
    """Per-sequence tracking (reference ``DSSequenceDescriptor``).

    The block table is a preallocated int32 row (``_table[:n_blocks]``) so
    batch assembly copies it with one vectorized write.
    """

    uid: int
    seen_tokens: int = 0
    n_blocks: int = 0
    _table: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros((8,), np.int32))

    @property
    def blocks(self) -> np.ndarray:
        """Live block ids (view — do not mutate)."""
        return self._table[: self.n_blocks]

    def append_blocks(self, new: np.ndarray) -> None:
        need = self.n_blocks + len(new)
        if need > len(self._table):
            cap = max(need, 2 * len(self._table))
            table = np.zeros((cap,), np.int32)
            table[: self.n_blocks] = self._table[: self.n_blocks]
            self._table = table
        self._table[self.n_blocks: need] = new
        self.n_blocks = need

    def blocks_needed(self, new_tokens: int, block_size: int) -> int:
        total = self.seen_tokens + new_tokens
        need = -(-total // block_size)  # ceil
        return max(0, need - self.n_blocks)


class StateManager:
    """uid -> sequence state + block accounting (reference ``DSStateManager``
    inference/v2/ragged/ragged_manager.py:19)."""

    def __init__(self, num_blocks: int, block_size: int, max_seqs: int = 256,
                 max_blocks_per_seq: Optional[int] = None):
        self.allocator = BlockedAllocator(num_blocks)
        self.block_size = block_size
        self.max_seqs = max_seqs
        self.max_blocks_per_seq = max_blocks_per_seq
        self._seqs: Dict[int, SequenceDescriptor] = {}

    @property
    def n_active(self) -> int:
        return len(self._seqs)

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    @property
    def utilization(self) -> float:
        """Fraction of the KV pool's blocks currently allocated (the
        ``serving/kv_pool_utilization`` gauge)."""
        total = self.allocator.num_blocks
        return (total - self.allocator.free_blocks) / total

    def get(self, uid: int) -> Optional[SequenceDescriptor]:
        return self._seqs.get(uid)

    def get_or_create(self, uid: int) -> SequenceDescriptor:
        if uid not in self._seqs:
            if len(self._seqs) >= self.max_seqs:
                raise RuntimeError(f"max_seqs={self.max_seqs} active sequences reached")
            cap = self.max_blocks_per_seq or 8
            self._seqs[uid] = SequenceDescriptor(uid, _table=np.zeros((cap,), np.int32))
        return self._seqs[uid]

    def can_schedule(self, uids: Sequence[int], token_counts: Sequence[int]) -> bool:
        """Admission check (reference ``InferenceEngineV2.can_schedule`` :184)."""
        need = 0
        fresh = 0
        for uid, n in zip(uids, token_counts):
            seq = self._seqs.get(uid)
            if seq is None:
                fresh += 1
                total_blocks = -(-n // self.block_size)
                need += total_blocks
            else:
                total_blocks = seq.n_blocks + seq.blocks_needed(n, self.block_size)
                need += seq.blocks_needed(n, self.block_size)
            if self.max_blocks_per_seq is not None and total_blocks > self.max_blocks_per_seq:
                return False  # sequence would exceed engine max_seq_len
        if len(self._seqs) + fresh > self.max_seqs:
            return False
        return need <= self.allocator.free_blocks

    def extend(self, uid: int, new_tokens: int) -> SequenceDescriptor:
        """Ensure blocks exist for ``new_tokens`` more tokens of ``uid``."""
        seq = self.get_or_create(uid)
        need = seq.blocks_needed(new_tokens, self.block_size)
        if need:
            seq.append_blocks(self.allocator.allocate(need))
        return seq

    def flush(self, uid: int) -> None:
        """Release a finished sequence (reference ``flush_uid`` engine_v2.py).
        Refcount-aware: blocks the prefix cache still holds stay allocated
        (the sequence drops its reference); exclusively-owned blocks return
        to the free stack — identical to ``free`` when nothing is shared."""
        seq = self._seqs.pop(uid, None)
        if seq is not None and seq.n_blocks:
            self.allocator.release(seq.blocks)


# --------------------------------------------------------------- prefix cache
@dataclasses.dataclass
class PrefixHit:
    """Result of a prefix-cache lookup against a prompt.

    ``blocks`` are FULL cached blocks covering ``len(blocks) * block_size``
    leading tokens (already position-aligned: chain keys start at position
    0, so a hit is only possible for identically positioned content).
    ``cow_block``/``cow_len`` describe an optional partial hit one block
    deeper: a cached block whose first ``cow_len`` tokens match the prompt's
    next tokens — reusable via copy-on-write at the first divergent token.
    """

    blocks: List[int]
    cow_block: Optional[int] = None
    cow_len: int = 0

    @property
    def n_blocks(self) -> int:
        return len(self.blocks)


@dataclasses.dataclass
class _PrefixEntry:
    key: bytes
    block: int
    tokens: np.ndarray  # the block_size token ids this block's KV encodes
    parent: bytes  # chain key of the preceding prefix ('' for block 0)
    content_hash: Optional[str] = None  # blake2b over the quantized pool bytes


class PrefixCache:
    """Content-addressed KV-block reuse over the paged pool (ROADMAP #1b).

    Host-side index: chain-hash of position-aligned token blocks -> pool
    block id, with the allocator's refcounts making shared blocks safe
    (the cache itself holds one reference per entry; sequences reusing a
    block hold their own). Each entry additionally records a blake2b digest
    of the block's *quantized pool bytes* (values + scale pages together —
    exactly the PR-10 layout) at insert time: the cached artifact IS the
    quantized bytes attention reads, so a hit is never re-quantized and the
    digest pins that sharing/COW/eviction never corrupted the stored bytes
    (asserted by the correctness tests and the nightly smoke).

    LRU eviction: entries release their block reference in LRU order when
    ``capacity_blocks`` is exceeded or the engine needs blocks back
    (``evict_one`` under admission pressure). Releasing while a live
    sequence still references the block only drops the cache's hold — the
    block returns to the free stack at refcount zero.
    """

    def __init__(self, allocator: BlockedAllocator, block_size: int,
                 capacity_blocks: Optional[int] = None):
        from collections import OrderedDict

        self.allocator = allocator
        self.block_size = block_size
        self.capacity_blocks = capacity_blocks
        self._entries: "OrderedDict[bytes, _PrefixEntry]" = OrderedDict()
        self._children: Dict[bytes, List[bytes]] = {}
        # accounting for serving/prefix_* metrics
        self.lookups = 0
        self.hits = 0  # lookups that reused >= 1 token
        self.hit_tokens = 0  # tokens served from cache (incl. COW prefixes)
        self.insertions = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    @staticmethod
    def _chain_key(parent: bytes, tokens: np.ndarray) -> bytes:
        import hashlib

        h = hashlib.blake2b(parent, digest_size=16)
        h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
        return h.digest()

    # ----------------------------------------------------------------- lookup
    def match(self, tokens: np.ndarray) -> PrefixHit:
        """Longest cached prefix of ``tokens``, full blocks first, then an
        optional COW partial block. Reuse is capped at ``len(tokens) - 1``
        so at least one token remains to prefill (the step that samples the
        first new token needs a non-empty row)."""
        bs = self.block_size
        usable = max(len(tokens) - 1, 0)
        key = b""
        blocks: List[int] = []
        pos = 0
        while pos + bs <= usable:
            k = self._chain_key(key, tokens[pos: pos + bs])
            e = self._entries.get(k)
            if e is None:
                break
            self._entries.move_to_end(k)  # LRU touch
            blocks.append(e.block)
            key = k
            pos += bs
        # partial hit one block deeper: longest common prefix against any
        # cached child of the matched chain -> COW at the divergent token
        cow_block, cow_len, cow_key = None, 0, None
        rest = np.asarray(tokens[pos:usable], np.int32)
        if len(rest) > 0:
            for ck in self._children.get(key, ()):
                e = self._entries.get(ck)
                if e is None:
                    continue
                n = min(len(rest), bs)
                lcp = int((e.tokens[:n] == rest[:n]).cumprod().sum())
                if lcp > cow_len and lcp < bs:
                    cow_block, cow_len, cow_key = e.block, lcp, ck
            if cow_key is not None:
                self._entries.move_to_end(cow_key)
        return PrefixHit(blocks=blocks, cow_block=cow_block, cow_len=cow_len)

    def record(self, hit: Optional[PrefixHit]) -> None:
        """Count one ADMISSION's lookup outcome. Deliberately separate from
        ``match``: admission may re-probe the same stalled request every
        scheduling round while the pool is full — counting at match time
        would let one stalled request skew ``serving/prefix_hit_rate`` by
        its retry count."""
        self.lookups += 1
        if hit is not None and (hit.blocks or hit.cow_len):
            self.hits += 1
            self.hit_tokens += len(hit.blocks) * self.block_size + hit.cow_len

    @property
    def hit_rate(self) -> float:
        """Fraction of admissions that reused at least one cached token."""
        return self.hits / self.lookups if self.lookups else 0.0

    # ----------------------------------------------------------------- insert
    def insert(self, tokens: np.ndarray, blocks: Sequence[int],
               hasher=None) -> int:
        """Index the FULL blocks of ``tokens`` (``blocks[i]`` holds tokens
        ``[i*bs, (i+1)*bs)``). Already-cached prefixes are skipped; each new
        entry takes one ``share`` reference on its block and records
        ``hasher(block_id)`` (the quantized-bytes digest) when a hasher is
        given — called only for NEW entries, so re-inserting a warm prefix
        costs no device fetch. Returns the number of entries added."""
        bs = self.block_size
        n_full = min(len(tokens) // bs, len(blocks))
        key = b""
        added = 0
        for i in range(n_full):
            chunk = np.asarray(tokens[i * bs: (i + 1) * bs], np.int32)
            k = self._chain_key(key, chunk)
            if k not in self._entries:
                if self.capacity_blocks is not None:
                    while (len(self._entries) >= self.capacity_blocks
                           and self.evict_one()):
                        pass
                    if len(self._entries) >= self.capacity_blocks:
                        break
                self.allocator.share([int(blocks[i])])
                self._entries[k] = _PrefixEntry(
                    key=k, block=int(blocks[i]), tokens=chunk.copy(),
                    parent=key,
                    content_hash=hasher(int(blocks[i])) if hasher else None)
                self._children.setdefault(key, []).append(k)
                self.insertions += 1
                added += 1
            else:
                self._entries.move_to_end(k)
            key = k
        return added

    def entry_for_block(self, block: int) -> Optional[_PrefixEntry]:
        for e in self._entries.values():
            if e.block == block:
                return e
        return None

    # --------------------------------------------------------------- eviction
    def evict_one(self) -> bool:
        """Release the LRU entry's block reference. Returns False when
        empty."""
        if not self._entries:
            return False
        key, e = next(iter(self._entries.items()))
        del self._entries[key]
        sibs = self._children.get(e.parent)
        if sibs is not None:
            try:
                sibs.remove(key)
            except ValueError:
                pass
            if not sibs:
                del self._children[e.parent]
        self.allocator.release([e.block])
        self.evictions += 1
        return True

    def clear(self) -> None:
        while self.evict_one():
            pass


@dataclasses.dataclass
class RaggedBatch:
    """Dense view of one scheduling step (reference ``RaggedBatchWrapper``).

    Rows are sequences; pad rows have ``new_lens == 0``. ``tokens`` is
    right-padded to the chunk bucket; ``block_tables`` is padded with 0 (pad
    slots never read: masked by position; never written: writes route to the
    trash slot).

    When assembled through a ``BatchStaging``, the arrays are views into that
    staging pool and are overwritten by the next assembly of the same
    (rows, chunk) bucket — consume (i.e. ``jnp.asarray``) before rebuilding.
    """

    uids: List[int]
    tokens: np.ndarray  # [N, C] int32
    positions: np.ndarray  # [N, C] int32 (global position of each new token)
    new_lens: np.ndarray  # [N] int32
    block_tables: np.ndarray  # [N, P] int32
    seen: np.ndarray  # [N] int32 (tokens already in cache, before this step)

    @property
    def n_rows(self) -> int:
        return self.tokens.shape[0]


class BatchStaging:
    """Reusable per-(rows, chunk)-bucket staging buffers for batch assembly.

    One set of host arrays per bucket, zeroed and refilled in place each step
    — the device copy (``jnp.asarray`` at dispatch) is the only per-step
    allocation left. ``allocations``/``reuses`` are exposed so tests and the
    serving benchmark can assert steady-state reuse.
    """

    def __init__(self, max_pages: int):
        self.max_pages = max_pages
        self._bufs: Dict[Tuple[int, int], Dict[str, np.ndarray]] = {}
        self._dirty_rows: Dict[Tuple[int, int], int] = {}
        self.allocations = 0
        self.reuses = 0

    def acquire(self, rows: int, chunk: int) -> Dict[str, np.ndarray]:
        key = (rows, chunk)
        b = self._bufs.get(key)
        if b is None:
            b = {
                "tokens": np.zeros((rows, chunk), np.int32),
                "positions": np.zeros((rows, chunk), np.int32),
                "new_lens": np.zeros((rows,), np.int32),
                "block_tables": np.zeros((rows, self.max_pages), np.int32),
                "seen": np.zeros((rows,), np.int32),
            }
            self._bufs[key] = b
            self.allocations += 1
        else:
            self.reuses += 1
            d = self._dirty_rows.get(key, rows)
            if d:  # zero only the rows the previous step touched
                b["tokens"][:d] = 0
                b["positions"][:d] = 0
                b["new_lens"][:d] = 0
                b["block_tables"][:d] = 0
                b["seen"][:d] = 0
        return b

    def mark_dirty(self, rows: int, chunk: int, used_rows: int) -> None:
        self._dirty_rows[(rows, chunk)] = used_rows


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def build_ragged_batch(
    manager: StateManager,
    uids: Sequence[int],
    token_lists: Sequence[np.ndarray],
    max_pages: int,
    row_bucket: int = 8,
    chunk_bucket: int = 8,
    staging: Optional[BatchStaging] = None,
) -> RaggedBatch:
    """Allocate blocks and assemble the dense step arrays.

    Caller must have checked ``can_schedule`` and pass distinct uids; this
    raises if blocks run out. With ``staging``, the returned arrays are the
    staging pool's buffers (zero allocation in steady state); without, fresh
    arrays are allocated.
    """
    n = len(uids)
    assert n == len(token_lists) and n > 0
    lens = np.fromiter((len(t) for t in token_lists), dtype=np.int64, count=n)
    chunk = _round_up(max(int(lens.max()), 1), chunk_bucket)
    rows = _round_up(n, row_bucket)

    if staging is not None:
        buf = staging.acquire(rows, chunk)
        if staging.max_pages != max_pages:
            raise ValueError(
                f"staging max_pages={staging.max_pages} != requested {max_pages}")
        tokens, positions = buf["tokens"], buf["positions"]
        new_lens, block_tables, seen = buf["new_lens"], buf["block_tables"], buf["seen"]
        staging.mark_dirty(rows, chunk, n)
    else:
        tokens = np.zeros((rows, chunk), np.int32)
        positions = np.zeros((rows, chunk), np.int32)
        new_lens = np.zeros((rows,), np.int32)
        block_tables = np.zeros((rows, max_pages), np.int32)
        seen = np.zeros((rows,), np.int32)

    # --- block allocation: one vectorized allocator call for the whole step
    seqs = [manager.get_or_create(uid) for uid in uids]
    seen_v = np.fromiter((s.seen_tokens for s in seqs), dtype=np.int32, count=n)
    have_v = np.fromiter((s.n_blocks for s in seqs), dtype=np.int64, count=n)
    bs = manager.block_size
    need_v = np.maximum(-(-(seen_v.astype(np.int64) + lens) // bs) - have_v, 0)
    over = (have_v + need_v) > max_pages
    if over.any():
        i = int(np.argmax(over))
        raise RuntimeError(
            f"uid {uids[i]}: {int(have_v[i] + need_v[i])} blocks exceeds "
            f"max_pages={max_pages} (sequence longer than engine max_seq_len)"
        )
    fresh = manager.allocator.allocate(int(need_v.sum()))
    ends = np.cumsum(need_v)
    for i, s in enumerate(seqs):
        if need_v[i]:
            s.append_blocks(fresh[ends[i] - need_v[i]: ends[i]])

    # --- vectorized fills (no per-token Python loops)
    new_lens[:n] = lens
    seen[:n] = seen_v
    if int(lens.max()) == 1:
        # decode fast path: one token per row, position == seen (a
        # zero-length row stays a pad: new_lens==0 masks it device-side)
        positions[:n, 0] = seen_v
        tokens[:n, 0] = np.fromiter(
            (t[0] if len(t) else 0 for t in token_lists), dtype=np.int64, count=n)
    else:
        col = np.arange(chunk)
        valid = col[None, :] < lens[:, None]  # [n, chunk]
        positions[:n] = np.where(valid, seen_v[:, None] + col[None, :], 0)
        # row-major boolean scatter == concatenation order of the ragged lists
        tokens[:n][valid] = np.concatenate(
            [np.asarray(t, np.int32) for t in token_lists])
    for i, s in enumerate(seqs):
        block_tables[i, : s.n_blocks] = s._table[: s.n_blocks]

    return RaggedBatch(
        uids=list(uids), tokens=tokens, positions=positions,
        new_lens=new_lens, block_tables=block_tables, seen=seen,
    )
