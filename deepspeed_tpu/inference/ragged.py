"""Ragged-batch state management for continuous batching.

TPU-native analog of the reference FastGen ragged layer
(``inference/v2/ragged/``): ``BlockedAllocator`` (blocked_allocator.py:11),
``DSSequenceDescriptor`` (sequence_descriptor.py), ``DSStateManager``
(ragged_manager.py:19), and ``RaggedBatchWrapper`` (ragged_wrapper.py).

All of this is host-side bookkeeping (numpy, no device work): the device sees
only the dense arrays a ``RaggedBatch`` assembles — padded token/position
matrices plus per-sequence block tables into the paged KV pool. Static shape
buckets keep XLA recompiles rare; the pad rows write to a dedicated trash slot
in the pool (see ``paged.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np


class BlockedAllocator:
    """Free-list allocator for KV-cache blocks (reference
    ``BlockedAllocator`` inference/v2/ragged/blocked_allocator.py:11)."""

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"need at least 1 block, got {num_blocks}")
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._free_set = set(self._free)  # O(1) double-free detection
        self.num_blocks = num_blocks

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(f"cannot allocate {n} blocks ({len(self._free)} free)")
        out = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(out)
        return out

    def free(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            if b < 0 or b >= self.num_blocks or b in self._free_set:
                raise ValueError(f"bad free of block {b}")
            self._free.append(b)
            self._free_set.add(b)


@dataclasses.dataclass
class SequenceDescriptor:
    """Per-sequence tracking (reference ``DSSequenceDescriptor``)."""

    uid: int
    seen_tokens: int = 0
    blocks: List[int] = dataclasses.field(default_factory=list)

    def blocks_needed(self, new_tokens: int, block_size: int) -> int:
        total = self.seen_tokens + new_tokens
        need = -(-total // block_size)  # ceil
        return max(0, need - len(self.blocks))


class StateManager:
    """uid -> sequence state + block accounting (reference ``DSStateManager``
    inference/v2/ragged/ragged_manager.py:19)."""

    def __init__(self, num_blocks: int, block_size: int, max_seqs: int = 256,
                 max_blocks_per_seq: Optional[int] = None):
        self.allocator = BlockedAllocator(num_blocks)
        self.block_size = block_size
        self.max_seqs = max_seqs
        self.max_blocks_per_seq = max_blocks_per_seq
        self._seqs: Dict[int, SequenceDescriptor] = {}

    @property
    def n_active(self) -> int:
        return len(self._seqs)

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    def get(self, uid: int) -> Optional[SequenceDescriptor]:
        return self._seqs.get(uid)

    def get_or_create(self, uid: int) -> SequenceDescriptor:
        if uid not in self._seqs:
            if len(self._seqs) >= self.max_seqs:
                raise RuntimeError(f"max_seqs={self.max_seqs} active sequences reached")
            self._seqs[uid] = SequenceDescriptor(uid)
        return self._seqs[uid]

    def can_schedule(self, uids: Sequence[int], token_counts: Sequence[int]) -> bool:
        """Admission check (reference ``InferenceEngineV2.can_schedule`` :184)."""
        need = 0
        fresh = 0
        for uid, n in zip(uids, token_counts):
            seq = self._seqs.get(uid)
            if seq is None:
                fresh += 1
                total_blocks = -(-n // self.block_size)
                need += total_blocks
            else:
                total_blocks = len(seq.blocks) + seq.blocks_needed(n, self.block_size)
                need += seq.blocks_needed(n, self.block_size)
            if self.max_blocks_per_seq is not None and total_blocks > self.max_blocks_per_seq:
                return False  # sequence would exceed engine max_seq_len
        if len(self._seqs) + fresh > self.max_seqs:
            return False
        return need <= self.allocator.free_blocks

    def extend(self, uid: int, new_tokens: int) -> SequenceDescriptor:
        """Ensure blocks exist for ``new_tokens`` more tokens of ``uid``."""
        seq = self.get_or_create(uid)
        need = seq.blocks_needed(new_tokens, self.block_size)
        if need:
            seq.blocks.extend(self.allocator.allocate(need))
        return seq

    def flush(self, uid: int) -> None:
        """Release a finished sequence (reference ``flush_uid`` engine_v2.py)."""
        seq = self._seqs.pop(uid, None)
        if seq is not None and seq.blocks:
            self.allocator.free(seq.blocks)


@dataclasses.dataclass
class RaggedBatch:
    """Dense view of one scheduling step (reference ``RaggedBatchWrapper``).

    Rows are sequences; pad rows have ``new_lens == 0``. ``tokens`` is
    right-padded to the chunk bucket; ``block_tables`` is padded with 0 (pad
    slots never read: masked by position; never written: writes route to the
    trash slot)."""

    uids: List[int]
    tokens: np.ndarray  # [N, C] int32
    positions: np.ndarray  # [N, C] int32 (global position of each new token)
    new_lens: np.ndarray  # [N] int32
    block_tables: np.ndarray  # [N, P] int32
    seen: np.ndarray  # [N] int32 (tokens already in cache, before this step)

    @property
    def n_rows(self) -> int:
        return self.tokens.shape[0]


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def build_ragged_batch(
    manager: StateManager,
    uids: Sequence[int],
    token_lists: Sequence[np.ndarray],
    max_pages: int,
    row_bucket: int = 8,
    chunk_bucket: int = 8,
) -> RaggedBatch:
    """Allocate blocks and assemble the dense step arrays.

    Caller must have checked ``can_schedule``; this raises if blocks run out.
    """
    n = len(uids)
    assert n == len(token_lists) and n > 0
    chunk = max(len(t) for t in token_lists)
    chunk = _round_up(max(chunk, 1), chunk_bucket)
    rows = _round_up(n, row_bucket)

    tokens = np.zeros((rows, chunk), np.int32)
    positions = np.zeros((rows, chunk), np.int32)
    new_lens = np.zeros((rows,), np.int32)
    block_tables = np.zeros((rows, max_pages), np.int32)
    seen = np.zeros((rows,), np.int32)

    for i, (uid, toks) in enumerate(zip(uids, token_lists)):
        toks = np.asarray(toks, np.int32)
        seq = manager.extend(uid, len(toks))
        if len(seq.blocks) > max_pages:
            raise RuntimeError(
                f"uid {uid}: {len(seq.blocks)} blocks exceeds max_pages={max_pages} "
                f"(sequence longer than engine max_seq_len)"
            )
        tokens[i, : len(toks)] = toks
        positions[i, : len(toks)] = seq.seen_tokens + np.arange(len(toks))
        new_lens[i] = len(toks)
        block_tables[i, : len(seq.blocks)] = seq.blocks
        seen[i] = seq.seen_tokens

    return RaggedBatch(
        uids=list(uids), tokens=tokens, positions=positions,
        new_lens=new_lens, block_tables=block_tables, seen=seen,
    )
