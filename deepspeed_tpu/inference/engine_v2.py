"""Continuous-batching inference engine (FastGen analog).

TPU-native analog of reference ``InferenceEngineV2``
(``inference/v2/engine_v2.py:30``): sequences identified by uid, tokens pushed
via ``put(uids, tokens)``, KV state lives in a paged pool addressed through
per-sequence block tables, and admission control (``can_schedule``/``query``)
lets a serving loop pack prefill chunks and decodes into one step.

Differences from the reference, by TPU design:
  - one jitted ragged step program per (rows, chunk) bucket instead of a
    kernel zoo; the paged gather/attention lives in ``paged.py``
  - the scheduler-facing API is identical in shape, but scheduling quanta are
    bucket sizes (static shapes) rather than arbitrary token counts

Serving fast path (the host leaves the per-token critical path):
  - sampling is fused into the jitted step programs, so decode dispatches
    return token ids, not ``[rows, vocab]`` logits — no per-token logits D2H
  - decode runs as a K-step chained program (``paged.ragged_decode_chain``):
    one dispatch and one host sync per K decoded tokens, with per-row
    EOS/budget masking inside the ``lax.scan``; the scheduler admits and
    preempts at chain boundaries, and the chain length auto-shrinks to honor
    ``max_new_tokens`` and KV-pool pressure (``decode_chain=1`` reproduces
    the per-token loop's outputs exactly)
  - batch assembly writes into preallocated per-bucket staging buffers
    (``ragged.BatchStaging``), and all scheduler bookkeeping is O(1) amortized
"""

from __future__ import annotations

import functools
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from pydantic import Field

from deepspeed_tpu.config.config_utils import DeepSpeedConfigModel
from deepspeed_tpu.inference.config import QuantConfig, ServingSLOConfig
from deepspeed_tpu.inference.lifecycle import LifecycleTracker
from deepspeed_tpu.inference.paged import (
    MigrationBuffer,
    PagedKVPool,
    copy_pool_blocks,
    export_pool_blocks,
    import_pool_blocks,
    init_pool,
    ragged_decode_chain,
    ragged_forward,
    ragged_spec_decode_chain,
)
from deepspeed_tpu.inference.ragged import (
    BatchStaging,
    PrefixCache,
    RaggedBatch,
    StateManager,
    build_ragged_batch,
)
from deepspeed_tpu.inference.sampling import sample_logits
from deepspeed_tpu.models.transformer import TransformerConfig, causal_lm_partition_rules
from deepspeed_tpu.parallel.autotp import place_parameters
from deepspeed_tpu.telemetry import get_tracer
from deepspeed_tpu.telemetry.fleet import note_step as _fleet_note_step
from deepspeed_tpu.topology.mesh import build_mesh, set_mesh
from deepspeed_tpu.utils.logging import log_dist


class RaggedInferenceConfig(DeepSpeedConfigModel):
    """v2 engine config (reference ``RaggedInferenceEngineConfig``:
    state-manager + KV-cache sizing)."""

    dtype: str = "bf16"
    tp_size: int = 1
    # Expert-parallel serving (ISSUE 15): width of the mesh's ``ep`` axis.
    # Expert weights shard over ep at placement (moe_partition_rules) and
    # the MoE block's dispatch/combine runs through the facade all_to_all
    # (model._moe_ep_collective — exact no-drop routing, so an ep>1 engine
    # decodes token-identical to ep=1 on the same checkpoint). The serving
    # router is oblivious: replicas declare capacity, not topology.
    ep_size: int = 1
    kv_block_size: int = 16
    num_kv_blocks: int = 512
    # Quantized KV-cache storage (ISSUE 10): None = pool in ``dtype``;
    # "int8" | "fp8" = pool holds 1-byte values + one fp32 scale per
    # (layer, slot, kv-head) head vector (the shared ops.quant block math),
    # dequant fused into the paged-attention block loads. ~1.9x the token
    # slots per HBM byte at head_dim>=64 — the admission-capacity lever.
    kv_cache_dtype: Optional[str] = None
    # Byte budget for the paged pool: when set, ``num_kv_blocks`` is DERIVED
    # as kv_blocks_for_bytes(kv_pool_bytes, ...) with the real (quantized or
    # dense) block bytes — fixed HBM, variable capacity. None keeps the
    # explicit num_kv_blocks.
    kv_pool_bytes: Optional[int] = None
    # Weight-only quantization for the serving weights (inference/woq.py —
    # same QuantConfig as v1 init_inference, incl. per-tensor-class
    # selection): int8/int4/fp8 bytes in HBM, dequant at the matmul boundary.
    quant: QuantConfig = Field(default_factory=QuantConfig)
    max_seqs: int = 64  # max concurrently tracked sequences
    max_seq_len: Optional[int] = None  # default: model max_seq_len
    row_bucket: int = 8
    chunk_bucket: int = 16
    # K decode iterations per dispatched program (paged.ragged_decode_chain):
    # one dispatch + one host sync per K decoded tokens. 1 = per-token loop
    # (same outputs, K× the dispatch/sync overhead). The effective chain
    # shrinks automatically near max_new_tokens and under KV-pool pressure.
    decode_chain: int = 8
    # Content-hash prefix cache over the paged pool (ISSUE 12): finished
    # prefill blocks are indexed by position-aligned token-chain hash and
    # kept alive by allocator refcounts, so a later prompt sharing the
    # prefix reuses the QUANTIZED block bytes directly (zero re-prefill,
    # zero re-quantization); a partially matching block is reused via
    # copy-on-write at the first divergent token. Off by default — the
    # decode fast path is byte-identical when disabled.
    prefix_cache: bool = False
    # Cap on cache-held blocks as a fraction of the pool
    # (utils/hbm.prefix_cache_capacity_blocks) — cache-aware pool sizing:
    # the cache can never starve live sequences below (1-fraction) of the
    # pool, and admission pressure evicts LRU entries before preempting.
    prefix_cache_fraction: float = 0.5
    # Record a blake2b digest of each cached block's quantized pool bytes at
    # insert (one jitted fetch + D2H per NEW block, prefill-boundary only).
    # The digest is the cached artifact's integrity identity — the
    # correctness harness and the nightly smoke compare it at hit time.
    # Lookups key on token-chain hashes either way, so latency-critical
    # deployments can turn the fetch off without changing cache behavior.
    prefix_cache_hash_bytes: bool = True
    # Disaggregated serving role (ISSUE 14): which phase this replica serves
    # under a phase-aware ServingRouter. "mixed" (default) serves both —
    # the engine-only behavior, byte-identical to before. "prefill" replicas
    # take fresh admissions and hand finished prefills to the decode pool
    # via KV-block migration; "decode" replicas never take fresh admissions,
    # they re-admit migrated requests and run their decode chains. The role
    # only steers the router's placement — every engine can run every
    # program (that is what the mixed-mode fallback relies on).
    role: str = "mixed"
    # In-flight post-prefill export cap per replica (double-buffered page
    # streaming: the export of request N overlaps the prefill of N+1).
    migration_depth: int = 2
    # Speculative decoding (ISSUE 12): number of draft tokens verified per
    # model forward inside the decode chain (0 = off). Drafts come from an
    # on-device n-gram (prompt-lookup) proposer over the row's history;
    # verify-and-accept runs in the SAME jitted chain program — still one
    # dispatch + one host sync per chain, >1 accepted token per forward on
    # agreeable text. Greedy-only (acceptance compares argmax targets).
    spec_decode: int = 0
    spec_ngram: int = 2  # n-gram length the proposer matches on
    # Pre-flight HBM-fit check (utils/hbm.py) before param/pool
    # materialization: "warn" | "refuse" | "off".
    hbm_check: str = "warn"
    # SLO targets for the per-request lifecycle metrics (TTFT/TPOT goodput —
    # inference/lifecycle.py). Tracking itself keys off the telemetry tracer;
    # this block only sets the targets and rolling-window length.
    serving_slo: ServingSLOConfig = Field(default_factory=ServingSLOConfig)
    # Serving flight-recorder mode (diagnostics/flight_recorder.py): keep a
    # bounded ring of per-request records (id, phase stamps, chain count) so
    # a crashed serving run's post-mortem names the in-flight requests.
    flight_recorder: bool = False

    @property
    def jax_dtype(self):
        from deepspeed_tpu.inference.config import _DTYPES

        return _DTYPES[self.dtype.lower()]

    @property
    def validated_role(self) -> str:
        if self.role not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f"role must be prefill|decode|mixed, got {self.role!r}")
        return self.role

    @property
    def kv_quant(self) -> Optional[str]:
        """None | 'int8' | 'fp8' — quantized-storage mode of the KV pool."""
        name = (self.kv_cache_dtype or "").lower()
        if name in ("int8", "fp8"):
            return name
        from deepspeed_tpu.inference.config import _DTYPES

        if name and name not in _DTYPES:
            raise ValueError(
                f"kv_cache_dtype must be a float dtype name or 'int8'|'fp8', "
                f"got {self.kv_cache_dtype!r}")
        return None

    @property
    def kv_jax_dtype(self):
        """Pool storage dtype when NOT block-quantized (default: compute)."""
        from deepspeed_tpu.inference.config import _DTYPES

        if self.kv_quant is not None or not self.kv_cache_dtype:
            return self.jax_dtype
        return _DTYPES[self.kv_cache_dtype.lower()]

    @property
    def kv_dtype_name(self) -> str:
        """The label the serving gauges carry ('int8'/'fp8'/float name)."""
        return (self.kv_cache_dtype or self.dtype).lower()


def build_hf_engine(
    path: str,
    config: Union["RaggedInferenceConfig", Dict, None] = None,
    mesh: Optional[Mesh] = None,
) -> "InferenceEngineV2":
    """One call from a HuggingFace checkpoint directory to a serving engine
    (reference ``inference/v2/engine_factory.py:69 build_hf_engine`` — there a
    policy zoo maps each family onto kernel containers; here the 13-family
    ingestion in ``checkpoint/hf.py`` produces the generic ragged
    transformer's pytree directly)."""
    from deepspeed_tpu.checkpoint.hf import load_hf_checkpoint

    model_config, params = load_hf_checkpoint(path)
    return InferenceEngineV2(model_config, params, config, mesh=mesh)


class InferenceEngineV2:
    """uid-keyed continuous batching over a paged KV pool."""

    def __init__(
        self,
        model_config: TransformerConfig,
        params: Any,
        config: Union[RaggedInferenceConfig, Dict, None] = None,
        mesh: Optional[Mesh] = None,
    ):
        if config is None:
            config = {}
        if isinstance(config, dict):
            config = RaggedInferenceConfig(**config)
        config.validated_role  # raise on a bad disagg role before any work
        self.model_config = model_config
        self.config = config
        if mesh is None:
            axes = {"tp": config.tp_size, "dp": -1}
            if config.ep_size > 1:
                axes["ep"] = config.ep_size
            mesh = build_mesh(axis_sizes=axes)
        self.mesh = mesh
        set_mesh(mesh)
        if mesh.shape.get("ep", 1) > 1:
            if model_config.num_experts <= 0:
                raise ValueError(
                    f"ep_size={mesh.shape['ep']} on a dense model: expert "
                    "parallelism needs num_experts > 0")
            if model_config.num_experts % mesh.shape["ep"]:
                raise ValueError(
                    f"num_experts={model_config.num_experts} not divisible "
                    f"by ep_size={mesh.shape['ep']}")
            log_dist(
                f"expert-parallel serving: experts sharded over ep="
                f"{mesh.shape['ep']}, MoE dispatch/combine through the "
                "facade all_to_all (algorithm="
                f"{model_config.moe_dispatch_algorithm or 'facade default'}, "
                f"codec={model_config.moe_wire_codec or 'exact'})", ranks=[0])

        max_len = config.max_seq_len or model_config.max_seq_len
        self.max_seq_len = max_len
        self.max_pages = -(-max_len // config.kv_block_size)

        from deepspeed_tpu.utils.hbm import kv_blocks_for_bytes, kv_slot_bytes

        dtype = config.jax_dtype
        kv_quant = config.kv_quant
        kv_dtype = config.kv_jax_dtype
        kv_dtype_b = jnp.dtype(kv_dtype).itemsize
        # The real (quantized or dense) per-token pool cost — ONE formula
        # shared with the pre-flight guard and the capacity benchmark.
        self.kv_bytes_per_token = kv_slot_bytes(
            model_config.num_layers, model_config.kv_heads,
            model_config.dims_per_head, kv_dtype_b, kv_quant)
        if config.kv_pool_bytes is not None:
            # byte-budget sizing: admission capacity follows the REAL block
            # bytes, so an int8 pool at the same budget admits ~1.9x the
            # concurrent requests of a bf16 one
            num_blocks = kv_blocks_for_bytes(
                config.kv_pool_bytes, model_config.num_layers,
                config.kv_block_size, model_config.kv_heads,
                model_config.dims_per_head, kv_dtype_b, kv_quant)
        else:
            num_blocks = config.num_kv_blocks
        self.num_kv_blocks = num_blocks
        self.state = StateManager(num_blocks, config.kv_block_size, config.max_seqs,
                                  max_blocks_per_seq=self.max_pages)
        self._staging = BatchStaging(self.max_pages)
        self.prefix_cache: Optional[PrefixCache] = None
        if config.prefix_cache:
            from deepspeed_tpu.utils.hbm import prefix_cache_capacity_blocks

            self.prefix_cache = PrefixCache(
                self.state.allocator, config.kv_block_size,
                capacity_blocks=prefix_cache_capacity_blocks(
                    num_blocks, config.prefix_cache_fraction))

        n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
        kv_on_tp = model_config.kv_heads % mesh.shape["tp"] == 0
        # Compiled-program registry (telemetry/programs.py): the v2 step
        # programs are wrapped at build time when capture is live, and the
        # pre-flight byte estimate below doubles as the serving-scope
        # calibration baseline for hbm/estimate_ratio.
        from deepspeed_tpu.telemetry.programs import get_program_registry

        self._programs = get_program_registry()
        if config.hbm_check != "off" or self._programs.enabled:
            # Refuse/warn BEFORE any device materialization: PER-DEVICE bytes
            # — params shard over tp (autotp partition rules), the KV pool
            # shards over tp only when kv_heads divides — plus a
            # [rows, vocab] logits buffer. Quantized storage enters with its
            # REAL byte formulas: a pool/model that only fits quantized is
            # admitted, an over-budget one refused before the wedge.
            from deepspeed_tpu.utils.hbm import check_hbm_fit

            tp = max(mesh.shape["tp"], 1)
            dtype_b = jnp.dtype(dtype).itemsize
            if config.quant.enabled and tp == 1:
                from deepspeed_tpu.inference.woq import (
                    quantized_bytes_estimate,
                    woq_format,
                )

                param_bytes = quantized_bytes_estimate(
                    params, woq_format(config.quant),
                    min_size=config.quant.min_leaf_size,
                    classes=config.quant.tensor_classes, dense_itemsize=dtype_b)
            else:
                # tp>1 places dense shards first (WOQ quantizes in place
                # after — see below), so the dense tp-shard bytes ARE the
                # placement peak
                param_bytes = n_params * dtype_b // tp
            kv_bytes = (num_blocks * config.kv_block_size + 1) * self.kv_bytes_per_token
            # per-step attention workspace of the gather fallback: one
            # layer's gathered (dequantized) KV blocks + fp32 score/prob
            # arrays for a bucketed step (round-10 calibration: without it
            # the serving estimate under-counted 2-3.5x on configs whose
            # pool doesn't dominate; the Pallas path needs less — estimates
            # must cover the worst dispatching path)
            gathered = self.max_pages * config.kv_block_size
            workspace = config.row_bucket * gathered * (
                2 * model_config.kv_heads * model_config.dims_per_head * dtype_b
                + 2 * model_config.num_heads * config.chunk_bucket * 4)
            need = (param_bytes
                    + kv_bytes // (tp if kv_on_tp else 1)
                    + config.row_bucket * model_config.vocab_size * 4
                    + workspace)
            if config.hbm_check != "off":
                check_hbm_fit(need, what="InferenceEngineV2 init (params + KV pool)",
                              mode=config.hbm_check)
            self._programs.set_hbm_estimate(need, scope="serving")
        woq_pre = config.quant.enabled and max(mesh.shape["tp"], 1) == 1
        if woq_pre:
            # WOQ before placement (the dense weights never hit the device):
            # int8/int4/fp8 values + fp32 scales, dequant at each matmul
            # boundary with compute-dtype accumulation (inference/woq.py).
            # tp>1 instead places the dense shards and quantizes after — the
            # pre-quantized flat layout would place replicated, costing MORE
            # per device than a dense tp shard for tp>2.
            from deepspeed_tpu.inference.woq import quantize_params, woq_format

            params = quantize_params(
                params, woq_format(config.quant),
                min_size=config.quant.min_leaf_size,
                classes=config.quant.tensor_classes)
        self.params = place_parameters(params, mesh, causal_lm_partition_rules, dtype)
        if config.quant.enabled and not woq_pre:
            from deepspeed_tpu.inference.woq import quantize_params, woq_format

            fmt = woq_format(config.quant)
            min_size = config.quant.min_leaf_size
            classes = config.quant.tensor_classes
            self.params = jax.jit(lambda p: quantize_params(
                p, fmt, min_size=min_size, classes=classes))(self.params)
        # KV pool: kv-head dim over tp, slots replicated over dp
        pool = init_pool(model_config, num_blocks, config.kv_block_size, kv_dtype,
                         kv_quant=kv_quant)
        if not kv_on_tp and mesh.shape["tp"] > 1:
            # correct but a quiet perf/memory cliff: each tp rank holds the
            # FULL pool instead of 1/tp of it (round-3 verdict weak item 8)
            log_dist(
                f"KV pool REPLICATED over tp={mesh.shape['tp']}: kv_heads="
                f"{model_config.kv_heads} not divisible — expect tp-times the "
                "per-chip KV memory; pick tp dividing kv_heads to shard it",
                ranks=[0],
            )
        kv_spec = NamedSharding(mesh, P(None, None, "tp" if kv_on_tp else None, None))
        self.pool = PagedKVPool(
            k=jax.device_put(pool.k, kv_spec), v=jax.device_put(pool.v, kv_spec),
            k_scale=None if pool.k_scale is None else jax.device_put(pool.k_scale, kv_spec),
            v_scale=None if pool.v_scale is None else jax.device_put(pool.v_scale, kv_spec))
        log_dist(
            f"InferenceEngineV2: {n_params/1e6:.1f}M params, "
            f"{num_blocks}x{config.kv_block_size} KV slots "
            f"[{config.kv_dtype_name}, {self.kv_bytes_per_token} B/token], "
            f"mesh={dict(mesh.shape)}"
        )
        self._step_cache: Dict[Tuple, Any] = {}
        self._chain_buf: Dict[int, Dict[str, np.ndarray]] = {}
        self._spec_buf: Dict[int, Dict[str, np.ndarray]] = {}
        self._tracer = get_tracer()
        # Serving flight recorder (opt-in): per-request ring so a crash dump
        # names the in-flight requests even with the tracer disabled.
        self._recorder = None
        if config.flight_recorder:
            from deepspeed_tpu.diagnostics.flight_recorder import (
                FlightRecorder,
                install_process_hooks,
            )

            self._recorder = FlightRecorder(
                request_capacity=max(2 * config.max_seqs, 32))
            self._recorder.set_context(
                kind="serving", max_seqs=config.max_seqs,
                decode_chain=config.decode_chain,
                kv_blocks=self.num_kv_blocks)
            install_process_hooks()
        # Most recent generate()'s per-request tracker (None when telemetry
        # is disabled and no recorder is configured — no records allocated).
        self.lifecycle: Optional[LifecycleTracker] = None
        # Serving-loop accounting (always on — plain int adds). The parity
        # tests assert the dispatch/sync contract on these; the serving
        # benchmark and telemetry gauges read them too.
        self.dispatch_count = 0        # compiled programs dispatched
        self.host_sync_count = 0       # host blocking fetches
        self.tokens_decoded = 0        # decode tokens produced by generate()
        self.chain_steps = 0           # decode-chain dispatches (fleet liveness)
        # prefix-cache + speculative accounting (plain int adds; the serving
        # benchmark and the router smoke read these)
        self.prefill_tokens_total = 0  # prompt tokens submitted for prefill
        self.prefill_tokens_cached = 0  # of those, served from the prefix cache
        self.cow_copies = 0            # copy-on-write block clones dispatched
        self.spec_model_steps = 0      # model forwards inside spec chains
        self.spec_tokens_emitted = 0   # tokens those forwards emitted

    # ---------------------------------------------------------------- admission
    def query(self, uid: int) -> Tuple[int, int]:
        """(seen_tokens, free_kv_slots) for scheduler accounting (reference
        ``engine_v2.query`` :158)."""
        seq = self.state.get(uid)
        seen = seq.seen_tokens if seq is not None else 0
        return seen, self.state.free_blocks * self.config.kv_block_size

    def can_schedule(self, uids: Sequence[int], token_counts: Sequence[int]) -> bool:
        return self.state.can_schedule(uids, token_counts)

    def flush(self, uid: int) -> None:
        self.state.flush(uid)

    # ---------------------------------------------------------------- programs
    def _watch(self, fn, kind: str, *parts):
        """Program-registry watcher around a jitted step (identity when
        capture is off at build time — the dispatch path stays untouched;
        ``jit_cache_size`` counts ``_step_cache`` entries either way).
        The label carries every component of the step-cache key so distinct
        compiled programs never collide under one registry label."""
        if not self._programs.enabled:
            return fn
        label = f"v2:{kind}:" + "".join(str(p) for p in parts)
        return self._programs.wrap(fn, label, hbm_scope="serving")

    @staticmethod
    def _kw_tag(sample_kw: Tuple, eos_id=None) -> str:
        """Deterministic short tag for the sampling-config part of a step
        key ('' for the common default config)."""
        if not sample_kw and eos_id is None:
            return ""
        import zlib

        return f"s{zlib.crc32(repr((tuple(sample_kw), eos_id)).encode()) & 0xffff:04x}"

    def _step_fn(self, rows: int, chunk: int):
        """Mixed prefill/decode step -> last-token logits (the v2 ``put``)."""
        key = ("logits", rows, chunk)
        if key not in self._step_cache:
            cfg = self.model_config
            bs = self.config.kv_block_size

            @functools.partial(jax.jit, donate_argnums=(1,))
            def step(params, pool, tokens, positions, new_lens, block_tables):
                return ragged_forward(params, cfg, pool, tokens, positions, new_lens, block_tables, bs)

            self._step_cache[key] = self._watch(step, "step", f"r{rows}", f"c{chunk}")
        return self._step_cache[key]

    def _sample_step_fn(self, rows: int, chunk: int, sample_kw: Tuple):
        """Mixed step with sampling FUSED into the program -> token ids [N].

        ``put``-for-decode through this path returns int32 ids, not
        [rows, vocab] logits — the per-token logits D2H is gone.
        """
        key = ("sample", rows, chunk, sample_kw)
        if key not in self._step_cache:
            cfg = self.model_config
            bs = self.config.kv_block_size
            kw = dict(sample_kw)

            @functools.partial(jax.jit, donate_argnums=(1,))
            def step(params, pool, tokens, positions, new_lens, block_tables, rng):
                logits, pool = ragged_forward(
                    params, cfg, pool, tokens, positions, new_lens, block_tables, bs)
                rng, sub = jax.random.split(rng)
                toks = sample_logits(logits, sub, **kw)
                return toks, rng, pool

            self._step_cache[key] = self._watch(
                step, "prefill", f"r{rows}", f"c{chunk}", self._kw_tag(sample_kw))
        return self._step_cache[key]

    def _chain_fn(self, rows: int, k: int, eos_id: Optional[int], sample_kw: Tuple):
        """K-step decode chain program (paged.ragged_decode_chain)."""
        key = ("chain", rows, k, eos_id, sample_kw)
        if key not in self._step_cache:
            cfg = self.model_config
            bs = self.config.kv_block_size
            kw = dict(sample_kw)

            @functools.partial(jax.jit, donate_argnums=(1,))
            def chain(params, pool, tokens, start_pos, block_tables, active, budgets, rng):
                return ragged_decode_chain(
                    params, cfg, pool, tokens, start_pos, block_tables, bs,
                    active, budgets, rng, k, eos_id, **kw)

            self._step_cache[key] = self._watch(
                chain, "decode_chain", f"r{rows}", f"k{k}",
                self._kw_tag(sample_kw, eos_id))
        return self._step_cache[key]

    def _spec_chain_fn(self, rows: int, k: int, eos_id: Optional[int]):
        """Speculative K-step decode chain program
        (paged.ragged_spec_decode_chain). Keyed (rows, k) like the plain
        chain — n_spec/ngram are engine config, so one compiled program per
        (rows, K) still holds. Greedy-only by construction."""
        key = ("spec", rows, k, eos_id)
        if key not in self._step_cache:
            cfg = self.model_config
            bs = self.config.kv_block_size
            n_spec = self.config.spec_decode
            ngram = self.config.spec_ngram

            @functools.partial(jax.jit, donate_argnums=(1,))
            def chain(params, pool, tokens, start_pos, block_tables, active,
                      budgets, rng, history, hist_len):
                return ragged_spec_decode_chain(
                    params, cfg, pool, tokens, start_pos, block_tables, bs,
                    active, budgets, rng, k, eos_id, history, hist_len,
                    n_spec=n_spec, ngram=ngram)

            self._step_cache[key] = self._watch(
                chain, "spec_chain", f"r{rows}", f"k{k}", f"m{n_spec}",
                self._kw_tag((), eos_id))
        return self._step_cache[key]

    def _cow_fn(self):
        """Copy-on-write block clone (paged.copy_pool_blocks): src/dst ride
        as traced scalars, so ONE compiled program serves every COW event."""
        key = ("cow",)
        if key not in self._step_cache:
            bs = self.config.kv_block_size

            @functools.partial(jax.jit, donate_argnums=(0,))
            def cow(pool, src, dst):
                return copy_pool_blocks(pool, src, dst, bs)

            self._step_cache[key] = self._watch(cow, "cow")
        return self._step_cache[key]

    def jit_cache_size(self, kind: Optional[str] = None) -> int:
        """Number of compiled step programs (optionally of one kind:
        'logits' | 'sample' | 'chain' | 'spec' | 'cow') — recompile
        assertions in tests."""
        return sum(1 for k in self._step_cache if kind is None or k[0] == kind)

    # ---------------------------------------------------------- prefix cache
    def _block_fetch_fn(self):
        """One jitted dynamic-slice program fetching a block's pool pages
        (the slot offset rides as a traced scalar — eager slicing would
        compile a fresh XLA program per distinct block offset)."""
        key = ("blockfetch",)
        if key not in self._step_cache:
            bs = self.config.kv_block_size

            @jax.jit
            def fetch(pool, start):
                def sl(a):
                    if a is None:
                        return None
                    return jax.lax.dynamic_slice_in_dim(a, start, bs, axis=1)

                return (sl(pool.k), sl(pool.v), sl(pool.k_scale), sl(pool.v_scale))

            self._step_cache[key] = fetch
        return self._step_cache[key]

    def _block_content_hash(self, block: int) -> str:
        """blake2b over the block's pool bytes — for a quantized pool the
        int8/fp8 value pages AND the fp32 scale pages together (the PR-10
        layout travels as one unit). This digest is the cached artifact's
        identity: tests and the nightly smoke compare it at hit time against
        the insert-time digest to prove sharing/COW/eviction never touched
        the stored bytes, and it is taken over exactly the bytes the
        paged-attention block loads read (a hit is never re-quantized)."""
        import hashlib

        bs = self.config.kv_block_size
        parts = self._block_fetch_fn()(self.pool, jnp.int32(block * bs))
        h = hashlib.blake2b(digest_size=16)
        for arr in parts:
            if arr is not None:
                h.update(np.asarray(arr).tobytes())
        return h.hexdigest()

    def prefix_probe(self, cand: np.ndarray):
        """Prefix-cache lookup for admission accounting: returns
        ``(hit, admission_token_count)`` where the count excludes the
        tokens fully cached blocks cover. The COW clone's block is
        deliberately NOT subtracted — ``_attach_prefix`` allocates it
        outside ``can_schedule``, and counting its tokens as to-prefill
        makes the admission estimate cover that allocation. One definition
        shared by ``generate`` and the serving router."""
        pc = self.prefix_cache
        if pc is None:
            return None, len(cand)
        hit = pc.match(cand)
        return hit, len(cand) - hit.n_blocks * self.config.kv_block_size

    def _pin_hit(self, hit) -> None:
        """Take a temporary reference on every block of a PrefixHit. Between
        ``prefix_probe`` and ``_attach_prefix`` the admission path may evict
        LRU cache entries (``_can_schedule_evicting``) — without the pin,
        eviction of an entry whose ONLY holder was the cache would free the
        very blocks the hit is about to share, and the attach would raise
        mid-serving. Pinned blocks survive eviction (the entry goes, the
        bytes stay) and the pin is dropped by ``_unpin_hit`` either way."""
        if hit is None:
            return
        blocks = list(hit.blocks)
        if hit.cow_block is not None:
            blocks.append(hit.cow_block)
        self.state.allocator.share(blocks)

    def _unpin_hit(self, hit) -> None:
        if hit is None:
            return
        blocks = list(hit.blocks)
        if hit.cow_block is not None:
            blocks.append(hit.cow_block)
        self.state.allocator.release(blocks)

    def _attach_prefix(self, uid: int, hit) -> int:
        """Wire a PrefixHit into a fresh sequence: share the full cached
        blocks, clone the COW block (if any) up to the divergent token, and
        return how many prompt tokens the cache covered (== the new
        sequence's ``seen_tokens``)."""
        bs = self.config.kv_block_size
        alloc = self.state.allocator
        seq = self.state.get_or_create(uid)
        assert seq.seen_tokens == 0 and seq.n_blocks == 0
        reuse = 0
        if hit.blocks:
            alloc.share(hit.blocks)
            seq.append_blocks(np.asarray(hit.blocks, np.int32))
            reuse = len(hit.blocks) * bs
        if hit.cow_block is not None and hit.cow_len > 0:
            # hold the source across the allocation (our own allocate may
            # trigger LRU eviction, which could otherwise free the source)
            alloc.share([hit.cow_block])
            dst = self._ensure_blocks(1)
            with self._tracer.span("serve:cow", src=hit.cow_block, dst=int(dst[0])):
                self.pool = self._cow_fn()(
                    self.pool, jnp.int32(hit.cow_block), jnp.int32(dst[0]))
            self.dispatch_count += 1
            alloc.release([hit.cow_block])
            seq.append_blocks(dst)
            reuse += hit.cow_len
            self.cow_copies += 1
        seq.seen_tokens = reuse
        return reuse

    def _ensure_blocks(self, n: int) -> np.ndarray:
        """Allocate ``n`` blocks, evicting LRU prefix-cache entries if the
        free stack runs short."""
        pc = self.prefix_cache
        while (self.state.free_blocks < n and pc is not None
               and pc.evict_one()):
            pass
        return self.state.allocator.allocate(n)

    def _insert_prefix(self, uid: int, full_tokens: np.ndarray) -> None:
        """Index the finished prefill's full blocks (values already in the
        pool — the entries' content hashes are snapshots of the quantized
        bytes as written)."""
        pc = self.prefix_cache
        seq = self.state.get(uid)
        if pc is None or seq is None:
            return
        hasher = (self._block_content_hash
                  if self.config.prefix_cache_hash_bytes else None)
        pc.insert(full_tokens, seq.blocks, hasher=hasher)

    def try_admit(self, uid: int, cand: np.ndarray, other_uids: Sequence[int],
                  other_counts: Sequence[int]) -> Optional[np.ndarray]:
        """ONE definition of prefix-aware admission, shared by ``generate``
        and the serving router: probe the cache, pin the hit across the
        (evicting) schedule check, attach shared/COW blocks on success, and
        account the reuse. Returns the suffix tokens still needing prefill,
        or None when the request does not fit alongside ``other_uids``
        (state unchanged — the pin is dropped either way)."""
        hit, adm_count = self.prefix_probe(cand)
        self._pin_hit(hit)
        if not self._can_schedule_evicting(
                list(other_uids) + [uid], list(other_counts) + [adm_count]):
            self._unpin_hit(hit)
            return None
        reuse = 0
        if hit is not None and (hit.blocks or hit.cow_len):
            reuse = self._attach_prefix(uid, hit)
        self._unpin_hit(hit)
        if self.prefix_cache is not None:
            self.prefix_cache.record(hit)
        self.prefill_tokens_total += len(cand)
        self.prefill_tokens_cached += reuse
        return cand[reuse:]

    # ------------------------------------------------------------- migration
    def _export_fn(self, pages: int):
        """Block-export gather program (paged.export_pool_blocks): block ids
        ride as traced values, so one compiled program per page bucket
        serves every migration. NOT donated — the source pool stays live
        (the source keeps serving while the pages stream out)."""
        key = ("export", pages)
        if key not in self._step_cache:
            bs = self.config.kv_block_size

            @jax.jit
            def export(pool, blocks):
                return export_pool_blocks(pool, blocks, bs)

            self._step_cache[key] = self._watch(export, "export", f"p{pages}")
        return self._step_cache[key]

    def _import_fn(self, pages: int):
        """Block-import scatter program (paged.import_pool_blocks): the
        destination pool is donated like every other pool-mutating step."""
        key = ("import", pages)
        if key not in self._step_cache:
            bs = self.config.kv_block_size

            @functools.partial(jax.jit, donate_argnums=(0,))
            def imp(pool, buf, blocks, n_valid):
                return import_pool_blocks(pool, buf, blocks, n_valid, bs)

            self._step_cache[key] = self._watch(imp, "import", f"p{pages}")
        return self._step_cache[key]

    @staticmethod
    def _page_bucket(n: int) -> int:
        """Round a migration's page count up to the next power of two so a
        handful of compiled export/import programs serve every request
        length (the same static-shape discipline as the step buckets)."""
        b = 1
        while b < n:
            b *= 2
        return b

    def export_request(self, uid: int) -> Dict[str, Any]:
        """Export ``uid``'s KV blocks as a contiguous migration buffer
        (ISSUE 14): a read-only gather in block-table order — quantized
        bytes verbatim, scale pages riding along, refcounts untouched (a
        block the prefix cache shares is exported without disturbing its
        holders; the source releases its OWN reference only at ``flush``
        after the import commits). The dispatch is asynchronous: the pages
        stream out while the host assembles the next prefill."""
        seq = self.state.get(uid)
        if seq is None or seq.n_blocks == 0:
            raise ValueError(f"uid {uid} has no KV blocks to export")
        n = seq.n_blocks
        pages = self._page_bucket(n)
        padded = np.zeros((pages,), np.int32)
        padded[:n] = seq.blocks
        with self._tracer.span("serve:export", uid=uid, blocks=n):
            buf = self._export_fn(pages)(self.pool, jnp.asarray(padded))
        self.dispatch_count += 1
        return {"buffer": buf, "n_blocks": n, "pages": pages,
                "seen_tokens": seq.seen_tokens,
                "kv_dtype": str(jnp.dtype(self.pool.k.dtype)),
                "quant": self.pool.quant,
                "block_size": self.config.kv_block_size}

    def can_import(self, n_blocks: int) -> bool:
        """Whether an ``n_blocks`` migration could be admitted right now
        (seq slot + free blocks after LRU cache eviction) — the refusal
        path the router consults so a rejected import leaves the request
        on its source instead of dropping it."""
        if self.state.n_active >= self.config.max_seqs:
            return False
        pc = self.prefix_cache
        while self.state.free_blocks < n_blocks and pc is not None \
                and pc.evict_one():
            pass
        return self.state.free_blocks >= n_blocks

    def import_request(self, uid: int, export: Dict[str, Any]) -> bool:
        """Import an ``export_request`` ticket as a fresh sequence ``uid``:
        allocate destination blocks (any fragmentation — the scatter IS the
        block-table rewrite), scatter the buffer verbatim, and register the
        descriptor with the source's ``seen_tokens``. Returns False —
        destination state unchanged — when capacity refuses; raises on a
        layout mismatch (pools that disagree on dtype/geometry are a
        deployment error, not a capacity condition)."""
        if export["block_size"] != self.config.kv_block_size or \
                export["quant"] != self.pool.quant or \
                export["kv_dtype"] != str(jnp.dtype(self.pool.k.dtype)):
            raise ValueError(
                f"migration layout mismatch: source "
                f"(bs={export['block_size']}, quant={export['quant']}, "
                f"dtype={export['kv_dtype']}) vs destination "
                f"(bs={self.config.kv_block_size}, quant={self.pool.quant}, "
                f"dtype={jnp.dtype(self.pool.k.dtype)})")
        buf: MigrationBuffer = export["buffer"]
        if buf.k.shape[0] != self.pool.k.shape[0] or \
                buf.k.shape[2:] != self.pool.k.shape[2:]:
            raise ValueError(
                f"migration layout mismatch: buffer pages {buf.k.shape} vs "
                f"pool {self.pool.k.shape}")
        n = export["n_blocks"]
        if not self.can_import(n):
            return False
        dst_blocks = self.state.allocator.allocate(n)
        pages = export["pages"]
        padded = np.zeros((pages,), np.int32)
        padded[:n] = dst_blocks
        try:
            with self._tracer.span("serve:import", uid=uid, blocks=n):
                self.pool = self._import_fn(pages)(
                    self.pool, buf, jnp.asarray(padded), jnp.int32(n))
        except BaseException:
            # the scatter never committed (self.pool rebinds only on
            # success): return the allocation so a failed import — which
            # the router degrades, not drops — cannot leak destination
            # capacity attempt over attempt
            self.state.allocator.free(dst_blocks)
            raise
        self.dispatch_count += 1
        seq = self.state.get_or_create(uid)
        assert seq.seen_tokens == 0 and seq.n_blocks == 0
        seq.append_blocks(dst_blocks)
        seq.seen_tokens = export["seen_tokens"]
        return True

    def chain_window(self, budgets: Sequence[int], k: int) -> List[int]:
        """KV tokens one K-step chain may consume per row: each of the K
        iterations emits up to ``1 + spec_decode`` tokens, plus the
        ``spec_decode`` transient rejected-draft slots. One formula for
        ``generate`` and the router's pressure loops (spec_decode=0 reduces
        to the plain ``min(k, budget)``)."""
        m = 1 + self.config.spec_decode
        return [min(k * m, b) + self.config.spec_decode for b in budgets]

    def _can_schedule_evicting(self, uids, counts) -> bool:
        """``can_schedule`` that reclaims cache-only blocks under pressure:
        LRU prefix entries release their references until admission fits or
        the cache is dry — cached prefixes never starve live traffic."""
        if self.state.can_schedule(uids, counts):
            return True
        pc = self.prefix_cache
        if pc is None:
            return False
        while pc.evict_one():
            if self.state.can_schedule(uids, counts):
                return True
        return False

    # ---------------------------------------------------------------- put
    def _build_batch(self, uids, token_lists) -> RaggedBatch:
        with self._tracer.span("serve:assemble", rows=len(uids)):
            return build_ragged_batch(
                self.state, uids, token_lists, self.max_pages,
                self.config.row_bucket, self.config.chunk_bucket,
                staging=self._staging,
            )

    def put(self, uids: Sequence[int], token_lists: Sequence[np.ndarray]) -> np.ndarray:
        """Push new tokens for each uid; returns last-token logits [len(uids), V]
        (reference ``engine_v2.put`` :107). Mixed prefill/decode is fine —
        pass a whole prompt for new sequences and single tokens for decodes.

        This is the logits-returning compatibility path; the serving loop
        (``generate``) uses the fused-sampling programs instead and never
        ships logits to the host.
        """
        if not self.can_schedule(uids, [len(t) for t in token_lists]):
            raise RuntimeError("insufficient KV blocks/slots; call can_schedule first")
        batch = self._build_batch(uids, token_lists)
        step = self._step_fn(batch.n_rows, batch.tokens.shape[1])
        with self._tracer.span("serve:dispatch", kind="put", rows=batch.n_rows):
            logits, self.pool = step(
                self.params, self.pool,
                jnp.asarray(batch.tokens), jnp.asarray(batch.positions),
                jnp.asarray(batch.new_lens), jnp.asarray(batch.block_tables),
            )
        self.dispatch_count += 1
        for uid, toks in zip(uids, token_lists):
            self.state.get(uid).seen_tokens += len(toks)
        self.host_sync_count += 1
        return np.asarray(logits[: len(uids)])

    def _put_sample(self, uids, token_lists, rng, sample_kw: Tuple,
                    tracker: Optional[LifecycleTracker] = None,
                    rids: Optional[Sequence[int]] = None) -> Tuple[np.ndarray, jax.Array]:
        """Fused put+sample: push tokens, return (sampled next-token ids
        [len(uids)] host numpy, new rng). One dispatch, one host sync, no
        logits transfer."""
        batch = self._build_batch(uids, token_lists)
        step = self._sample_step_fn(batch.n_rows, batch.tokens.shape[1], sample_kw)
        with self._tracer.span("serve:dispatch", kind="prefill", rows=batch.n_rows):
            if tracker is not None and rids is not None:
                tracker.mark_dispatch(rids, "prefill")
            toks, rng, self.pool = step(
                self.params, self.pool,
                jnp.asarray(batch.tokens), jnp.asarray(batch.positions),
                jnp.asarray(batch.new_lens), jnp.asarray(batch.block_tables),
                rng,
            )
        self.dispatch_count += 1
        for uid, t in zip(uids, token_lists):
            self.state.get(uid).seen_tokens += len(t)
        with self._tracer.span("serve:fetch", kind="prefill"):
            out = np.asarray(toks[: len(uids)])
        self.host_sync_count += 1
        return out, rng

    # ---------------------------------------------------------------- chain
    def _chain_arrays(self, rows: int) -> Dict[str, np.ndarray]:
        buf = self._chain_buf.get(rows)
        if buf is None:
            buf = {
                "tokens": np.zeros((rows,), np.int32),
                "pos": np.zeros((rows,), np.int32),
                "tables": np.zeros((rows, self.max_pages), np.int32),
                "active": np.zeros((rows,), bool),
                "budgets": np.zeros((rows,), np.int32),
            }
            self._chain_buf[rows] = buf
        else:
            buf["tables"][:] = 0
            buf["active"][:] = False
            buf["budgets"][:] = 0
        return buf

    def decode_chain(
        self,
        uids: Sequence[int],
        last_tokens: Sequence[int],
        budgets: Sequence[int],
        k: int,
        rng: jax.Array,
        eos_id: Optional[int] = None,
        sample_kw: Tuple = (("do_sample", False),),
        tracker: Optional[LifecycleTracker] = None,
        rids: Optional[Sequence[int]] = None,
    ) -> Tuple[np.ndarray, np.ndarray, jax.Array]:
        """Run one K-step chained decode over ``uids``.

        Caller must have verified ``can_schedule(uids, [k]*len(uids))``.
        Returns ``(tokens [n, k], emitted [n], rng)`` where
        ``tokens[i, :emitted[i]]`` are the new tokens of ``uids[i]`` (the
        EOS token, when hit, is included and the row stops). seen_tokens
        advances by ``emitted[i]`` — exactly the KV slots written.
        """
        n = len(uids)
        rows = -(-n // self.config.row_bucket) * self.config.row_bucket
        with self._tracer.span("serve:assemble", kind="chain", rows=rows):
            # pre-extend every row's block table for its share of the K-token
            # window (capped by the row's remaining budget — no KV slots are
            # reserved past max_new_tokens) so the compiled program never
            # needs the allocator mid-chain
            buf = self._chain_arrays(rows)
            for i, uid in enumerate(uids):
                seq = self.state.extend(uid, min(k, int(budgets[i])))
                buf["tables"][i, : seq.n_blocks] = seq.blocks
                buf["pos"][i] = seq.seen_tokens
            buf["tokens"][:n] = last_tokens
            buf["active"][:n] = True
            buf["budgets"][:n] = np.minimum(budgets, k)
        chain = self._chain_fn(rows, k, eos_id, sample_kw)
        with self._tracer.span("serve:dispatch", kind="chain", rows=rows, k=k):
            if tracker is not None and rids is not None:
                tracker.mark_dispatch(rids, "chain")
            out, emitted, _, rng, self.pool = chain(
                self.params, self.pool,
                jnp.asarray(buf["tokens"]), jnp.asarray(buf["pos"]),
                jnp.asarray(buf["tables"]), jnp.asarray(buf["active"]),
                jnp.asarray(buf["budgets"]), rng,
            )
        self.dispatch_count += 1
        with self._tracer.span("serve:fetch", kind="chain"):
            out = np.asarray(out[:n])
            emitted = np.asarray(emitted[:n])
        self.host_sync_count += 1
        for uid, e in zip(uids, emitted):
            self.state.get(uid).seen_tokens += int(e)
        return out, emitted, rng

    def decode_spec_chain(
        self,
        uids: Sequence[int],
        last_tokens: Sequence[int],
        budgets: Sequence[int],
        k: int,
        rng: jax.Array,
        histories: Sequence[np.ndarray],
        eos_id: Optional[int] = None,
        tracker: Optional[LifecycleTracker] = None,
        rids: Optional[Sequence[int]] = None,
    ) -> Tuple[np.ndarray, np.ndarray, jax.Array]:
        """One speculative chain over ``uids``: ``k`` verify forwards, each
        proposing ``spec_decode`` n-gram drafts — up to ``k * (1+n_spec)``
        accepted tokens from ONE dispatch and ONE host sync. ``histories``
        are the rows' full token contexts (prompt + generated, INCLUDING the
        ``last_tokens`` entry) feeding the on-device proposer. Greedy only.

        Block tables are pre-extended for the emission window plus
        ``n_spec`` transient slots (rejected-draft KV writes land past the
        last accepted token and are overwritten by later steps).
        """
        n = len(uids)
        n_spec = self.config.spec_decode
        m = 1 + n_spec
        rows = -(-n // self.config.row_bucket) * self.config.row_bucket
        with self._tracer.span("serve:assemble", kind="spec_chain", rows=rows):
            buf = self._chain_arrays(rows)
            sb = self._spec_buf.get(rows)
            if sb is None:
                sb = {"hist": np.zeros((rows, self.max_seq_len), np.int32),
                      "hist_len": np.zeros((rows,), np.int32)}
                self._spec_buf[rows] = sb
            else:
                sb["hist"][:] = 0
                sb["hist_len"][:] = 0
            for i, uid in enumerate(uids):
                window = min(k * m, int(budgets[i]))
                seq = self.state.extend(uid, window + n_spec)
                buf["tables"][i, : seq.n_blocks] = seq.blocks
                buf["pos"][i] = seq.seen_tokens
                h = histories[i]
                sb["hist"][i, : len(h)] = h
                sb["hist_len"][i] = len(h)
            buf["tokens"][:n] = last_tokens
            buf["active"][:n] = True
            buf["budgets"][:n] = np.minimum(budgets, k * m)
        chain = self._spec_chain_fn(rows, k, eos_id)
        with self._tracer.span("serve:dispatch", kind="spec_chain", rows=rows,
                               k=k, n_spec=n_spec):
            if tracker is not None and rids is not None:
                tracker.mark_dispatch(rids, "chain")
            out, emitted, _, steps, rng, self.pool = chain(
                self.params, self.pool,
                jnp.asarray(buf["tokens"]), jnp.asarray(buf["pos"]),
                jnp.asarray(buf["tables"]), jnp.asarray(buf["active"]),
                jnp.asarray(buf["budgets"]), rng,
                jnp.asarray(sb["hist"]), jnp.asarray(sb["hist_len"]),
            )
        self.dispatch_count += 1
        with self._tracer.span("serve:fetch", kind="spec_chain"):
            out = np.asarray(out[:n])
            emitted = np.asarray(emitted[:n])
            steps = np.asarray(steps[:n])
        self.host_sync_count += 1
        for uid, e in zip(uids, emitted):
            self.state.get(uid).seen_tokens += int(e)
        self.spec_model_steps += int(steps.sum())
        self.spec_tokens_emitted += int(emitted.sum())
        return out, emitted, rng

    # ----------------------------------------------------------- numerics plane
    def _numerics_probe_chain(self, n_spec: int) -> None:
        """Serving-fidelity probes (telemetry/numerics.py plane 3), sampled
        at decode-chain boundaries: KV dequant round-trip error for the
        quantized pool formats, WOQ matmul error for the quantized weight
        format, and the spec-decode acceptance-rate trend alarm (PR-2
        median+MAD, low side). Standalone dispatches — the compiled decode
        programs are untouched; a single attribute check when disabled."""
        from deepspeed_tpu.telemetry import numerics as numerics_mod

        nm = numerics_mod.get_observatory()
        if not nm.enabled:
            return
        if n_spec > 0 and self.spec_model_steps:
            nm.note_spec_accept(
                (self.spec_tokens_emitted - self.spec_model_steps)
                / (self.spec_model_steps * n_spec))
        every = max(1, int(nm.config.sample_every))
        if self.chain_steps % every != 0:
            return
        kvq = self.config.kv_quant
        if kvq is not None:
            nm.kv_dequant_probe(kvq,
                                head_dim=self.model_config.dims_per_head)
        if self.config.quant.enabled:
            from deepspeed_tpu.inference.woq import woq_format

            nm.woq_matmul_probe(woq_format(self.config.quant))

    # ---------------------------------------------------------------- serving loop
    def generate(
        self,
        prompts: Sequence[np.ndarray],
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: int = 0,
        arrival_times: Optional[Sequence[float]] = None,
    ) -> List[np.ndarray]:
        """Convenience continuous-batching loop (the MII serving-layer analog).

        Admission and preemption happen at chain boundaries: each round
        admits pending prompts as one fused prefill+sample step, then decodes
        every active sequence with one K-step chained program (T3 discipline,
        arxiv 2401.16677 — the host prepares the next round while the device
        runs the current chain). When the pool cannot fit the next chain
        window, the chain first shrinks, then the youngest active sequence is
        preempted (flushed and re-queued with its full context, reference
        FastGen scheduler behavior) rather than crashing mid-generation.

        ``arrival_times`` (seconds relative to the call, one per prompt)
        turns the batch call into an open-loop workload: a prompt enters the
        admission queue only once its arrival time has passed — this is what
        ``tools/bench_serving.py --slo`` drives to measure TTFT/queue-wait
        under a synthetic arrival pattern. None (default) queues everything
        immediately, exactly the previous behavior.

        When the telemetry tracer is enabled (or ``flight_recorder`` is
        configured) every request is lifecycle-tracked (arrival -> admission
        -> first token -> chain boundaries -> finish): ``serving/*`` SLO
        metrics land in the shared registry and each finished request emits
        its own Perfetto track with flow arrows into the dispatch spans that
        served it (``inference/lifecycle.py``). Disabled, no per-request
        records are allocated and the loop is unchanged.
        """
        prompts = [np.asarray(p, np.int32) for p in prompts]
        pool_tokens = self.num_kv_blocks * self.config.kv_block_size
        n_spec = self.config.spec_decode
        if n_spec > 0 and do_sample:
            raise ValueError(
                "spec_decode is greedy-only (verify-and-accept compares "
                "argmax targets); disable do_sample or set spec_decode=0")
        # spec chains write up to n_spec transient (rejected-draft) KV slots
        # past the last emitted token — the length guards carry that margin
        margin = n_spec
        for i, p in enumerate(prompts):
            if len(p) + max_new_tokens + margin > self.max_seq_len:
                raise ValueError(
                    f"prompt {i} ({len(p)} tokens) + max_new_tokens={max_new_tokens} "
                    f"(+{margin} speculative slack) exceeds engine "
                    f"max_seq_len={self.max_seq_len}"
                )
            if len(p) + max_new_tokens + margin > pool_tokens:
                raise ValueError(
                    f"prompt {i} ({len(p)} tokens) + max_new_tokens={max_new_tokens} "
                    f"cannot ever fit the KV pool ({pool_tokens} slots); no amount of "
                    f"preemption can complete it"
                )
        sample_kw = (("do_sample", do_sample), ("temperature", temperature),
                     ("top_k", top_k), ("top_p", top_p))
        t_start = time.perf_counter()
        arr: Optional[List[float]] = None
        if arrival_times is not None:
            if len(arrival_times) != len(prompts):
                raise ValueError(
                    f"arrival_times has {len(arrival_times)} entries for "
                    f"{len(prompts)} prompts")
            arr = [float(a) for a in arrival_times]
            queue: deque = deque(sorted(range(len(prompts)), key=lambda i: arr[i]))
        else:
            queue = deque(range(len(prompts)))  # idx, FIFO
        gen: Dict[int, List[int]] = {i: [] for i in range(len(prompts))}
        active: Dict[int, int] = {}  # uid -> idx
        order: Dict[int, None] = {}  # admission order (insertion-ordered set)
        outputs: Dict[int, np.ndarray] = {}
        # committed key, replicated like every step output: a fresh PRNGKey
        # is uncommitted, but the key a chain returns carries
        # NamedSharding(mesh, P()) — jit caches on that difference, so an
        # uncommitted first key makes the SECOND admission wave recompile
        # the prefill program mid-serving (a ~0.4s TTFT cliff under bursts)
        rng = jax.device_put(jax.random.PRNGKey(seed),
                             NamedSharding(self.mesh, P()))
        next_uid = 0
        registry = self._tracer.registry if self._tracer.enabled else None

        # ---- per-request lifecycle tracking (None = nothing allocated)
        tracker: Optional[LifecycleTracker] = None
        if self._tracer.enabled or self._recorder is not None:
            tracker = LifecycleTracker(
                self._tracer, slo=self.config.serving_slo,
                labels={"k": self.config.decode_chain},
                recorder=self._recorder)
            for i in range(len(prompts)):
                tracker.arrive(i, now=t_start + (arr[i] if arr is not None else 0.0))
        self.lifecycle = tracker
        if registry is not None:
            # the cheap scheduler/pool gauges, refreshed at chain boundaries
            # (handles resolved once — the loop pays plain attribute sets)
            g_queue = registry.gauge("serving/queue_depth")
            g_occ = registry.gauge("serving/batch_occupancy")
            g_free = registry.gauge("serving/kv_pool_free_blocks")
            kv_name = self.config.kv_dtype_name
            g_util = registry.gauge("serving/kv_pool_utilization", dtype=kv_name)
            # quantized-serving capacity facts (set once — they are config,
            # not chain-boundary state): which storage the pool runs and what
            # one token slot costs, the number capacity plans divide HBM by
            registry.gauge("serving/kv_pool_dtype", dtype=kv_name).set(1.0)
            registry.gauge("serving/kv_bytes_per_token").set(
                float(self.kv_bytes_per_token))
            c_preempt = registry.counter("serving/preemptions")
            c_tokens = registry.counter("serving/tokens_decoded")
            c_chains = registry.counter("serving/chains")
            h_chain_len = registry.histogram("serving/chain_len")
            g_pfx_hit = g_pfx_blocks = g_spec_acc = g_spec_tpf = None
            if self.prefix_cache is not None:
                g_pfx_hit = registry.gauge("serving/prefix_hit_rate")
                g_pfx_blocks = registry.gauge("serving/prefix_cached_blocks")
            if self.config.spec_decode > 0:
                g_spec_acc = registry.gauge("serving/spec_accept_rate")
                g_spec_tpf = registry.gauge("serving/spec_tokens_per_forward")

        def context(idx: int) -> np.ndarray:
            return np.concatenate([prompts[idx], np.asarray(gen[idx], np.int32)])

        def accept(u: int, t: int) -> None:
            """Record token t for uid u; retire the row if done."""
            idx = active[u]
            gen[idx].append(int(t))
            if len(gen[idx]) >= max_new_tokens or (
                eos_token_id is not None and int(t) == eos_token_id
            ):
                outputs[idx] = np.asarray(gen[idx], np.int32)
                active.pop(u)
                order.pop(u)
                self.flush(u)
                if tracker is not None:
                    tracker.finish(idx)

        pc = self.prefix_cache
        while queue or active:
            # ---- admit pending prompts (fused prefill + first-token sample)
            adm_uids: List[int] = []
            adm_tokens: List[np.ndarray] = []
            adm_counts: List[int] = []
            adm_full: List[np.ndarray] = []  # full contexts, for cache insert
            decoding = list(active.keys())  # reserve 1-token decode headroom
            while queue and len(active) < self.config.max_seqs:
                idx = queue[0]
                if arr is not None and time.perf_counter() - t_start < arr[idx]:
                    break  # open-loop workload: not arrived yet
                cand = context(idx)
                suffix = self.try_admit(
                    next_uid, cand, decoding + adm_uids,
                    [1] * len(decoding) + adm_counts)
                if suffix is None:
                    break
                queue.popleft()
                adm_uids.append(next_uid)
                adm_tokens.append(suffix)
                adm_counts.append(len(suffix))
                adm_full.append(cand)
                if tracker is not None:
                    tracker.admit(idx, next_uid)
                active[next_uid] = idx
                order[next_uid] = None
                next_uid += 1
            if adm_uids:
                adm_rids = [active[u] for u in adm_uids]
                toks, rng = self._put_sample(adm_uids, adm_tokens, rng, sample_kw,
                                             tracker=tracker, rids=adm_rids)
                if pc is not None:
                    # index the freshly written full blocks (quantized bytes
                    # are in the pool now — hashes snapshot them as written)
                    for u, full in zip(adm_uids, adm_full):
                        self._insert_prefix(u, full)
                if tracker is not None:
                    tracker.emitted_batch(adm_rids, (1,) * len(adm_rids))
                for u, t in zip(adm_uids, toks):
                    accept(u, t)
            if not active:
                if queue and not adm_uids:
                    if arr is not None:
                        wait = t_start + arr[queue[0]] - time.perf_counter()
                        if wait > 0:  # idle until the next synthetic arrival
                            time.sleep(min(wait, 0.05))
                            continue
                    raise RuntimeError(
                        f"KV pool too small for a single sequence "
                        f"({self.num_kv_blocks} blocks x {self.config.kv_block_size})"
                    )
                continue

            # ---- one chained decode over the active set. K stays pinned at
            # decode_chain so one compiled program serves every chain (per-row
            # budget masks inside the scan handle the max_new_tokens tail);
            # only KV-pool pressure shrinks the window, then preempts. With
            # speculative decoding each of the K forwards may emit up to
            # 1+n_spec tokens, so the KV window scales by that factor plus
            # the n_spec transient-write slack.
            uids = list(active.keys())
            budgets = [max_new_tokens - len(gen[active[u]]) for u in uids]
            k = self.config.decode_chain
            while True:
                while k > 1 and not self._can_schedule_evicting(
                        uids, self.chain_window(budgets, k)):
                    k -= 1
                if self._can_schedule_evicting(uids, self.chain_window(budgets, k)):
                    break
                victim = next(reversed(order))
                del order[victim]
                i = uids.index(victim)
                uids.pop(i)
                budgets.pop(i)
                idx = active.pop(victim)
                self.flush(victim)
                queue.appendleft(idx)
                if tracker is not None:
                    tracker.preempt(idx)
                if registry is not None:
                    c_preempt.add(1.0)
                if not uids:
                    raise RuntimeError(
                        f"KV pool too small for a single sequence "
                        f"({self.num_kv_blocks} blocks x {self.config.kv_block_size})"
                    )
                k = self.config.decode_chain
            last = [gen[active[u]][-1] for u in uids]
            chain_rids = [active[u] for u in uids]
            if n_spec > 0:
                histories = [context(active[u]) for u in uids]
                out, emitted, rng = self.decode_spec_chain(
                    uids, last, budgets, k, rng, histories,
                    eos_id=eos_token_id, tracker=tracker, rids=chain_rids)
            else:
                out, emitted, rng = self.decode_chain(
                    uids, last, budgets, k, rng, eos_id=eos_token_id,
                    sample_kw=sample_kw, tracker=tracker, rids=chain_rids)
            n_emitted = int(emitted.sum())
            self.tokens_decoded += n_emitted
            # serving liveness for /healthz + fleet heartbeats: a decode
            # chain is this engine's "step" (two plain writes)
            self.chain_steps += 1
            _fleet_note_step(self.chain_steps)
            if tracker is not None:
                # ONE stamp per chain boundary; TPOT = boundary delta / tokens
                now = time.perf_counter()
                tracker.emitted_batch(chain_rids, emitted, now=now)
                tracker.sample_gauges(now=now)
            if registry is not None:
                c_tokens.add(n_emitted)
                c_chains.add(1.0)
                h_chain_len.observe(float(k))
                g_queue.set(float(len(queue)))
                g_occ.set(len(active) / self.config.max_seqs)
                g_free.set(float(self.state.free_blocks))
                g_util.set(self.state.utilization)
                if g_pfx_hit is not None:
                    g_pfx_hit.set(pc.hit_rate)
                    g_pfx_blocks.set(float(len(pc)))
                if g_spec_acc is not None and self.spec_model_steps:
                    g_spec_acc.set(
                        (self.spec_tokens_emitted - self.spec_model_steps)
                        / (self.spec_model_steps * n_spec))
                    g_spec_tpf.set(
                        self.spec_tokens_emitted / self.spec_model_steps)
            self._numerics_probe_chain(n_spec)
            for i, u in enumerate(uids):
                for t in out[i, : emitted[i]]:
                    if u in active:
                        accept(u, t)
        if tracker is not None:
            # final refresh: the last finishes land after the last chain
            # boundary's sample, so goodput/tokens-per-s see them here
            tracker.sample_gauges()
        if registry is not None:
            g_queue.set(0.0)
            g_occ.set(0.0)
            g_free.set(float(self.state.free_blocks))
            g_util.set(self.state.utilization)
        return [outputs[i] for i in range(len(prompts))]
