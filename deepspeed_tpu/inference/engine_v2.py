"""Continuous-batching inference engine (FastGen analog).

TPU-native analog of reference ``InferenceEngineV2``
(``inference/v2/engine_v2.py:30``): sequences identified by uid, tokens pushed
via ``put(uids, tokens)``, KV state lives in a paged pool addressed through
per-sequence block tables, and admission control (``can_schedule``/``query``)
lets a serving loop pack prefill chunks and decodes into one step.

Differences from the reference, by TPU design:
  - one jitted ragged step program per (rows, chunk) bucket instead of a
    kernel zoo; the paged gather/attention lives in ``paged.py``
  - the scheduler-facing API is identical in shape, but scheduling quanta are
    bucket sizes (static shapes) rather than arbitrary token counts
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.config.config_utils import DeepSpeedConfigModel
from deepspeed_tpu.inference.config import InferenceConfig
from deepspeed_tpu.inference.paged import PagedKVPool, init_pool, ragged_forward
from deepspeed_tpu.inference.ragged import RaggedBatch, StateManager, build_ragged_batch
from deepspeed_tpu.inference.sampling import sample_logits
from deepspeed_tpu.models.transformer import TransformerConfig, causal_lm_partition_rules
from deepspeed_tpu.parallel.autotp import place_parameters
from deepspeed_tpu.topology.mesh import build_mesh, set_mesh
from deepspeed_tpu.utils.logging import log_dist


class RaggedInferenceConfig(DeepSpeedConfigModel):
    """v2 engine config (reference ``RaggedInferenceEngineConfig``:
    state-manager + KV-cache sizing)."""

    dtype: str = "bf16"
    tp_size: int = 1
    kv_block_size: int = 16
    num_kv_blocks: int = 512
    max_seqs: int = 64  # max concurrently tracked sequences
    max_seq_len: Optional[int] = None  # default: model max_seq_len
    row_bucket: int = 8
    chunk_bucket: int = 16

    @property
    def jax_dtype(self):
        from deepspeed_tpu.inference.config import _DTYPES

        return _DTYPES[self.dtype.lower()]


def build_hf_engine(
    path: str,
    config: Union["RaggedInferenceConfig", Dict, None] = None,
    mesh: Optional[Mesh] = None,
) -> "InferenceEngineV2":
    """One call from a HuggingFace checkpoint directory to a serving engine
    (reference ``inference/v2/engine_factory.py:69 build_hf_engine`` — there a
    policy zoo maps each family onto kernel containers; here the 13-family
    ingestion in ``checkpoint/hf.py`` produces the generic ragged
    transformer's pytree directly)."""
    from deepspeed_tpu.checkpoint.hf import load_hf_checkpoint

    model_config, params = load_hf_checkpoint(path)
    return InferenceEngineV2(model_config, params, config, mesh=mesh)


class InferenceEngineV2:
    """uid-keyed continuous batching over a paged KV pool."""

    def __init__(
        self,
        model_config: TransformerConfig,
        params: Any,
        config: Union[RaggedInferenceConfig, Dict, None] = None,
        mesh: Optional[Mesh] = None,
    ):
        if config is None:
            config = {}
        if isinstance(config, dict):
            config = RaggedInferenceConfig(**config)
        self.model_config = model_config
        self.config = config
        if mesh is None:
            mesh = build_mesh(axis_sizes={"tp": config.tp_size, "dp": -1})
        self.mesh = mesh
        set_mesh(mesh)

        max_len = config.max_seq_len or model_config.max_seq_len
        self.max_seq_len = max_len
        self.max_pages = -(-max_len // config.kv_block_size)
        self.state = StateManager(config.num_kv_blocks, config.kv_block_size, config.max_seqs,
                                  max_blocks_per_seq=self.max_pages)

        dtype = config.jax_dtype
        self.params = place_parameters(params, mesh, causal_lm_partition_rules, dtype)
        # KV pool: kv-head dim over tp, slots replicated over dp
        pool = init_pool(model_config, config.num_kv_blocks, config.kv_block_size, dtype)
        kv_on_tp = model_config.kv_heads % mesh.shape["tp"] == 0
        if not kv_on_tp and mesh.shape["tp"] > 1:
            # correct but a quiet perf/memory cliff: each tp rank holds the
            # FULL pool instead of 1/tp of it (round-3 verdict weak item 8)
            log_dist(
                f"KV pool REPLICATED over tp={mesh.shape['tp']}: kv_heads="
                f"{model_config.kv_heads} not divisible — expect tp-times the "
                "per-chip KV memory; pick tp dividing kv_heads to shard it",
                ranks=[0],
            )
        kv_spec = NamedSharding(mesh, P(None, None, "tp" if kv_on_tp else None, None))
        self.pool = PagedKVPool(k=jax.device_put(pool.k, kv_spec), v=jax.device_put(pool.v, kv_spec))
        n_params = sum(x.size for x in jax.tree_util.tree_leaves(self.params))
        log_dist(
            f"InferenceEngineV2: {n_params/1e6:.1f}M params, "
            f"{config.num_kv_blocks}x{config.kv_block_size} KV slots, mesh={dict(mesh.shape)}"
        )
        self._step_cache: Dict[Tuple[int, int], Any] = {}

    # ---------------------------------------------------------------- admission
    def query(self, uid: int) -> Tuple[int, int]:
        """(seen_tokens, free_kv_slots) for scheduler accounting (reference
        ``engine_v2.query`` :158)."""
        seq = self.state.get(uid)
        seen = seq.seen_tokens if seq is not None else 0
        return seen, self.state.free_blocks * self.config.kv_block_size

    def can_schedule(self, uids: Sequence[int], token_counts: Sequence[int]) -> bool:
        return self.state.can_schedule(uids, token_counts)

    def flush(self, uid: int) -> None:
        self.state.flush(uid)

    # ---------------------------------------------------------------- put
    def _step_fn(self, rows: int, chunk: int):
        key = (rows, chunk)
        if key not in self._step_cache:
            cfg = self.model_config
            bs = self.config.kv_block_size

            @functools.partial(jax.jit, donate_argnums=(1,))
            def step(params, pool, tokens, positions, new_lens, block_tables):
                return ragged_forward(params, cfg, pool, tokens, positions, new_lens, block_tables, bs)

            self._step_cache[key] = step
        return self._step_cache[key]

    def put(self, uids: Sequence[int], token_lists: Sequence[np.ndarray]) -> np.ndarray:
        """Push new tokens for each uid; returns last-token logits [len(uids), V]
        (reference ``engine_v2.put`` :107). Mixed prefill/decode is fine —
        pass a whole prompt for new sequences and single tokens for decodes."""
        if not self.can_schedule(uids, [len(t) for t in token_lists]):
            raise RuntimeError("insufficient KV blocks/slots; call can_schedule first")
        batch = build_ragged_batch(
            self.state, uids, token_lists, self.max_pages,
            self.config.row_bucket, self.config.chunk_bucket,
        )
        step = self._step_fn(batch.n_rows, batch.tokens.shape[1])
        logits, self.pool = step(
            self.params, self.pool,
            jnp.asarray(batch.tokens), jnp.asarray(batch.positions),
            jnp.asarray(batch.new_lens), jnp.asarray(batch.block_tables),
        )
        for uid, toks in zip(uids, token_lists):
            self.state.get(uid).seen_tokens += len(toks)
        return np.asarray(logits[: len(uids)])

    # ---------------------------------------------------------------- serving loop
    def generate(
        self,
        prompts: Sequence[np.ndarray],
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: int = 0,
    ) -> List[np.ndarray]:
        """Convenience continuous-batching loop (the MII serving-layer analog).

        Each step is ONE ``put`` mixing newly admitted prompts (prefill) with
        single-token decodes of the active set. When the pool cannot fit the
        next decode step, the youngest active sequence is preempted (flushed
        and re-queued with its full context, reference FastGen scheduler
        behavior) rather than crashing mid-generation.
        """
        prompts = [np.asarray(p, np.int32) for p in prompts]
        pool_tokens = self.config.num_kv_blocks * self.config.kv_block_size
        for i, p in enumerate(prompts):
            if len(p) + max_new_tokens > self.max_seq_len:
                raise ValueError(
                    f"prompt {i} ({len(p)} tokens) + max_new_tokens={max_new_tokens} "
                    f"exceeds engine max_seq_len={self.max_seq_len}"
                )
            if len(p) + max_new_tokens > pool_tokens:
                raise ValueError(
                    f"prompt {i} ({len(p)} tokens) + max_new_tokens={max_new_tokens} "
                    f"cannot ever fit the KV pool ({pool_tokens} slots); no amount of "
                    f"preemption can complete it"
                )
        queue: List[int] = list(range(len(prompts)))  # idx, FIFO
        gen: Dict[int, List[int]] = {i: [] for i in queue}
        active: Dict[int, int] = {}  # uid -> idx
        order: List[int] = []  # admission order (youngest last) for preemption
        outputs: Dict[int, np.ndarray] = {}
        rng = jax.random.PRNGKey(seed)
        next_uid = 0

        def context(idx: int) -> np.ndarray:
            return np.concatenate([prompts[idx], np.asarray(gen[idx], np.int32)])

        while queue or active:
            # decode every active sequence
            step_uids = list(active.keys())
            step_tokens: List[np.ndarray] = [np.asarray([gen[active[u]][-1]], np.int32)
                                             for u in step_uids]
            counts = [1] * len(step_uids)
            # make room for decodes: preempt youngest until the step fits
            while step_uids and not self.state.can_schedule(step_uids, counts):
                victim = order.pop()
                i = step_uids.index(victim)
                step_uids.pop(i), step_tokens.pop(i), counts.pop(i)
                idx = active.pop(victim)
                self.flush(victim)
                queue.insert(0, idx)
            # admit pending prompts that fit alongside the decodes
            while queue and len(active) + 1 <= self.config.max_seqs:
                idx = queue[0]
                cand = context(idx)
                if not self.state.can_schedule(step_uids + [next_uid], counts + [len(cand)]):
                    break
                queue.pop(0)
                step_uids.append(next_uid)
                step_tokens.append(cand)
                counts.append(len(cand))
                active[next_uid] = idx
                order.append(next_uid)
                next_uid += 1
            if not step_uids:
                raise RuntimeError(
                    f"KV pool too small for a single sequence "
                    f"({self.config.num_kv_blocks} blocks x {self.config.kv_block_size})"
                )
            logits = self.put(step_uids, step_tokens)
            rng, sub = jax.random.split(rng)
            toks = np.asarray(sample_logits(
                jnp.asarray(logits), sub, do_sample=do_sample,
                temperature=temperature, top_k=top_k, top_p=top_p,
            ))
            for u, t in zip(step_uids, toks):
                idx = active[u]
                gen[idx].append(int(t))
                if len(gen[idx]) >= max_new_tokens or (
                    eos_token_id is not None and int(t) == eos_token_id
                ):
                    outputs[idx] = np.asarray(gen[idx], np.int32)
                    active.pop(u)
                    order.remove(u)
                    self.flush(u)
        return [outputs[i] for i in range(len(prompts))]
