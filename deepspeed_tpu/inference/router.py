"""SLO-aware continuous-batching router over N engine_v2 replicas.

The serving tier's front end (ROADMAP open item 1a): one process-level
scheduler dispatching requests over N :class:`InferenceEngineV2` replicas.
The engines' serving loop (``generate``) stays the single-replica path; the
router drives the same primitives directly — ``can_schedule`` admission,
fused ``_put_sample`` prefill, ``decode_chain``/``decode_spec_chain`` — so
every fast-path invariant (one dispatch + one host sync per K tokens,
on-device sampling, prefix-cache reuse, speculative chains) holds per
replica unchanged.

Scheduling model (single-threaded, chain-granular):

  - **Assignment**: an arrived request is bound to the least-loaded replica.
    The load signal is the same per-replica ``serving/queue_depth`` /
    ``serving/goodput`` state the PR-5 gauges expose — assigned-but-waiting
    plus active rows, discounted by the replica's rolling goodput (a replica
    missing its SLO window attracts less new load).
  - **SLO-aware admission** (``serving_slo`` config block): before a prefill
    is dispatched, the request's projected TTFT — wait so far plus the
    replica's EMA time-to-first-token — is checked against
    ``ttft_ms * admission_ttft_factor``. ``admission="shed"`` rejects a
    request that can no longer make its budget (it returns ``None`` and
    stops consuming queue capacity that on-budget requests could use);
    ``"defer"`` holds it queued while any replica could still make the
    budget and sheds only when none can. Shedding happens strictly BEFORE
    admission: an admitted request is never dropped (the nightly router
    smoke gates on exactly that).
  - **Replica-affine re-admission**: a preemption at a chain boundary
    re-queues the request pinned to its replica, so its prefix-cache
    blocks there (PR-12 content-hash reuse) make the re-prefill nearly
    free — the preempted context re-enters through the cache instead of
    recomputing.

Observability: per-replica ``LifecycleTracker``s (labels ``{"replica": i}``)
feed the standard ``serving/*`` SLO metrics per replica, ``router/*``
counters/gauges cover the router's own decisions, and each replica gets its
own Perfetto track with one slice per dispatched program.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
from deepspeed_tpu.inference.lifecycle import LifecycleTracker
from deepspeed_tpu.telemetry import get_tracer
from deepspeed_tpu.telemetry import fleet

# virtual Perfetto track ids for replica tracks (request tracks live at
# lifecycle.TRACK_BASE = 0x5E51_0000; replicas get their own range)
REPLICA_TRACK_BASE = 0x5E52_0000


class _Replica:
    """Router-side view of one engine replica."""

    def __init__(self, index: int, engine: InferenceEngineV2):
        self.index = index
        self.engine = engine
        self.active: Dict[int, int] = {}  # uid -> rid
        self.order: Dict[int, None] = {}  # admission order (insertion-ordered)
        self.assigned: deque = deque()  # rids bound here, not yet admitted
        self.tracker: Optional[LifecycleTracker] = None
        # host-observed EMAs (seconds): the admission gate's TTFT projection
        self.prefill_ema = 0.0
        self.chain_ema = 0.0
        self.dispatches = 0

    def load(self) -> float:
        """Queue-depth-based load score, goodput-discounted: replicas
        missing their SLO window attract less new load."""
        depth = len(self.assigned) + len(self.active)
        goodput = 1.0
        if self.tracker is not None and self.tracker._emit:
            g = self.tracker._g_goodput.value
            if g is not None and self.tracker._win_slo:
                goodput = float(g)
        return depth + (1.0 - goodput)

    def ema(self, attr: str, value: float, alpha: float = 0.3) -> None:
        cur = getattr(self, attr)
        setattr(self, attr, value if cur == 0.0 else (1 - alpha) * cur + alpha * value)


class ServingRouter:
    """Continuous-batching front end over N engine replicas.

    ``engines`` must share model/config semantics (the router assumes any
    replica can serve any request). ``slo`` defaults to the first engine's
    ``serving_slo`` block; ``clock`` is injectable so the admission gate is
    testable against a fake clock.
    """

    def __init__(self, engines: Sequence[InferenceEngineV2], slo=None,
                 clock=time.perf_counter):
        if not engines:
            raise ValueError("ServingRouter needs at least one engine replica")
        self.replicas = [_Replica(i, e) for i, e in enumerate(engines)]
        self.slo = slo if slo is not None else engines[0].config.serving_slo
        self._clock = clock
        self._tracer = get_tracer()
        # decision accounting (always on — the smoke and tests read these)
        self.shed_count = 0
        self.deferred_count = 0
        self.preemptions = 0
        self.affine_readmits = 0
        # distributed-trace contexts minted per request (fleet.TraceContext):
        # rid -> ctx; the wire form (`dispatch_context`) is what a real
        # process-boundary replica receives with its dispatch, and the flow
        # id is derived from (run_id, rid) so BOTH processes compute it —
        # the in-process replicas consume it through the lifecycle trackers
        self._trace_ctx: Dict[int, fleet.TraceContext] = {}
        self._request_seq = 0
        # multi-process crash forensics: a replica's flight-recorder dumps
        # must name which replica (and which run) they came from
        ident = fleet.get_identity()
        for rep in self.replicas:
            rec = getattr(rep.engine, "_recorder", None)
            if rec is not None:
                rec.set_context(replica=rep.index, run_id=ident.run_id,
                                process_index=ident.process_index)

    @classmethod
    def build(cls, model_config, params, engine_config=None, replicas: int = 2,
              **kw) -> "ServingRouter":
        """N replicas from one (config, params) — each gets its own KV pool
        and scheduler state; params are shared (same host arrays)."""
        engines = [InferenceEngineV2(model_config, params, dict(engine_config or {}))
                   for _ in range(replicas)]
        return cls(engines, **kw)

    # ------------------------------------------------------------ admission
    def _projected_ttft_s(self, waited_s: float, rep: _Replica) -> float:
        """Wait so far + the replica's estimated time to first token: one
        prefill dispatch — which the scheduling round runs BEFORE the decode
        chains, so a replica with admission capacity prefills immediately; a
        full replica adds one chain boundary (its earliest slot)."""
        est = rep.prefill_ema
        if len(rep.active) >= rep.engine.config.max_seqs:
            est += rep.chain_ema
        return waited_s + est

    def _admission_decision(self, waited_s: float, rep: _Replica) -> str:
        """'admit' | 'defer' | 'shed' for a request that has waited
        ``waited_s`` and would prefill on ``rep`` next. Pure function of the
        SLO block + replica EMAs — pinned by the fake-clock tests."""
        slo = self.slo
        mode = getattr(slo, "admission", "none") if slo is not None else "none"
        ttft_ms = getattr(slo, "ttft_ms", None) if slo is not None else None
        if mode == "none" or ttft_ms is None:
            return "admit"
        budget_s = ttft_ms * getattr(slo, "admission_ttft_factor", 1.0) / 1e3
        if self._projected_ttft_s(waited_s, rep) <= budget_s:
            return "admit"
        if mode == "defer":
            # hold while ANY replica could still make the budget; shed only
            # when the wait alone has already blown it everywhere
            if any(self._projected_ttft_s(waited_s, r) <= budget_s
                   for r in self.replicas):
                return "defer"
            return "shed" if waited_s > budget_s else "defer"
        return "shed"

    def _least_loaded(self) -> _Replica:
        return min(self.replicas, key=lambda r: (r.load(), r.index))

    # ---------------------------------------------------------------- serve
    def serve(
        self,
        prompts: Sequence[np.ndarray],
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: int = 0,
        arrival_times: Optional[Sequence[float]] = None,
    ) -> List[Optional[np.ndarray]]:
        """Route ``prompts`` across the replicas; returns one output per
        prompt, ``None`` for requests the admission gate shed. The loop is
        the engine's ``generate`` lifted one level: assignment + SLO gate,
        then per replica the admit/prefill/chain round — each replica's
        device work is still one fused program per phase."""
        prompts = [np.asarray(p, np.int32) for p in prompts]
        n_req = len(prompts)
        spec = self.replicas[0].engine.config.spec_decode > 0
        if spec and do_sample:
            raise ValueError(
                "spec_decode is greedy-only (verify-and-accept compares "
                "argmax targets); disable do_sample or set spec_decode=0")
        # the same feasibility guards engine.generate applies — a prompt no
        # replica can ever serve must raise here, not stall the router loop
        for rep in self.replicas:
            eng = rep.engine
            pool_tokens = eng.num_kv_blocks * eng.config.kv_block_size
            margin = eng.config.spec_decode
            for i, p in enumerate(prompts):
                if len(p) + max_new_tokens + margin > eng.max_seq_len:
                    raise ValueError(
                        f"prompt {i} ({len(p)} tokens) + max_new_tokens="
                        f"{max_new_tokens} (+{margin} speculative slack) "
                        f"exceeds replica {rep.index} max_seq_len={eng.max_seq_len}")
                if len(p) + max_new_tokens + margin > pool_tokens:
                    raise ValueError(
                        f"prompt {i} ({len(p)} tokens) + max_new_tokens="
                        f"{max_new_tokens} cannot ever fit replica "
                        f"{rep.index}'s KV pool ({pool_tokens} slots)")
        sample_kw = (("do_sample", do_sample), ("temperature", temperature),
                     ("top_k", top_k), ("top_p", top_p))
        t_start = self._clock()
        if arrival_times is not None and len(arrival_times) != n_req:
            raise ValueError(
                f"arrival_times has {len(arrival_times)} entries for {n_req} prompts")
        arr = [float(a) for a in arrival_times] if arrival_times is not None \
            else [0.0] * n_req
        pending = deque(sorted(range(n_req), key=lambda i: arr[i]))
        # one TraceContext per request, fleet-unique request ids (monotonic
        # across serve() calls): the flow id both the admission arrow here
        # and a remote replica's serve:dispatch step derive independently
        seq0 = self._request_seq
        self._request_seq += n_req
        self._trace_ctx = {i: fleet.TraceContext.mint(seq0 + i)
                           for i in range(n_req)}
        affinity: List[Optional[int]] = [None] * n_req
        admitted_once: set = set()  # rids that ever dispatched a prefill
        gen: Dict[int, List[int]] = {i: [] for i in range(n_req)}
        outputs: Dict[int, Optional[np.ndarray]] = {}
        # committed replicated key, like engine.generate: an uncommitted
        # PRNGKey makes every replica's second admission wave recompile its
        # prefill program mid-burst (jit caches on committed-ness)
        from jax.sharding import NamedSharding, PartitionSpec as P

        rng = jax.device_put(jax.random.PRNGKey(seed),
                             NamedSharding(self.replicas[0].engine.mesh, P()))
        next_uid = 0
        tr = self._tracer
        registry = tr.registry if tr.enabled else None

        if registry is not None:
            c_requests = registry.counter("router/requests")
            c_shed = registry.counter("router/shed_requests")
            c_defer = registry.counter("router/deferred")
            c_preempt = registry.counter("router/preemptions")
            c_affine = registry.counter("router/affine_readmissions")
            g_depth = [registry.gauge("router/replica_queue_depth",
                                      replica=r.index) for r in self.replicas]
            g_active = [registry.gauge("router/replica_active", replica=r.index)
                        for r in self.replicas]
            c_disp = [registry.counter("router/dispatches", replica=r.index)
                      for r in self.replicas]
            c_requests.add(float(n_req))
            for r in self.replicas:
                tr.name_track(REPLICA_TRACK_BASE + r.index, f"replica {r.index}")
        for r in self.replicas:
            if tr.enabled or r.engine._recorder is not None:
                r.tracker = LifecycleTracker(
                    tr, slo=self.slo, clock=self._clock,
                    labels={"k": r.engine.config.decode_chain,
                            "replica": r.index},
                    recorder=r.engine._recorder)

        def context(idx: int) -> np.ndarray:
            return np.concatenate([prompts[idx], np.asarray(gen[idx], np.int32)])

        def replica_span(rep: _Replica, name: str, t0: float, t1: float) -> None:
            if registry is None:
                return
            tr.append_events([{
                "kind": "span", "name": name, "cat": "router",
                "ts": t0 - tr.origin(), "dur": max(t1 - t0, 0.0),
                "tid": REPLICA_TRACK_BASE + rep.index,
                "args": {"replica": rep.index}}])

        def accept(rep: _Replica, u: int, t: int) -> None:
            idx = rep.active[u]
            gen[idx].append(int(t))
            if len(gen[idx]) >= max_new_tokens or (
                    eos_token_id is not None and int(t) == eos_token_id):
                outputs[idx] = np.asarray(gen[idx], np.int32)
                rep.active.pop(u)
                rep.order.pop(u)
                rep.engine.flush(u)
                if rep.tracker is not None:
                    rep.tracker.finish(idx)

        def shed(idx: int, rep: Optional[_Replica]) -> None:
            outputs[idx] = None
            self.shed_count += 1
            if registry is not None:
                c_shed.add(1.0)
            if rep is not None and rep.tracker is not None:
                # an arrived-but-never-served request still counts against
                # the replica's request totals (goodput's denominator is
                # finished requests only; shed ones are reported separately)
                rep.tracker.arrive(idx, now=t_start + arr[idx])

        while pending or any(r.assigned or r.active for r in self.replicas):
            now = self._clock()
            did_work = False

            # ---- phase 1: bind arrived requests to the least-loaded
            # replica (preempted requests keep their affinity — their cached
            # prefix blocks live there)
            while pending and now - t_start >= arr[pending[0]]:
                idx = pending.popleft()
                if affinity[idx] is not None:
                    rep = self.replicas[affinity[idx]]
                    self.affine_readmits += 1
                    if registry is not None:
                        c_affine.add(1.0)
                else:
                    rep = self._least_loaded()
                    affinity[idx] = rep.index
                rep.assigned.append(idx)

            # ---- phase 2: per replica, SLO-gated admission + fused prefill
            for rep in self.replicas:
                eng = rep.engine
                adm_uids: List[int] = []
                adm_tokens: List[np.ndarray] = []
                adm_counts: List[int] = []
                adm_full: List[np.ndarray] = []
                decoding = list(rep.active.keys())
                deferred: List[int] = []
                while rep.assigned and len(rep.active) < eng.config.max_seqs:
                    idx = rep.assigned[0]
                    waited = now - (t_start + arr[idx])
                    # the SLO gate applies to FIRST admissions only: a
                    # preempted request was already admitted and holds
                    # generated tokens — dropping it now would violate the
                    # "an admitted request is never dropped" invariant (it
                    # re-admits unconditionally, on its affine replica)
                    decision = ("admit" if idx in admitted_once
                                else self._admission_decision(waited, rep))
                    if decision == "shed":
                        rep.assigned.popleft()
                        shed(idx, rep)
                        continue
                    if decision == "defer":
                        # migrate toward the replica the decision says could
                        # still make the budget — a never-admitted request
                        # has no KV and no cached prefix to lose by rebinding
                        rep.assigned.popleft()
                        best = min(self.replicas,
                                   key=lambda r: self._projected_ttft_s(waited, r))
                        if best is not rep:
                            affinity[idx] = best.index
                            best.assigned.append(idx)
                        else:
                            deferred.append(idx)
                        self.deferred_count += 1
                        if registry is not None:
                            c_defer.add(1.0)
                        continue
                    cand = context(idx)
                    suffix = eng.try_admit(next_uid, cand, decoding + adm_uids,
                                           [1] * len(decoding) + adm_counts)
                    if suffix is None:
                        break
                    rep.assigned.popleft()
                    admitted_once.add(idx)
                    adm_uids.append(next_uid)
                    adm_tokens.append(suffix)
                    adm_counts.append(len(suffix))
                    adm_full.append(cand)
                    if rep.tracker is not None:
                        rep.tracker.arrive(idx, now=t_start + arr[idx])
                        rep.tracker.admit(idx, next_uid)
                        rep.tracker.set_trace_context(
                            idx, self._trace_ctx[idx])
                    rep.active[next_uid] = idx
                    rep.order[next_uid] = None
                    next_uid += 1
                rep.assigned.extend(deferred)
                if adm_uids:
                    did_work = True
                    adm_rids = [rep.active[u] for u in adm_uids]
                    t0 = self._clock()
                    toks, rng = eng._put_sample(
                        adm_uids, adm_tokens, rng, sample_kw,
                        tracker=rep.tracker, rids=adm_rids)
                    t1 = self._clock()
                    rep.ema("prefill_ema", t1 - t0)
                    rep.dispatches += 1
                    replica_span(rep, "prefill", t0, t1)
                    if registry is not None:
                        c_disp[rep.index].add(1.0)
                    if eng.prefix_cache is not None:
                        for u, full in zip(adm_uids, adm_full):
                            eng._insert_prefix(u, full)
                    if rep.tracker is not None:
                        rep.tracker.emitted_batch(adm_rids, (1,) * len(adm_rids))
                    for u, t in zip(adm_uids, toks):
                        accept(rep, u, t)

            # ---- phase 3: per replica, one chained decode over its rows
            for rep in self.replicas:
                if not rep.active:
                    continue
                eng = rep.engine
                did_work = True
                uids = list(rep.active.keys())
                budgets = [max_new_tokens - len(gen[rep.active[u]]) for u in uids]
                k = eng.config.decode_chain
                while True:
                    while k > 1 and not eng._can_schedule_evicting(
                            uids, eng.chain_window(budgets, k)):
                        k -= 1
                    if eng._can_schedule_evicting(uids, eng.chain_window(budgets, k)):
                        break
                    # preempt the youngest row; it re-queues pinned to THIS
                    # replica so its cached prefix blocks stay useful
                    victim = next(reversed(rep.order))
                    del rep.order[victim]
                    i = uids.index(victim)
                    uids.pop(i)
                    budgets.pop(i)
                    idx = rep.active.pop(victim)
                    eng.flush(victim)
                    pending.appendleft(idx)
                    self.preemptions += 1
                    if rep.tracker is not None:
                        rep.tracker.preempt(idx)
                    if registry is not None:
                        c_preempt.add(1.0)
                    if not uids:
                        raise RuntimeError(
                            f"replica {rep.index}: KV pool too small for a "
                            f"single sequence ({eng.num_kv_blocks} blocks)")
                    k = eng.config.decode_chain
                last = [gen[rep.active[u]][-1] for u in uids]
                chain_rids = [rep.active[u] for u in uids]
                t0 = self._clock()
                if spec:
                    histories = [context(rep.active[u]) for u in uids]
                    out, emitted, rng = eng.decode_spec_chain(
                        uids, last, budgets, k, rng, histories,
                        eos_id=eos_token_id, tracker=rep.tracker,
                        rids=chain_rids)
                else:
                    out, emitted, rng = eng.decode_chain(
                        uids, last, budgets, k, rng, eos_id=eos_token_id,
                        sample_kw=sample_kw, tracker=rep.tracker,
                        rids=chain_rids)
                t1 = self._clock()
                rep.ema("chain_ema", t1 - t0)
                rep.dispatches += 1
                replica_span(rep, "chain", t0, t1)
                eng.tokens_decoded += int(emitted.sum())
                if rep.tracker is not None:
                    rep.tracker.emitted_batch(chain_rids, emitted, now=t1)
                    rep.tracker.sample_gauges(now=t1)
                if registry is not None:
                    c_disp[rep.index].add(1.0)
                    g_depth[rep.index].set(float(len(rep.assigned)))
                    g_active[rep.index].set(float(len(rep.active)))
                for i, u in enumerate(uids):
                    for t in out[i, : emitted[i]]:
                        if u in rep.active:
                            accept(rep, u, t)

            if not did_work:
                if pending:
                    wait = t_start + arr[pending[0]] - self._clock()
                    if wait > 0:  # open-loop: idle until the next arrival
                        time.sleep(min(wait, 0.02))
                    continue
                if any(r.assigned for r in self.replicas):
                    if not any(r.active for r in self.replicas):
                        # nothing decoding anywhere, yet the assigned
                        # requests could not be admitted: with the serve()
                        # feasibility guards above this means deferred
                        # requests waiting out their admission gate — let
                        # wall time advance instead of spinning hot (they
                        # admit or shed as `waited` grows)
                        time.sleep(0.001)
                    continue  # active rows elsewhere will free capacity
        for rep in self.replicas:
            if rep.tracker is not None:
                rep.tracker.sample_gauges()
        if registry is not None:
            for rep in self.replicas:
                g_depth[rep.index].set(0.0)
                g_active[rep.index].set(0.0)
        return [outputs.get(i) for i in range(n_req)]

    def dispatch_context(self, idx: int) -> Optional[Dict[str, Any]]:
        """Wire-form trace context for request ``idx`` of the current/most
        recent ``serve()`` — what a REAL process-boundary replica receives
        alongside its dispatch payload. The receiver rebuilds it with
        ``fleet.TraceContext.from_wire`` and wraps its work in
        ``fleet.dispatch_span(ctx)``, which emits the ``serve:dispatch``
        span + in-span flow step that binds into this router's admission
        arrow once ``tools/trace_merge.py`` joins the streams."""
        ctx = self._trace_ctx.get(idx)
        return ctx.to_wire() if ctx is not None else None

    def reset_estimates(self) -> None:
        """Zero the per-replica latency EMAs. Call after a warmup pass: the
        first dispatch of each program carries its XLA compile time, and an
        EMA seeded with compile latency makes the admission gate project
        every cold request over budget (it would shed the whole burst)."""
        for rep in self.replicas:
            rep.prefill_ema = 0.0
            rep.chain_ema = 0.0

    # ------------------------------------------------------------- reporting
    def goodput(self) -> Tuple[int, int]:
        """(slo_met, slo_missed) summed over the replica trackers."""
        met = missed = 0
        for rep in self.replicas:
            t = rep.tracker
            if t is None or not t._emit:
                continue
            met += int(t._c_slo_met.value)
            missed += int(t._c_slo_missed.value)
        return met, missed

    def stats(self) -> Dict[str, Any]:
        return {
            "replicas": len(self.replicas),
            "shed": self.shed_count,
            "deferred": self.deferred_count,
            "preemptions": self.preemptions,
            "affine_readmissions": self.affine_readmits,
            "dispatches": [r.dispatches for r in self.replicas],
        }
